"""Fleet observatory (corda_tpu/loadtest/observatory.py).

Covers: cross-node trace stitching (trace-id join, fan-in link join,
cursor-replay dedupe), the notarised-pair critical-path decomposition,
disruption MTTR + the annotated timeline (detect records, metric
inflections), the FleetCollector's cursor-draining poll loop against a
REAL ops endpoint over a LocalSession (wedged node = counted, not
fatal), the gate direction pins for the new keys, soak_gate's --mttr
ceiling, and the fleet_report renderer.
"""
import json
import subprocess
import sys

import pytest

from corda_tpu.loadtest import observatory as obs
from corda_tpu.loadtest.gate import direction


def _span(node=None, name="rpc.start_flow", trace="t" * 32, span_id="s1",
          start=1.0, dur=5.0, tags=None, links=None):
    d = {"trace_id": trace, "span_id": span_id, "name": name,
         "start": start, "duration_ms": dur, "tags": tags or {}}
    if links:
        d["links"] = links
    return d


# ---------------------------------------------------------------------------
# stitching + critical path
# ---------------------------------------------------------------------------

class TestStitching:
    def test_joins_by_trace_id_across_nodes(self):
        traces = obs.stitch_traces([
            ("bank_a", [_span(span_id="a1")]),
            ("notary", [_span(span_id="n1", name="notary.commit_batch",
                              start=1.004, dur=2.0)]),
        ])
        t = traces["t" * 32]
        assert t["nodes"] == ["bank_a", "notary"]
        assert t["span_count"] == 2
        assert [s["fleet_node"] for s in t["spans"]] == ["bank_a", "notary"]
        assert t["wall_ms"] == pytest.approx(6.0)

    def test_fan_in_span_joins_every_linked_trace(self):
        batch = _span(
            span_id="v1", name="verifier.batch", trace="c" * 32,
            links=[{"trace_id": "a" * 32}, {"trace_id": "b" * 32}],
        )
        traces = obs.stitch_traces([
            ("a", [_span(trace="a" * 32, span_id="a1")]),
            ("b", [_span(trace="b" * 32, span_id="b1")]),
            ("v", [batch]),
        ])
        # the shared batch shows up in BOTH pairs' trees (and its own)
        for tid in ("a" * 32, "b" * 32, "c" * 32):
            names = {s["name"] for s in traces[tid]["spans"]}
            assert "verifier.batch" in names

    def test_cursor_replay_does_not_double_count(self):
        s = _span(span_id="dup")
        traces = obs.stitch_traces([("n", [s, dict(s)])])
        assert traces["t" * 32]["span_count"] == 1

    def test_critical_path_hops_in_pair_order(self):
        tid = "p" * 32
        spans = [
            _span(trace=tid, span_id="1", name="rpc.start_flow",
                  start=1.000, dur=40.0),
            _span(trace=tid, span_id="2", name="flow.CashPaymentFlow",
                  start=1.001, dur=38.0, tags={"responder": False}),
            _span(trace=tid, span_id="3", name="p2p.deliver",
                  start=1.005, dur=1.0),
            _span(trace=tid, span_id="4", name="flow.CashPaymentResponder",
                  start=1.007, dur=20.0, tags={"responder": True}),
            _span(trace=tid, span_id="5", name="verifier.batch",
                  start=1.010, dur=8.0),
            _span(trace=tid, span_id="6", name="notary.commit_batch",
                  start=1.020, dur=5.0),
            # a second, SLOWER p2p hop: the critical path reports it
            _span(trace=tid, span_id="7", name="p2p.deliver",
                  start=1.030, dur=3.0),
        ]
        nodes = ["a", "a", "a", "b", "n", "n", "b"]
        traces = obs.stitch_traces([
            (n, [s]) for n, s in zip(nodes, spans)
        ])
        cp = obs.critical_path(traces[tid])
        assert cp["complete"] is True
        assert [h["hop"] for h in cp["hops"]] == [
            "rpc", "initiator_flow", "p2p", "responder_flow",
            "verifier_batch", "notary_commit",
        ]
        p2p = next(h for h in cp["hops"] if h["hop"] == "p2p")
        assert p2p["duration_ms"] == 3.0 and p2p["node"] == "b"
        resp = next(h for h in cp["hops"] if h["hop"] == "responder_flow")
        assert resp["node"] == "b"

    def test_top_paths_only_notarised_sorted_by_wall(self):
        fast = [_span(trace="f" * 32, span_id="1", dur=2.0),
                _span(trace="f" * 32, span_id="2", name="notary.commit",
                      start=1.001, dur=1.0)]
        slow = [_span(trace="d" * 32, span_id="3", dur=50.0),
                _span(trace="d" * 32, span_id="4", name="notary.commit",
                      start=1.010, dur=30.0)]
        unnotarised = [_span(trace="e" * 32, span_id="5", dur=999.0)]
        traces = obs.stitch_traces(
            [("n", fast + slow + unnotarised)]
        )
        top = obs.top_critical_paths(traces, n=5)
        assert [cp["trace_id"] for cp in top] == ["d" * 32, "f" * 32]
        assert obs.top_critical_paths(traces, n=1)[0]["trace_id"] == "d" * 32


# ---------------------------------------------------------------------------
# MTTR + timeline
# ---------------------------------------------------------------------------

class TestMttrAndTimeline:
    EVENTS = [
        (10.0, "restart", "fired"),
        (13.0, "restart", "recovered+2"),
        (20.0, "hang", "fired"),
        (21.5, "hang", "recovered+1"),
        (30.0, "worker_kill", "skipped: no target visible"),
        (40.0, "restart", "fired"),
        (45.0, "restart", "recovered+3"),
    ]

    def test_mttr_means_per_kind(self):
        mttr = obs.disruption_mttr(self.EVENTS)
        assert mttr == {
            "mttr_ms{kind=hang}": 1500.0,
            "mttr_ms{kind=restart}": 4000.0,  # mean of 3s and 5s
        }

    def test_timeline_annotates_detect_and_inflections(self):
        t0_wall = 1000.0
        node_logs = {
            "bank_a": [
                {"ts": 1011.0, "level": "warning", "component": "rpc",
                 "message": "connection lost", "seq": 1},
                {"ts": 1011.5, "level": "info", "component": "flow",
                 "message": "below the warning floor", "seq": 2},
                {"ts": 1500.0, "level": "error", "component": "rpc",
                 "message": "outside every window", "seq": 3},
            ],
        }
        node_samples = {
            "bank_a": [
                {"seq": 1, "ts": 1009.0,
                 "metrics": {"Pay.Count": {"count": 50, "rate": 10.0}}},
                {"seq": 2, "ts": 1011.0,
                 "metrics": {"Pay.Count": {"count": 51, "rate": 1.0}}},
            ],
        }
        timeline = obs.build_timeline(
            self.EVENTS, t0_wall,
            node_logs=node_logs, node_samples=node_samples,
        )
        first = timeline[0]
        assert first["kind"] == "restart"
        assert first["mttr_ms"] == 3000.0
        # detect: fire at t=10, first warning+ at wall 1011 -> t=11
        assert first["detect_ms"] == 1000.0
        assert [e["message"] for e in first["node_events"]] == [
            "connection lost"
        ]
        assert first["metric_inflections"] == [{
            "node": "bank_a", "metric": "Pay.Count",
            "before_rate": 10.0, "during_min_rate": 1.0,
        }]
        # the skipped mark rides through verbatim
        skipped = next(e for e in timeline if "skipped" in str(e.get("what")))
        assert skipped["kind"] == "worker_kill"
        # windows without correlated data annotate nothing but still
        # carry the ground-truth mttr
        assert timeline[1]["mttr_ms"] == 1500.0
        assert timeline[1]["node_events"] == []

    def test_inflection_floor_ignores_idle_families(self):
        samples = [
            {"ts": 1.0, "metrics": {"Idle": {"rate": 0.1},
                                    "Busy": {"rate": 8.0}}},
            {"ts": 5.0, "metrics": {"Idle": {"rate": 0.0},
                                    "Busy": {"rate": 8.1}}},
        ]
        # Idle sits under the floor; Busy never collapsed
        assert obs.metric_inflections(samples, 4.0, 6.0) == []


# ---------------------------------------------------------------------------
# the collector against a real ops endpoint over a LocalSession
# ---------------------------------------------------------------------------

class TestFleetCollector:
    def test_poll_drains_all_feeds_and_cursors_stick(self):
        from corda_tpu.loadtest.remote import LocalSession, parse_hosts
        from corda_tpu.node.opsserver import OpsServer
        from corda_tpu.utils import tracing
        from corda_tpu.utils.eventlog import EventLog
        from corda_tpu.utils.metrics import MetricRegistry
        from corda_tpu.utils.timeseries import MetricsHistory
        from corda_tpu.utils.tracing import Tracer

        prev = tracing.set_tracer(Tracer())
        registry = MetricRegistry()
        history = MetricsHistory(registry, interval_s=60.0)
        log = EventLog()
        srv = OpsServer(registry, history=history, event_log=log)
        try:
            registry.counter("Fleet.C").inc(5)
            history.sample_once(now=1.0)
            with tracing.get_tracer().span("rpc.start_flow"):
                pass
            log.emit("warning", "fleet", "first record")
            session = LocalSession(parse_hosts("local")[0])
            wedged = obs.NodeProbe(
                "ghost", session, 1, timeout_s=4.0  # port 1: unreachable
            )
            collector = obs.FleetCollector(
                [obs.NodeProbe("alpha", session, srv.port, timeout_s=8.0),
                 wedged],
            )
            ok = collector.poll_once()
            assert ok == {"alpha": True, "ghost": False}
            stats = collector.stats()
            assert stats["spans"] == 1
            assert stats["samples"] == 1
            assert stats["log_records"] == 1
            assert stats["wedged_polls"] == 1
            # second poll: cursors advanced, nothing re-read, new data in
            log.emit("error", "fleet", "second record")
            with tracing.get_tracer().span("notary.commit_batch"):
                pass
            collector.poll_once()
            stats = collector.stats()
            assert stats["spans"] == 2
            assert stats["log_records"] == 2
            logs = collector.node_logs()["alpha"]
            assert [e["message"] for e in logs] == [
                "first record", "second record",
            ]
            traces = collector.stitched()
            assert len(traces) == 2
            capture = collector.capture()
            assert capture["nodes"]["alpha"]["ok"] is True
            assert capture["nodes"]["ghost"]["ok"] is False
            assert capture["traces_stitched"] == 2
            json.dumps(capture)  # the soak record embeds this verbatim
        finally:
            srv.stop()
            tracing.set_tracer(prev)

    def test_callable_ops_port_and_no_port_is_unreachable(self):
        from corda_tpu.loadtest.remote import LocalSession, parse_hosts

        session = LocalSession(parse_hosts("local")[0])
        probe = obs.NodeProbe("n", session, lambda: None)
        assert probe.ops_port is None
        assert probe.fetch({"health": "/healthz"}) is None


# ---------------------------------------------------------------------------
# gate direction pins + the CLIs
# ---------------------------------------------------------------------------

class TestGateAndTools:
    @pytest.mark.parametrize("key,expected", [
        ("mttr_ms{kind=restart}", "lower"),
        ("mttr.mttr_ms{kind=hang}", "lower"),
        ("fleet_observe_overhead_pct", "lower"),
        ("fleet_observe_on_per_sec", "higher"),
        ("fleet_observe_off_per_sec", "higher"),
    ])
    def test_direction_pins(self, key, expected):
        assert direction(key) == expected

    def _record(self, mttr):
        return {
            "pairs": 10, "hard_error_rate": 0.0, "consistent": True,
            "disruptions_fired": 3, "disruptions_recovered": 3,
            "mttr": mttr, "slo_violations": [],
        }

    def _soak_gate(self, record, *extra):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        return subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "soak_gate.py"),
             "--current", "-", *extra],
            input=json.dumps(record), capture_output=True, text=True,
        )

    def test_soak_gate_mttr_breach_fails(self):
        record = self._record({"mttr_ms{kind=restart}": 3000.0,
                               "mttr_ms{kind=hang}": 90000.0})
        proc = self._soak_gate(record, "--mttr", "60000")
        assert proc.returncode == 1
        verdict = json.loads(proc.stdout)
        assert any(
            v["key"] == "mttr.mttr_ms{kind=hang}" and v["kind"] == "max"
            for v in verdict["violations"]
        )
        # under the ceiling: passes
        assert self._soak_gate(record, "--mttr", "120000").returncode == 0

    def test_soak_gate_missing_mttr_on_disrupted_run_breaches(self):
        proc = self._soak_gate(self._record({}), "--mttr", "60000")
        assert proc.returncode == 1
        verdict = json.loads(proc.stdout)
        assert any(
            v["key"] == "mttr" and v["kind"] == "missing"
            for v in verdict["violations"]
        )
        # without --mttr the same record still passes (opt-in ceiling)
        assert self._soak_gate(self._record({})).returncode == 0

    def test_fleet_report_renders_all_sections(self, tmp_path):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        record = {
            "fleet": {
                "nodes": {"bank_a": {"ok": True, "health": "ok",
                                     "wedged_polls": 0, "spans": 12,
                                     "log_records": 3, "samples": 9}},
                "polls": 4, "wedged_polls": 0, "traces_stitched": 2,
                "cross_node_traces": 1,
                "critical_paths": [{
                    "trace_id": "a" * 32, "wall_ms": 42.0,
                    "nodes": ["bank_a", "notary"], "complete": True,
                    "hops": [{"hop": "rpc", "name": "rpc.start_flow",
                              "node": "bank_a", "t_offset_ms": 0.0,
                              "duration_ms": 40.0}],
                }],
            },
            "timeline": [{"kind": "restart", "what": "recovered+2",
                          "fired_t": 10.0, "recovered_t": 13.0,
                          "mttr_ms": 3000.0, "detect_ms": 1000.0,
                          "node_events": [{"node": "bank_a", "t": 11.0,
                                           "level": "warning",
                                           "component": "rpc",
                                           "message": "connection lost"}],
                          "metric_inflections": []}],
            "mttr": {"mttr_ms{kind=restart}": 3000.0},
        }
        path = tmp_path / "soak.json"
        path.write_text(json.dumps(record))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "fleet_report.py"),
             "--current", str(path)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0
        out = proc.stdout
        assert "== fleet ==" in out and "bank_a" in out
        assert "mttr=3000.0ms" in out and "detect=1000.0ms" in out
        assert "connection lost" in out
        assert "rpc.start_flow on bank_a" in out
        # an empty record renders placeholders, exit 0 (report != gate)
        empty = tmp_path / "empty.json"
        empty.write_text("{}")
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "fleet_report.py"),
             "--current", str(empty)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0
        assert "(no fleet capture in record)" in proc.stdout

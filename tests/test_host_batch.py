"""Differential tests for the native batched ed25519 verifier
(native/src/ed25519_msm.cpp + core/crypto/host_batch.py) against the
host OpenSSL oracle. The batch path must agree with `crypto.is_valid`
bit-for-bit on every reject class, and accept every honestly-generated
signature."""
import numpy as np
import pytest

from corda_tpu import native
from corda_tpu.core.crypto import crypto, ed25519_math as em, host_batch
from corda_tpu.core.crypto import batch as crypto_batch
from corda_tpu.core.crypto.keys import SchemePublicKey
from corda_tpu.core.crypto.schemes import EDDSA_ED25519_SHA512

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)

ED = EDDSA_ED25519_SHA512.scheme_code_name


def _rows(n, n_keys=8, seed=3):
    rng = np.random.default_rng(seed)
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs = [em.public_from_seed(s) for s in seeds]
    rows = []
    for i in range(n):
        k = i % n_keys
        m = rng.bytes(40)
        rows.append((pubs[k], em.sign(seeds[k], m), m))
    return rows


def _oracle(rows):
    return [
        crypto.is_valid(SchemePublicKey(ED, bytes(p)), bytes(s), bytes(m))
        for p, s, m in rows
    ]


def test_point_roundtrip_matches_encoding():
    rng = np.random.default_rng(1)
    for _ in range(8):
        pub = em.public_from_seed(rng.bytes(32))
        rt = native.ed25519_point_roundtrip(pub)
        assert rt is not None
        x = int.from_bytes(rt[0], "little")
        y = int.from_bytes(rt[1], "little")
        assert y == int.from_bytes(pub, "little") & (2**255 - 1)
        assert (x & 1) == (pub[31] >> 7)
        # on-curve: -x^2 + y^2 = 1 + d x^2 y^2 (mod p)
        p = 2**255 - 19
        d = (-121665 * pow(121666, p - 2, p)) % p
        assert (-x * x + y * y) % p == (1 + d * x * x * y * y) % p


def test_off_curve_encoding_rejected():
    """Decompression must reject exactly the y values whose x^2 candidate
    (y^2-1)/(dy^2+1) is a non-residue — checked against a pure-Python
    Legendre-symbol oracle for a spread of y values."""
    p = 2**255 - 19
    d = (-121665 * pow(121666, p - 2, p)) % p
    for y in (2, 3, 5, 7, 1000, 2**200 + 7):
        u = (y * y - 1) % p
        v = (d * y * y + 1) % p
        x2 = u * pow(v, p - 2, p) % p
        on_curve = x2 == 0 or pow(x2, (p - 1) // 2, p) == 1
        got = native.ed25519_point_roundtrip(y.to_bytes(32, "little"))
        assert (got is not None) == on_curve, f"y={y}"
        if got is not None:
            x = int.from_bytes(got[0], "little")
            assert (x * x) % p == x2


def test_all_valid_batch_accepts():
    rows = _rows(300)
    assert host_batch.verify_batch_host(rows) == [True] * 300


def test_reject_classes_match_openssl_oracle():
    rows = _rows(128)
    L = host_batch.L
    # tamper a spread of reject classes
    p0, s0, m0 = rows[0]
    rows[0] = (p0, s0, m0 + b"!")                       # wrong message
    p1, s1, m1 = rows[1]
    rows[1] = (p1, s1[:32] + b"\x01" + s1[33:], m1)      # corrupt s
    p2, s2, m2 = rows[2]
    rows[2] = (p2, b"\x00" * 64, m2)                     # zero signature
    p3, s3, m3 = rows[3]
    rows[3] = (p3, s3[:32] + L.to_bytes(32, "little"), m3)  # s >= L
    p4, s4, m4 = rows[4]
    rows[4] = (b"\x00" * 31 + b"\x80", s4, m4)           # non-canonical-ish A
    p5, s5, m5 = rows[5]
    rows[5] = (p5, s5[:31], m5)                          # truncated sig
    out = host_batch.verify_batch_host(rows)
    assert out == _oracle(rows)
    assert out[:6] == [False] * 6
    assert all(out[6:])


def test_every_position_detected_alone():
    """Binary-search fallback keeps exact positional semantics for a
    single bad row at assorted positions."""
    for bad_pos in (0, 63, 64, 127):
        rows = _rows(128, seed=bad_pos + 10)
        p, s, m = rows[bad_pos]
        rows[bad_pos] = (p, s, m + b"x")
        out = host_batch.verify_batch_host(rows)
        assert out == [i != bad_pos for i in range(128)]


def test_distinct_keys_no_aggregation_path():
    rng = np.random.default_rng(9)
    rows = []
    for i in range(96):
        s = rng.bytes(32)
        m = rng.bytes(32)
        rows.append((em.public_from_seed(s), em.sign(s, m), m))
    p, s, m = rows[40]
    rows[40] = (p, s, m + b"!")
    out = host_batch.verify_batch_host(rows)
    assert out == [i != 40 for i in range(96)]


def test_dispatch_routes_large_cpu_ed25519_bucket_to_msm(monkeypatch):
    calls = {}
    real = host_batch.verify_batch_host

    def spy(rows):
        calls["n"] = len(rows)
        return real(rows)

    monkeypatch.setattr(host_batch, "verify_batch_host", spy)
    monkeypatch.setattr(crypto_batch, "DISPATCH", "auto")
    monkeypatch.setattr(crypto_batch, "_resolved_backend", "cpu")
    rows = _rows(80)
    items = [(SchemePublicKey(ED, p), s, m) for p, s, m in rows]
    items[7] = (items[7][0], items[7][1], items[7][2] + b"!")
    out = crypto_batch.verify_batch(items)
    assert out == [i != 7 for i in range(80)]
    assert calls.get("n") == 80


def test_host_batch_disable_env_falls_back(monkeypatch):
    monkeypatch.setenv("CORDA_TPU_HOST_BATCH", "0")
    assert not host_batch.available()
    monkeypatch.setattr(crypto_batch, "DISPATCH", "host")
    rows = _rows(70)
    items = [(SchemePublicKey(ED, p), s, m) for p, s, m in rows]
    assert crypto_batch.verify_batch(items) == [True] * 70


def test_verdicts_independent_of_batch_composition():
    """The SAME signature must get the SAME verdict whether its batch
    passes wholesale or gets binary-searched because an unrelated row is
    bad (review finding: a cofactorless leaf rule made verdicts depend
    on batch composition)."""
    rows = _rows(96, seed=21)
    clean = host_batch.verify_batch_host(rows)
    p, s, m = rows[0]
    dirty_rows = [(p, s, m + b"!")] + rows[1:]
    dirty = host_batch.verify_batch_host(dirty_rows)
    assert clean == [True] * 96
    assert dirty == [False] + clean[1:]


def test_small_buckets_use_the_same_rule(monkeypatch):
    """The cofactored rule applies to EVERY bucket size on the CPU path
    (review finding: a rule flipping at a size threshold lets an
    adversarial torsion signature split replicas whose batchers grouped
    it differently)."""
    calls = {"n": 0}
    real = host_batch.verify_batch_host

    def spy(rows):
        calls["n"] += len(rows)
        return real(rows)

    monkeypatch.setattr(host_batch, "verify_batch_host", spy)
    monkeypatch.setattr(crypto_batch, "DISPATCH", "auto")
    monkeypatch.setattr(crypto_batch, "_resolved_backend", "cpu")
    rows = _rows(2)
    items = [(SchemePublicKey(ED, p), s, m) for p, s, m in rows]
    assert crypto_batch.verify_batch(items) == [True, True]
    assert calls["n"] == 2


def test_msm_rejects_unreduced_scalar_with_error_code():
    """An oversized scalar (>= 2^253) must return the -2 caller-bug code,
    never silently truncate into a wrong verdict."""
    rows = _rows(4, seed=33)
    pts = b"".join(bytes(s[:32]) for _, s, _ in rows)
    bad_scalar = (2**255 + 5).to_bytes(32, "little")
    scalars = bad_scalar + b"\x01".ljust(32, b"\x00") * 3
    assert native.ed25519_msm_is_small(pts, scalars, 4) == -2


def test_tiny_batches_all_sizes_differential():
    """The Straus/comb small-batch path (n <= 16) must agree with the
    oracle for every size and every tamper position across the
    Pippenger crossover."""
    rng = np.random.default_rng(55)
    seeds = [rng.bytes(32) for _ in range(4)]
    pubs = [em.public_from_seed(s) for s in seeds]
    for n in (1, 2, 3, 15, 16, 17):
        rows = []
        for i in range(n):
            k = i % 4
            m = rng.bytes(40)
            rows.append((pubs[k], em.sign(seeds[k], m), m))
        assert host_batch.verify_batch_host(rows) == [True] * n, n
        bad = n // 2
        p, s, m = rows[bad]
        rows[bad] = (p, s, m + b"!")
        out = host_batch.verify_batch_host(rows)
        assert out == [i != bad for i in range(n)], (n, bad)


def test_fuzz_differential_random_mutations():
    """500 random single-bit/byte mutations across pub/sig/msg, verified
    batch-wise against the per-signature OpenSSL oracle. Random
    corruption never produces the crafted torsion signatures where the
    cofactored rule legitimately diverges, so exact agreement is required
    (deterministic seed)."""
    rng = np.random.default_rng(2026)
    seeds = [rng.bytes(32) for _ in range(6)]
    pubs = [em.public_from_seed(s) for s in seeds]
    rows = []
    for i in range(125):
        k = i % 6
        m = rng.bytes(56)
        rows.append([pubs[k], em.sign(seeds[k], m), m])
    for _ in range(4):  # 4 passes x 125 rows = 500 mutations
        mutated = []
        for pub, sig, m in rows:
            field = rng.integers(0, 3)
            blob = bytearray((pub, sig, m)[field])
            blob[rng.integers(0, len(blob))] ^= 1 << rng.integers(0, 8)
            row = [pub, sig, m]
            row[field] = bytes(blob)
            mutated.append(tuple(row))
        got = host_batch.verify_batch_host(mutated)
        want = _oracle(mutated)
        assert got == want, [
            (i, g, w) for i, (g, w) in enumerate(zip(got, want)) if g != w
        ]


def _keyed_rows(n, n_keys, rng):
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs = [em.public_from_seed(s) for s in seeds]
    out = []
    for i in range(n):
        m = rng.bytes(40)
        out.append((pubs[i % n_keys], em.sign(seeds[i % n_keys], m), m))
    return out


def test_decompressed_key_cache_verdicts_identical():
    """r4 VERDICT weak #3: the per-key affine cache must change only the
    speed, never a verdict — warm passes (cache hits) must reproduce
    cold verdicts including exact tamper positions."""
    rng = np.random.default_rng(11)
    rows = _keyed_rows(64, 64, rng)  # all-distinct keys
    host_batch._A_CACHE.clear()
    cold = host_batch.verify_batch_host(rows)
    assert cold == [True] * 64
    assert len(host_batch._A_CACHE) == 64  # every key cached
    # tamper two rows and re-verify with a WARM cache
    bad = list(rows)
    bad[5] = (bad[5][0], bad[5][1], b"tampered")
    bad[41] = (bad[41][0], b"\x01" * 64, bad[41][2])
    warm = host_batch.verify_batch_host(bad)
    assert warm == [i not in (5, 41) for i in range(64)]


def test_off_curve_key_with_cache_still_rejected():
    """A pubkey encoding not on the curve never enters the cache and its
    rows still fail cleanly through the compressed fallback path."""
    rng = np.random.default_rng(12)
    rows = _keyed_rows(8, 8, rng)
    # y = 2 is not on the curve (x^2 = (y^2-1)/(dy^2+1) is non-square)
    off = (2).to_bytes(32, "little")
    assert native.ed25519_decompress_many([off]) == [None]
    rows.append((off, rows[0][1], rows[0][2]))
    host_batch._A_CACHE.clear()
    out = host_batch.verify_batch_host(rows)
    assert out == [True] * 8 + [False]
    assert off not in host_batch._A_CACHE


def test_key_cache_is_bounded(monkeypatch):
    monkeypatch.setattr(host_batch, "_A_CACHE_MAX", 16)
    host_batch._A_CACHE.clear()
    rng = np.random.default_rng(13)
    for _ in range(3):
        rows = _keyed_rows(24, 24, rng)
        assert host_batch.verify_batch_host(rows) == [True] * 24
        assert len(host_batch._A_CACHE) <= 16
    host_batch._A_CACHE.clear()


def test_native_msm_prep_matches_python_bigints():
    """The native z*h / z*s mulmod accumulation must agree with the
    Python bigint reference on every output word."""
    rng = np.random.default_rng(14)
    n, n_groups = 37, 9
    L = host_batch.L
    sigs = rng.bytes(64 * n)
    # s halves must be < L: clamp top byte
    sigs = bytearray(sigs)
    for i in range(n):
        sigs[64 * i + 63] &= 0x0F
    sigs = bytes(sigs)
    h_words = bytearray(rng.bytes(32 * n))
    for i in range(n):
        h_words[32 * i + 31] &= 0x0F  # h < 2^252 <= L
    h_words = bytes(h_words)
    z = rng.bytes(16 * n)
    groups = [int(rng.integers(0, n_groups)) for _ in range(n)]
    gbuf = b"".join(g.to_bytes(4, "little") for g in groups)
    z_out, key_accum, b_out = native.ed25519_msm_prep(
        sigs, h_words, z, gbuf, n, n_groups
    )
    # Python reference
    ref_acc = [0] * n_groups
    ref_b = 0
    for i in range(n):
        zi = int.from_bytes(z[16 * i:16 * i + 16], "little") | 1
        assert int.from_bytes(z_out[32 * i:32 * i + 32], "little") == zi
        h = int.from_bytes(h_words[32 * i:32 * i + 32], "little")
        s = int.from_bytes(sigs[64 * i + 32:64 * i + 64], "little")
        ref_acc[groups[i]] = (ref_acc[groups[i]] + zi * h) % L
        ref_b = (ref_b + zi * s) % L
    for g in range(n_groups):
        got = int.from_bytes(key_accum[32 * g:32 * g + 32], "little")
        assert got == ref_acc[g], f"group {g}"
    assert int.from_bytes(b_out, "little") == ref_b

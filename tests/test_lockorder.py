"""Runtime lock-order deadlock detector (corda_tpu/utils/lockorder.py).

The tier-1 concurrency deliverable of the analysis suite: a synthetic
ABBA acquisition must be reported as a cycle carrying BOTH acquisition
stacks, the hold-time watchdog must fire, Condition waits must not hold
their edges open — and a representative MockNetwork notarise plus a
sharded cross-shard commit must run under the armed detector with ZERO
cycles (docs/static-analysis.md).
"""
import threading
import time

import pytest

from corda_tpu.utils import lockorder


@pytest.fixture
def armed():
    lockorder.enable(True)
    lockorder.reset()
    yield
    lockorder.enable(None)
    lockorder.reset()


def _run(fn, name):
    t = threading.Thread(target=fn, name=name, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


class TestCycleDetection:
    def test_abba_reported_with_both_stacks(self, armed):
        a = lockorder.make_lock("A")
        b = lockorder.make_lock("B")

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        _run(t1, "abba-1")
        _run(t2, "abba-2")
        cycles = lockorder.cycles()
        assert len(cycles) == 1
        report = cycles[0]
        assert sorted(report["locks"]) == ["A", "B"]
        assert report["closing_thread"] == "abba-2"
        # BOTH acquisition stacks on every edge of the cycle, resolving
        # to this test's frames
        assert len(report["edges"]) == 2
        for edge in report["edges"]:
            assert edge["held_stack"], edge
            assert edge["acquire_stack"], edge
            assert any("test_lockorder" in fr for fr in edge["acquire_stack"])
        threads = {e["thread"] for e in report["edges"]}
        assert threads == {"abba-1", "abba-2"}

    def test_cycle_reported_once(self, armed):
        a = lockorder.make_lock("A")
        b = lockorder.make_lock("B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for _ in range(3):
            _run(ab, "w1")
            _run(ba, "w2")
        assert len(lockorder.cycles()) == 1

    def test_three_lock_ring(self, armed):
        locks = [lockorder.make_lock(n) for n in "XYZ"]

        def grab(i, j):
            with locks[i]:
                with locks[j]:
                    pass

        _run(lambda: grab(0, 1), "r1")
        _run(lambda: grab(1, 2), "r2")
        _run(lambda: grab(2, 0), "r3")
        cycles = lockorder.cycles()
        assert len(cycles) == 1
        assert sorted(cycles[0]["locks"]) == ["X", "Y", "Z"]

    def test_consistent_order_no_cycle(self, armed):
        a = lockorder.make_lock("A")
        b = lockorder.make_lock("B")

        def ab():
            with a:
                with b:
                    pass

        for name in ("c1", "c2"):
            _run(ab, name)
        assert lockorder.cycles() == []

    def test_rlock_reentry_no_self_cycle(self, armed):
        r = lockorder.make_rlock("R")
        a = lockorder.make_lock("A")

        def t():
            with r:
                with r:
                    with a:
                        pass

        _run(t, "re")
        assert lockorder.cycles() == []
        assert lockorder.held_now() == []

    def test_self_deadlock_reported_before_blocking(self, armed):
        """A same-thread blocking re-acquire of a plain Lock is the
        simplest deadlock there is — the detector must leave evidence
        BEFORE the thread hangs."""
        lk = lockorder.make_lock("SelfDead")
        with lk:
            # timeout keeps the test alive; blocking=True still takes
            # the reporting path
            assert not lk.acquire(True, 0.05)
        reports = lockorder.reports("self_deadlock")
        assert len(reports) == 1
        r = reports[0]
        assert r["lock"] == "SelfDead"
        assert r["held_stack"] and r["acquire_stack"]
        # rlocks are reentrant: no such report
        rl = lockorder.make_rlock("FineReentry")
        with rl:
            with rl:
                pass
        assert len(lockorder.reports("self_deadlock")) == 1

    def test_cv_wait_restores_reentrant_count(self, armed):
        """Condition._release_save drops EVERY RLock recursion level;
        the held-stack must restore the full count on wakeup, or the
        lock silently stops contributing ordering edges."""
        cv = lockorder.make_condition(name="ReCv")
        lockw = cv._lockw
        other = lockorder.make_lock("ReOther")
        observed = []

        def waiter():
            with lockw:
                with lockw:  # recursion depth 2
                    with cv:  # depth 3, same lock
                        cv.wait(timeout=5)
                        observed.append(list(lockorder.held_now()))
                    # edges from this lock must still record
                    with other:
                        pass
                observed.append(list(lockorder.held_now()))
            observed.append(list(lockorder.held_now()))

        t = threading.Thread(target=waiter, name="recv", daemon=True)
        t.start()
        time.sleep(0.1)
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        # after wakeup the entry is back; releases unwind it exactly
        assert observed[0] == ["ReCv.lock"]
        assert observed[1] == ["ReCv.lock"]
        assert observed[2] == []
        assert ("ReCv.lock", "ReOther") in \
            lockorder.graph_snapshot()["edges"]

    def test_failed_nonblocking_acquire_keeps_stack_clean(self, armed):
        a = lockorder.make_lock("A")
        assert a.acquire(False)
        assert not a.acquire(False)  # same-thread retry fails on a Lock
        a.release()
        assert lockorder.held_now() == []


class TestConditionAndHold:
    def test_condition_wait_releases_bookkeeping(self, armed):
        lock = lockorder.make_lock("CvLock")
        cv = lockorder.make_condition(lock, name="Cv")
        other = lockorder.make_lock("Other")
        entered = threading.Event()

        def waiter():
            with cv:
                entered.set()
                cv.wait(timeout=5)
                # woken: lock re-acquired, bookkeeping restored
                assert lockorder.held_now() == ["CvLock"]

        t = threading.Thread(target=waiter, name="cv-wait", daemon=True)
        t.start()
        assert entered.wait(timeout=5)
        time.sleep(0.05)
        # while the waiter is parked it does NOT hold CvLock: taking
        # CvLock then Other from here must not build a cycle with
        # anything the waiter holds
        with cv:
            with other:
                cv.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        assert lockorder.cycles() == []

    def test_wait_for_predicate(self, armed):
        cv = lockorder.make_condition(name="WF")
        done = []

        def waiter():
            with cv:
                assert cv.wait_for(lambda: done, timeout=5)

        t = threading.Thread(target=waiter, name="wf", daemon=True)
        t.start()
        time.sleep(0.05)
        with cv:
            done.append(1)
            cv.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()

    def test_hold_time_watchdog(self, armed, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_LOCKCHECK_HOLD_MS", "10")
        h = lockorder.make_lock("Slow")
        with h:
            time.sleep(0.05)
        reports = lockorder.reports("hold")
        assert len(reports) == 1
        r = reports[0]
        assert r["lock"] == "Slow"
        assert r["held_ms"] >= 10
        assert any("test_lockorder" in fr for fr in r["acquire_stack"])
        # once per lock: a second slow hold does not duplicate
        with h:
            time.sleep(0.05)
        assert len(lockorder.reports("hold")) == 1


class TestPlumbing:
    def test_disabled_returns_plain_primitives(self):
        lockorder.enable(False)
        try:
            assert isinstance(lockorder.make_lock("x"),
                              type(threading.Lock()))
            rl = lockorder.make_rlock("y")
            assert not isinstance(rl, lockorder._InstrumentedLock)
            cv = lockorder.make_condition(name="z")
            assert isinstance(cv, threading.Condition)
        finally:
            lockorder.enable(None)

    def test_env_knob_arms(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_LOCKCHECK", "1")
        lockorder.enable(None)
        assert lockorder.enabled()
        lk = lockorder.make_lock("armed-by-env")
        assert isinstance(lk, lockorder._InstrumentedLock)
        monkeypatch.setenv("CORDA_TPU_LOCKCHECK", "0")
        assert not lockorder.enabled()

    def test_meta_and_graph_snapshot(self, armed):
        a = lockorder.make_lock("MA")
        b = lockorder.make_lock("MB")
        with a:
            with b:
                pass
        snap = lockorder.graph_snapshot()
        assert ("MA", "MB") in snap["edges"]
        meta = lockorder.meta()
        assert meta["enabled"] and meta["nodes"] >= 2
        assert meta["dropped"] == {"nodes": 0, "edges": 0, "reports": 0}

    def test_instrumented_lock_backs_condition_protocol(self, armed):
        # an RLock wrapper passed raw to threading.Condition still works
        rl = lockorder.make_rlock("CondBack")
        cv = threading.Condition(rl)
        with cv:
            assert not cv.wait(timeout=0.01)

    def test_node_eviction_cap(self, armed, monkeypatch):
        monkeypatch.setattr(lockorder, "MAX_NODES", 4)
        locks = [lockorder.make_lock(f"cap{i}") for i in range(8)]
        # capped locks stay functional, just unrecorded
        for lk in locks:
            with lk:
                pass
        assert lockorder.meta()["dropped"]["nodes"] > 0


class TestScenario:
    """The tier-1 acceptance scenario: MockNetwork notarise + sharded
    cross-shard commit under CORDA_TPU_LOCKCHECK semantics — zero
    cycles."""

    def test_mocknetwork_notarise_and_sharded_commit_no_cycles(self, armed):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.finance.flows import CashIssueFlow, CashPaymentFlow
        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        try:
            notary = net.create_notary_node(shards=4)
            bank = net.create_node("O=LockBank,L=London,C=GB")
            for i in range(3):
                h = bank.start_flow(CashIssueFlow(
                    Amount(100, "USD"), bytes([i + 1]), bank.info,
                    notary.info,
                ))
                net.run_network()
                h.result.result(timeout=10)
                token = Issued(bank.info.ref(i + 1), "USD")
                h2 = bank.start_flow(CashPaymentFlow(
                    Amount(100, token), bank.info, notary.info
                ))
                net.run_network()
                h2.result.result(timeout=10)
            # instrumented locks really were exercised: the node stack
            # built its locks through the factory while armed
            assert lockorder.meta()["nodes"] > 10
            assert lockorder.meta()["edges"] > 0
            assert lockorder.cycles() == [], lockorder.cycles()
        finally:
            net.stop_nodes()

    def test_pipelined_flush_no_cycles(self, armed):
        """The overlapped verification pipeline (docs/perf-pipeline.md)
        under the armed detector: a batcher flush drains through the
        staged engine — four stage threads, the ring condition, the
        batcher lock, the metric locks — with ZERO ordering cycles, and
        the engine's own locks were really instrumented (built through
        the lockorder factories while armed)."""
        from corda_tpu.core.crypto import crypto
        from corda_tpu.verifier.batcher import SignatureBatcher

        items = []
        for i in range(12):
            kp = crypto.entropy_to_keypair(7100 + i)
            content = b"lockcheck-pipe-%d" % i
            items.append(
                (kp.public, crypto.do_sign(kp.private, content), content)
            )
        b = SignatureBatcher(max_batch=4, linger_ms=10_000, pipeline=True)
        try:
            futures = []
            for k in range(3):  # 3 max_batch handoffs -> 3 ring batches
                futures += b.submit_many(items[4 * k:4 * (k + 1)])
            b.flush()
            assert all(f.result(timeout=10) for f in futures)
            assert b.flushes == 3
        finally:
            b.close()
        # the engine's locks were really instrumented while armed, and
        # the pipelined flush produced zero ordering cycles
        assert lockorder.meta()["nodes"] > 0
        assert lockorder.cycles() == [], lockorder.cycles()

    def test_cross_shard_commit_under_detector(self, armed):
        import hashlib

        from corda_tpu.core.contracts.structures import StateRef
        from corda_tpu.core.crypto.secure_hash import SecureHash
        from corda_tpu.node.database import NodeDatabase
        from corda_tpu.node.notary import PersistentUniquenessProvider
        from corda_tpu.node.sharded_notary import (
            ShardedUniquenessProvider,
            shard_of_key,
        )

        provider = ShardedUniquenessProvider(
            [PersistentUniquenessProvider(NodeDatabase(":memory:"))
             for _ in range(4)],
        )

        def ref_on(shard, tag):
            for nonce in range(100_000):
                h = hashlib.sha256(
                    f"lc-{tag}-{shard}-{nonce}".encode()
                ).digest()
                ref = StateRef(SecureHash(h), 0)
                if shard_of_key(h + (0).to_bytes(4, "big"), 4) == shard:
                    return ref
            raise AssertionError("no nonce")

        class _Party:
            name = "O=LockCheck,L=London,C=GB"

        # cross-shard: refs on three different shards in one commit,
        # driven from two threads to exercise the per-shard lock order
        refs_a = [ref_on(0, "a"), ref_on(1, "a"), ref_on(2, "a")]
        refs_b = [ref_on(1, "b"), ref_on(2, "b"), ref_on(3, "b")]
        tx_a = SecureHash(hashlib.sha256(b"lock-a").digest())
        tx_b = SecureHash(hashlib.sha256(b"lock-b").digest())
        errs = []

        def commit(refs, txid):
            try:
                provider.commit(refs, txid, _Party())
            except Exception as exc:  # pragma: no cover - surfaced below
                errs.append(exc)

        t1 = threading.Thread(target=commit, args=(refs_a, tx_a),
                              name="xshard-1", daemon=True)
        t2 = threading.Thread(target=commit, args=(refs_b, tx_b),
                              name="xshard-2", daemon=True)
        t1.start(), t2.start()
        t1.join(timeout=30), t2.join(timeout=30)
        assert not errs, errs
        assert provider.stats()["cross_commits"] >= 2
        assert lockorder.cycles() == [], lockorder.cycles()

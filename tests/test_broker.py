"""Broker semantics: competing consumers, ack/redelivery, durable journal.

Mirrors the behavior the reference proves for Artemis verifier queues
(`verifier/src/integration-test/.../VerifierTests.kt:54-101`).
"""
import struct
import threading

import pytest

from corda_tpu.messaging import (
    Broker, BrokerError, Message, UnknownQueueError,
)


def test_send_receive_ack():
    b = Broker()
    b.create_queue("q")
    mid = b.send("q", b"hello", {"k": "v"})
    c = b.create_consumer("q")
    msg = c.receive(timeout=1)
    assert msg is not None
    assert msg.payload == b"hello"
    assert msg.headers == {"k": "v"}
    assert msg.message_id == mid
    assert msg.delivery_count == 1
    c.ack(msg)
    with pytest.raises(BrokerError):
        c.ack(msg)


def test_send_to_unknown_queue_raises():
    b = Broker()
    with pytest.raises(UnknownQueueError):
        b.send("nope", b"x")


def test_competing_consumers_each_message_delivered_once():
    b = Broker()
    b.create_queue("q")
    for i in range(20):
        b.send("q", bytes([i]))
    c1, c2 = b.create_consumer("q"), b.create_consumer("q")
    got = []
    for c in (c1, c2) * 10:
        m = c.receive(timeout=0.1)
        if m:
            got.append(m.payload[0])
            c.ack(m)
    assert sorted(got) == list(range(20))


def test_consumer_death_redelivers_unacked():
    b = Broker()
    b.create_queue("q")
    b.send("q", b"a")
    b.send("q", b"b")
    c1 = b.create_consumer("q")
    m1 = c1.receive(timeout=1)
    assert m1.payload == b"a"
    c1.close()  # dies without acking -> "a" back at the front
    c2 = b.create_consumer("q")
    m = c2.receive(timeout=1)
    assert m.payload == b"a"
    assert m.delivery_count == 2
    c2.ack(m)
    m = c2.receive(timeout=1)
    assert m.payload == b"b"


def test_receive_blocks_until_send():
    b = Broker()
    b.create_queue("q")
    c = b.create_consumer("q")
    out = []
    t = threading.Thread(target=lambda: out.append(c.receive(timeout=5)))
    t.start()
    b.send("q", b"late")
    t.join(timeout=5)
    assert out and out[0].payload == b"late"


def test_durable_journal_recovery(tmp_path):
    d = str(tmp_path / "journal")
    b = Broker(journal_dir=d)
    b.create_queue("dq", durable=True)
    b.send("dq", b"one", {"h": "1"})
    b.send("dq", b"two")
    c = b.create_consumer("dq")
    m = c.receive(timeout=1)
    c.ack(m)  # "one" acked; "two" pending
    b.close()

    b2 = Broker(journal_dir=d)  # restart
    assert b2.queue_exists("dq")
    c2 = b2.create_consumer("dq")
    m = c2.receive(timeout=1)
    assert m.payload == b"two"
    assert m.delivery_count == 2  # marked as redelivery
    assert c2.receive(timeout=0.05) is None


def test_journal_torn_tail_truncated(tmp_path):
    d = str(tmp_path / "journal")
    b = Broker(journal_dir=d)
    b.create_queue("dq", durable=True)
    b.send("dq", b"good")
    b.close()
    path = str(tmp_path / "journal" / "dq.journal")
    with open(path, "ab") as fh:  # simulate crash mid-append
        fh.write(struct.pack(">BI", 1, 9999) + b"partial")
    b2 = Broker(journal_dir=d)
    c = b2.create_consumer("dq")
    m = c.receive(timeout=1)
    assert m.payload == b"good"
    assert c.receive(timeout=0.05) is None


def test_delete_queue():
    b = Broker()
    b.create_queue("q")
    b.send("q", b"x")
    b.delete_queue("q")
    assert not b.queue_exists("q")
    with pytest.raises(UnknownQueueError):
        b.send("q", b"y")


def test_counts():
    b = Broker()
    b.create_queue("q")
    assert b.consumer_count("q") == 0
    assert b.message_count("q") == 0
    b.send("q", b"x")
    c = b.create_consumer("q")
    assert b.consumer_count("q") == 1
    assert b.message_count("q") == 1
    m = c.receive(timeout=1)
    assert b.message_count("q") == 0
    c.close()
    # unacked message went back on close
    assert b.message_count("q") == 1


class TestJournalCompaction:
    def test_online_compaction_bounds_journal(self, tmp_path, monkeypatch):
        """A busy durable queue must not grow its journal without bound:
        after the ack threshold the journal rewrites to the pending set
        (reference: Artemis journal compaction)."""
        import os

        from corda_tpu.messaging.broker import Broker, _Journal

        monkeypatch.setattr(_Journal, "COMPACT_ACK_THRESHOLD", 50)
        broker = Broker(journal_dir=str(tmp_path))
        broker.create_queue("busy", durable=True)
        consumer = broker.create_consumer("busy")
        for round_no in range(4):
            for i in range(60):
                broker.send("busy", f"m{round_no}-{i}".encode())
            for _ in range(60):
                msg = consumer.receive(timeout=1)
                consumer.ack(msg)
        path = broker._journal_path("busy")
        size_after = os.path.getsize(path)
        # the last compaction rewrote the journal down to the <=10 then-
        # pending messages + tail acks; an append-only log would hold all
        # 240 enqueue+ack records (tens of kB)
        assert size_after < 10_000, size_after
        # an unacked message written after compaction still survives restart
        broker.send("busy", b"survivor")
        broker.close()
        broker2 = Broker(journal_dir=str(tmp_path))
        c2 = broker2.create_consumer("busy")
        survivor = c2.receive(timeout=1)
        assert survivor is not None and survivor.payload == b"survivor"
        broker2.close()

    def test_compaction_preserves_in_flight(self, tmp_path, monkeypatch):
        """Messages delivered but not yet acked must survive a compaction
        triggered by OTHER messages' acks."""
        from corda_tpu.messaging.broker import Broker, _Journal

        monkeypatch.setattr(_Journal, "COMPACT_ACK_THRESHOLD", 10)
        broker = Broker(journal_dir=str(tmp_path))
        broker.create_queue("q", durable=True)
        consumer = broker.create_consumer("q")
        broker.send("q", b"in-flight")
        held = consumer.receive(timeout=1)  # delivered, never acked
        for i in range(15):
            broker.send("q", f"x{i}".encode())
            msg = consumer.receive(timeout=1)
            consumer.ack(msg)  # crosses the threshold -> compaction
        broker.close()
        broker2 = Broker(journal_dir=str(tmp_path))
        c2 = broker2.create_consumer("q")
        recovered = c2.receive(timeout=1)
        assert recovered is not None and recovered.payload == b"in-flight"
        broker2.close()

    def test_large_backlog_skips_futile_compaction(self, tmp_path, monkeypatch):
        """With a standing backlog larger than the dead-record count,
        compaction is skipped (min-compact-percent semantics) and the
        window re-arms."""
        from corda_tpu.messaging.broker import Broker, _Journal

        monkeypatch.setattr(_Journal, "COMPACT_ACK_THRESHOLD", 5)
        broker = Broker(journal_dir=str(tmp_path))
        broker.create_queue("backlog", durable=True)
        consumer = broker.create_consumer("backlog")
        for i in range(100):  # big standing backlog
            broker.send("backlog", f"b{i}".encode())
        journal = broker._queues["backlog"].journal
        for _ in range(5):  # hits the ack threshold exactly
            consumer.ack(consumer.receive(timeout=1))
        # 5 acks < 95 pending: futile compaction skipped, window re-armed
        assert journal.acks_since_compact == 0
        import os

        # journal still holds every record (no rewrite happened)
        assert os.path.getsize(broker._journal_path("backlog")) > 5000
        broker.close()


class TestCrashRedelivery:
    """Consumer death with UNFLUSHED acks (the ACK_FLUSH_EVERY window):
    the journal's group-flushed acks trade a crash for redelivery, which
    receiver-side dedup by message id must absorb (docs/robustness.md)."""

    def test_unflushed_acks_redeliver_and_dedup_absorbs(self, tmp_path):
        from corda_tpu.messaging.broker import Broker

        d = str(tmp_path / "journal")
        broker = Broker(journal_dir=d)
        broker.create_queue("dq", durable=True)
        for i in range(10):
            broker.send("dq", b"m%d" % i)
        consumer = broker.create_consumer("dq")
        processed = {}  # message_id -> payload: the receiver's dedup set
        for _ in range(6):
            msg = consumer.receive(timeout=1)
            processed[msg.message_id] = msg.payload
            consumer.ack(msg)  # 6 acks < ACK_FLUSH_EVERY(64): unflushed
        # CRASH: a new broker replays the journal file as written on
        # disk — the old process's buffered ack records never made it
        broker2 = Broker(journal_dir=d)
        c2 = broker2.create_consumer("dq")
        redelivered, fresh = [], []
        while True:
            msg = c2.receive(timeout=0.2)
            if msg is None:
                break
            assert msg.delivery_count > 1  # journal marks ALL as redelivery
            if msg.message_id in processed:
                redelivered.append(msg)  # dedup absorbs: same id, same bytes
                assert processed[msg.message_id] == msg.payload
            else:
                fresh.append(msg)
            c2.ack(msg)
        # every acked-but-unflushed message came back; nothing was lost
        assert len(redelivered) == 6
        assert len(fresh) == 4
        broker.close()
        broker2.close()

    def test_enqueues_always_flushed_never_lost(self, tmp_path):
        """The asymmetric flush policy: enqueue records flush per append
        (losing one loses a message), so a crash right after send loses
        nothing even while acks ride the group-flush window."""
        from corda_tpu.messaging.broker import Broker

        d = str(tmp_path / "journal")
        broker = Broker(journal_dir=d)
        broker.create_queue("dq", durable=True)
        mids = [broker.send("dq", b"p%d" % i) for i in range(5)]
        # crash with NOTHING acked and the original handle never closed
        broker2 = Broker(journal_dir=d)
        c2 = broker2.create_consumer("dq")
        got = [c2.receive(timeout=1) for _ in range(5)]
        assert [m.message_id for m in got] == mids  # order preserved
        assert c2.receive(timeout=0.05) is None
        broker.close()
        broker2.close()

    def test_online_compaction_under_pending_messages_then_crash(
        self, tmp_path, monkeypatch
    ):
        """Compaction while the queue holds BOTH queued and in-flight
        messages, followed by a crash with unflushed acks: the rewritten
        journal must redeliver exactly the not-yet-flushed-acked set."""
        from corda_tpu.messaging.broker import Broker, _Journal

        monkeypatch.setattr(_Journal, "COMPACT_ACK_THRESHOLD", 8)
        d = str(tmp_path / "journal")
        broker = Broker(journal_dir=d)
        broker.create_queue("dq", durable=True)
        consumer = broker.create_consumer("dq")
        broker.send("dq", b"held")
        held = consumer.receive(timeout=1)  # in-flight across compaction
        assert held.payload == b"held"
        for i in range(8):
            broker.send("dq", b"work%d" % i)
        for _ in range(8):
            consumer.ack(consumer.receive(timeout=1))
        for i in range(3):
            broker.send("dq", b"queued%d" % i)
        journal = broker._queues["dq"].journal
        assert journal.acks_since_compact == 0  # compaction DID run
        # post-compaction traffic, acked but unflushed at crash time
        msg = consumer.receive(timeout=1)
        consumer.ack(msg)
        broker2 = Broker(journal_dir=d)
        c2 = broker2.create_consumer("dq")
        payloads = []
        while True:
            m = c2.receive(timeout=0.2)
            if m is None:
                break
            assert m.delivery_count > 1
            payloads.append(m.payload)
        # in-flight "held" + all queued survive; the unflushed ack of
        # queued0 redelivers (dedup territory), the 8 flushed... acks
        # were compacted away entirely
        assert set(payloads) == {b"held", b"queued0", b"queued1", b"queued2"}
        broker.close()
        broker2.close()

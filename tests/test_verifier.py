"""Verifier subsystem tests.

Mirrors the reference's `VerifierTests.kt:36-101` (single worker, N workers,
kill-one-mid-run redistribution, invalid-transaction rejection) plus the
TPU-specific signature batching seam.
"""
import time
from dataclasses import dataclass
from typing import List

import pytest

from corda_tpu.core.contracts import (
    Contract,
    ContractState,
    TransactionVerificationError,
    TypeOnlyCommandData,
    contract,
)
from corda_tpu.core.crypto import crypto
from corda_tpu.core.identity import Party
from corda_tpu.core.serialization.codec import corda_serializable
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.messaging import Broker
from corda_tpu.verifier import (
    InMemoryTransactionVerifierService,
    OutOfProcessTransactionVerifierService,
    SignatureBatcher,
    VerificationError,
    VerifierWorker,
)

ALICE_KP = crypto.entropy_to_keypair(80)
NOTARY_KP = crypto.entropy_to_keypair(81)
ALICE = Party("O=Alice,L=London,C=GB", ALICE_KP.public)
NOTARY = Party("O=Notary,L=Zurich,C=CH", NOTARY_KP.public)


@corda_serializable
@dataclass(frozen=True)
class VState(ContractState):
    magic: int = 7
    contract_name = "VContract"

    @property
    def participants(self) -> List:
        return []


@contract(name="VContract")
class VContract(Contract):
    def verify(self, tx) -> None:
        for s in tx.outputs_of_type(VState):
            if s.magic != 7:
                raise TransactionVerificationError(tx.id, "bad magic")


@corda_serializable
@dataclass(frozen=True)
class VCommand(TypeOnlyCommandData):
    pass


def _ltx(magic: int = 7):
    b = TransactionBuilder(notary=NOTARY)
    b.add_output_state(VState(magic=magic))
    b.add_command(VCommand(), ALICE_KP.public)
    wtx = b.to_wire_transaction()
    return wtx.to_ledger_transaction(
        resolve_state=lambda ref: (_ for _ in ()).throw(AssertionError),
        resolve_attachment=lambda h: (_ for _ in ()).throw(AssertionError),
    )


class TestSignatureBatcher:
    def _items(self, n, entropy0=100):
        items = []
        for i in range(n):
            kp = crypto.entropy_to_keypair(entropy0 + i)
            content = b"msg-%d" % i
            sig = crypto.do_sign(kp.private, content)
            items.append((kp.public, sig, content))
        return items

    def test_batch_resolves_futures(self):
        batcher = SignatureBatcher(max_batch=8, linger_ms=10_000)
        futures = batcher.submit_many(self._items(8))  # hits max_batch
        assert all(f.result(timeout=5) for f in futures)
        assert batcher.flushes == 1
        assert batcher.items_verified == 8

    def test_linger_flush(self):
        batcher = SignatureBatcher(max_batch=1000, linger_ms=30)
        fut = batcher.submit(self._items(1, entropy0=200)[0])
        assert fut.result(timeout=5) is True

    def test_bad_signature_isolated(self):
        items = self._items(4, entropy0=300)
        key, sig, content = items[2]
        items[2] = (key, sig, b"tampered")
        batcher = SignatureBatcher(max_batch=4, linger_ms=10_000)
        futures = batcher.submit_many(items)
        results = [f.result(timeout=5) for f in futures]
        assert results == [True, True, False, True]

    def test_cross_transaction_accumulation(self):
        batcher = SignatureBatcher(max_batch=6, linger_ms=10_000)
        f1 = batcher.submit_many(self._items(3, entropy0=400))
        f2 = batcher.submit_many(self._items(3, entropy0=500))
        assert all(f.result(timeout=5) for f in f1 + f2)
        assert batcher.flushes == 1  # one device dispatch for both txs


class TestInMemoryService:
    def test_valid_transaction(self):
        svc = InMemoryTransactionVerifierService()
        assert svc.verify(_ltx()).result(timeout=5) is None
        svc.stop()

    def test_invalid_transaction(self):
        svc = InMemoryTransactionVerifierService()
        err = svc.verify(_ltx(magic=8)).result(timeout=5)
        assert isinstance(err, VerificationError)
        with pytest.raises(VerificationError):
            svc.verify_sync(_ltx(magic=8))
        svc.stop()


class TestOutOfProcessService:
    def test_single_worker(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(broker, "nodeA")
        worker = VerifierWorker(broker).start()
        assert svc.verify(_ltx()).result(timeout=5) is None
        err = svc.verify(_ltx(magic=9)).result(timeout=5)
        assert isinstance(err, VerificationError)
        assert svc.metrics.success == 1
        assert svc.metrics.failure == 1
        assert svc.metrics.in_flight == 0
        worker.stop()
        svc.stop()

    def test_four_workers_share_load(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(broker, "nodeA")
        workers = [
            VerifierWorker(broker, name=f"verifier-{i}").start()
            for i in range(4)
        ]
        futures = [svc.verify(_ltx()) for _ in range(40)]
        assert all(f.result(timeout=10) is None for f in futures)
        assert sum(w.verified_count for w in workers) == 40
        # elasticity actually spread the work
        assert sum(1 for w in workers if w.verified_count > 0) >= 2
        for w in workers:
            w.stop()
        svc.stop()

    def test_worker_death_redistributes(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(broker, "nodeA")
        w1 = VerifierWorker(broker, name="doomed")
        # w1 never starts its thread: it holds a consumer but does no work,
        # simulating a worker that died after receiving nothing.
        futures = [svc.verify(_ltx()) for _ in range(10)]
        time.sleep(0.1)
        w2 = VerifierWorker(broker, name="survivor").start()
        w1.stop(graceful=False)  # crash: unacked work redelivered
        assert all(f.result(timeout=10) is None for f in futures)
        assert w2.verified_count == 10
        w2.stop()
        svc.stop()

    def test_signature_batch_offload(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(broker, "nodeA")
        worker = VerifierWorker(broker).start()
        items = []
        for i in range(6):
            kp = crypto.entropy_to_keypair(600 + i)
            content = b"content-%d" % i
            items.append((kp.public, crypto.do_sign(kp.private, content), content))
        key, sig, _ = items[3]
        items[3] = (key, sig, b"forged")
        futures = svc.verify_signatures(items)
        results = [f.result(timeout=10) for f in futures]
        assert results == [True, True, True, False, True, True]
        worker.stop()
        svc.stop()

    def test_worker_count_visible(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(broker, "nodeA")
        assert svc.worker_count() == 0  # reference warns on zero verifiers
        w = VerifierWorker(broker).start()
        assert svc.worker_count() == 1
        w.stop()
        svc.stop()

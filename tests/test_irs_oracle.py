"""IRS demo / oracle tests: tear-off signing + scheduler-driven fixing.

Reference parity: `samples/irs-demo/src/test/kotlin/net/corda/irs/api/
NodeInterestRatesTest.kt` (oracle signs valid tear-offs, refuses unknown
fixes and over-revealing/foreign tear-offs) and the scheduler firing a
fixing (IRSSimulation shape, radically reduced).
"""
import time
from dataclasses import replace

import pytest

from corda_tpu.core.contracts import StateAndRef
from corda_tpu.core.flows import FlowException
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.samples.irs_demo import (
    Fix,
    FixingFlow,
    FixOf,
    FixOutOfRange,
    InterestRateSwapState,
    IRSCommand,
    RateOracle,
    RatesFixFlow,
    UnknownFix,
)
from corda_tpu.testing.mocknetwork import MockNetwork

LIBOR_3M = FixOf("LIBOR", "2026-07-30", "3M")


class TestOracle:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.oracle_node = self.net.create_node("O=Oracle,L=Zurich,C=CH")
        self.oracle = RateOracle(
            self.oracle_node.info,
            self.oracle_node.services.key_management_service,
        )
        self.oracle_node.services.rate_oracle = self.oracle
        self.oracle.add_fix(Fix(LIBOR_3M, 3.25))

    def teardown_method(self):
        self.net.stop_nodes()

    def _irs_state(self, next_fixing_at=None):
        return InterestRateSwapState(
            fixed_leg_payer=self.alice.info,
            floating_leg_payer=self.alice.info,
            notional=1_000_000,
            fixed_rate=3.0,
            oracle_name=self.oracle_node.info.name,
            fix_of=LIBOR_3M,
            next_fixing_at=next_fixing_at,
        )

    def _issue_irs(self, next_fixing_at=None) -> StateAndRef:
        b = TransactionBuilder(notary=self.notary.info)
        b.add_output_state(self._irs_state(next_fixing_at))
        b.add_command(IRSCommand("Agree"), self.alice.info.owning_key)
        stx = self.alice.services.sign_initial_transaction(b)
        self.alice.services.record_transactions([stx])
        return stx.tx.out_ref(0)

    def test_rates_fix_flow_signs_over_tearoff(self):
        builder = TransactionBuilder(notary=self.notary.info)
        ref = self._issue_irs()
        builder.add_input_state(ref)
        builder.add_output_state(
            replace(ref.state.data, floating_rate=3.25, next_fixing_at=None)
        )
        builder.add_command(IRSCommand("Fixing"), self.alice.info.owning_key)
        h = self.alice.start_flow(
            RatesFixFlow(builder, self.oracle_node.info, LIBOR_3M, 3.0, 1.0)
        )
        self.net.run_network()
        wtx, fix, sig = h.result.result(timeout=5)
        assert fix.value == 3.25
        assert sig.is_valid(wtx.id.bytes)  # signature covers the FULL tx id
        assert self.oracle_node.info.owning_key.is_fulfilled_by({sig.by})

    def test_fix_out_of_tolerance_rejected(self):
        builder = TransactionBuilder(notary=self.notary.info)
        h = self.alice.start_flow(
            RatesFixFlow(builder, self.oracle_node.info, LIBOR_3M, 5.0, 0.1)
        )
        self.net.run_network()
        with pytest.raises(FixOutOfRange):
            h.result.result(timeout=5)

    def test_unknown_fix_rejected(self):
        builder = TransactionBuilder(notary=self.notary.info)
        h = self.alice.start_flow(
            RatesFixFlow(
                builder, self.oracle_node.info,
                FixOf("EURIBOR", "2026-07-30", "6M"), 3.0, 1.0,
            )
        )
        self.net.run_network()
        with pytest.raises(Exception, match="unknown fix"):
            h.result.result(timeout=5)

    def test_oracle_refuses_wrong_rate_command(self):
        """A tear-off with a Fix command whose value differs from the known
        rate must be refused (oracle attests data, not wishes)."""
        b = TransactionBuilder(notary=self.notary.info)
        ref = self._issue_irs()
        b.add_input_state(ref)
        b.add_command(
            Fix(LIBOR_3M, 99.0), self.oracle_node.info.owning_key
        )
        wtx = b.to_wire_transaction()
        from corda_tpu.core.contracts import Command

        ftx = wtx.build_filtered_transaction(
            lambda e: isinstance(e, Command) and isinstance(e.value, Fix)
        )
        with pytest.raises(Exception, match="unknown fix"):
            self.oracle.sign(ftx)

    def test_oracle_refuses_over_revealing_tearoff(self):
        """Revealed non-Fix components must abort signing — the oracle only
        attests rates, never transaction structure."""
        b = TransactionBuilder(notary=self.notary.info)
        ref = self._issue_irs()
        b.add_input_state(ref)
        b.add_command(Fix(LIBOR_3M, 3.25), self.oracle_node.info.owning_key)
        wtx = b.to_wire_transaction()
        ftx = wtx.build_filtered_transaction(lambda e: True)  # reveal all
        with pytest.raises(FlowException):
            self.oracle.sign(ftx)

    def test_privacy_of_tearoff(self):
        """The oracle-visible tear-off contains the Fix command but NOT the
        inputs/outputs of the transaction."""
        from corda_tpu.core.contracts import Command

        b = TransactionBuilder(notary=self.notary.info)
        ref = self._issue_irs()
        b.add_input_state(ref)
        b.add_output_state(replace(ref.state.data, floating_rate=3.25))
        b.add_command(Fix(LIBOR_3M, 3.25), self.oracle_node.info.owning_key)
        wtx = b.to_wire_transaction()
        ftx = wtx.build_filtered_transaction(
            lambda e: isinstance(e, Command) and isinstance(e.value, Fix)
        )
        assert ftx.inputs == []
        assert ftx.outputs == []
        assert len(ftx.commands) == 1
        assert ftx.id == wtx.id

    def test_scheduler_fires_fixing_flow(self):
        """A swap with a due fixing date goes through the whole pipeline:
        scheduler wake -> FixingFlow -> oracle query + tear-off sign ->
        finality; the replacement state carries the attested rate."""
        past = int((time.time() - 1) * 1_000_000_000)
        ref = self._issue_irs(next_fixing_at=past)
        started = self.alice.scheduler.wake()
        assert len(started) == 1
        self.net.run_network()
        fsm = self.alice.smm.flows[started[0]]
        stx = fsm.result.result(timeout=5)
        new_states = self.alice.services.vault_service.unconsumed_states(
            InterestRateSwapState.contract_name
        )
        assert len(new_states) == 1
        fixed = new_states[0].state.data
        assert fixed.floating_rate == 3.25
        assert fixed.next_fixing_at is None

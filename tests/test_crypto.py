"""Crypto unit tests.

Mirrors reference `core/src/test/kotlin/net/corda/core/crypto/CryptoUtilsTest.kt`
(per-scheme sign/verify/keygen, tamper detection, deterministic derivation).
"""
import pytest

from corda_tpu.core import crypto as c


SCHEMES = [
    c.EDDSA_ED25519_SHA512,
    c.ECDSA_SECP256K1_SHA256,
    c.ECDSA_SECP256R1_SHA256,
]


@pytest.mark.parametrize("scheme", SCHEMES, ids=lambda s: s.scheme_code_name)
def test_sign_verify_roundtrip(scheme):
    kp = c.generate_keypair(scheme)
    msg = b"hello tpu ledger"
    sig = c.do_sign(kp.private, msg)
    assert c.is_valid(kp.public, sig, msg)
    assert c.do_verify(kp.public, sig, msg)


@pytest.mark.parametrize("scheme", SCHEMES, ids=lambda s: s.scheme_code_name)
def test_tampered_message_rejected(scheme):
    kp = c.generate_keypair(scheme)
    sig = c.do_sign(kp.private, b"original")
    assert not c.is_valid(kp.public, sig, b"tampered")
    with pytest.raises(c.SignatureError):
        c.do_verify(kp.public, sig, b"tampered")


@pytest.mark.parametrize("scheme", SCHEMES, ids=lambda s: s.scheme_code_name)
def test_tampered_signature_rejected(scheme):
    kp = c.generate_keypair(scheme)
    sig = bytearray(c.do_sign(kp.private, b"msg"))
    sig[len(sig) // 2] ^= 0x40
    assert not c.is_valid(kp.public, bytes(sig), b"msg")


@pytest.mark.parametrize("scheme", SCHEMES, ids=lambda s: s.scheme_code_name)
def test_wrong_key_rejected(scheme):
    kp1 = c.generate_keypair(scheme)
    kp2 = c.generate_keypair(scheme)
    sig = c.do_sign(kp1.private, b"msg")
    assert not c.is_valid(kp2.public, sig, b"msg")


@pytest.mark.slow
@pytest.mark.skipif(
    not c.crypto.OPENSSL_AVAILABLE,
    reason="RSA needs the 'cryptography' package",
)
def test_rsa_sign_verify():
    kp = c.generate_keypair(c.RSA_SHA256)
    sig = c.do_sign(kp.private, b"rsa message")
    assert c.is_valid(kp.public, sig, b"rsa message")
    assert not c.is_valid(kp.public, sig, b"other")


def test_empty_payloads_rejected():
    kp = c.generate_keypair()
    with pytest.raises(c.CryptoError):
        c.do_sign(kp.private, b"")
    sig = c.do_sign(kp.private, b"x")
    with pytest.raises(c.CryptoError):
        c.do_verify(kp.public, sig, b"")
    with pytest.raises(c.CryptoError):
        c.do_verify(kp.public, b"", b"x")


@pytest.mark.parametrize(
    "scheme", [c.EDDSA_ED25519_SHA512, c.ECDSA_SECP256K1_SHA256, c.ECDSA_SECP256R1_SHA256],
    ids=lambda s: s.scheme_code_name,
)
def test_deterministic_derivation(scheme):
    kp1 = c.derive_keypair_from_entropy(scheme, 123456789)
    kp2 = c.derive_keypair_from_entropy(scheme, 123456789)
    kp3 = c.derive_keypair_from_entropy(scheme, 987654321)
    assert kp1.public == kp2.public
    assert kp1.private == kp2.private
    assert kp1.public != kp3.public
    sig = c.do_sign(kp1.private, b"derived")
    assert c.is_valid(kp1.public, sig, b"derived")


def test_find_signature_scheme():
    assert c.find_signature_scheme(4) is c.EDDSA_ED25519_SHA512
    assert c.find_signature_scheme("RSA_SHA256") is c.RSA_SHA256
    kp = c.generate_keypair(c.ECDSA_SECP256K1_SHA256)
    assert c.find_signature_scheme(kp.public) is c.ECDSA_SECP256K1_SHA256
    with pytest.raises(c.UnsupportedSchemeError):
        c.find_signature_scheme(99)


def test_scheme_registry_matches_reference_ids():
    # ids 1-6 with identical code names (reference Crypto.kt:176-183);
    # ids ABOVE 6 are framework extensions (7 = BLS_BLS12381, the
    # aggregate scheme — the reference has no BLS) and must never
    # collide with or renumber the reference block
    ids = {s.scheme_number_id for s in c.SUPPORTED_SIGNATURE_SCHEMES.values()}
    assert set(range(1, 7)) <= ids
    assert ids - set(range(1, 7)) == {7}
    assert c.SUPPORTED_SIGNATURE_SCHEMES["EDDSA_ED25519_SHA512"].scheme_number_id == 4
    assert c.SUPPORTED_SIGNATURE_SCHEMES["SPHINCS-256_SHA512"].scheme_number_id == 5
    assert c.SUPPORTED_SIGNATURE_SCHEMES["BLS_BLS12381"].scheme_number_id == 7
    assert c.DEFAULT_SIGNATURE_SCHEME is c.EDDSA_ED25519_SHA512


def test_public_key_on_curve():
    kp = c.generate_keypair(c.EDDSA_ED25519_SHA512)
    assert c.public_key_on_curve(kp.public)
    bad = c.SchemePublicKey("EDDSA_ED25519_SHA512", b"\xff" * 32)
    # high bit pattern decodes to a y >= p or off-curve point
    assert not c.public_key_on_curve(bad)
    kpk = c.generate_keypair(c.ECDSA_SECP256K1_SHA256)
    assert c.public_key_on_curve(kpk.public)


def test_host_oracle_agrees_with_pure_python_ed25519():
    from corda_tpu.core.crypto import ed25519_math as ed

    kp = c.generate_keypair(c.EDDSA_ED25519_SHA512)
    msg = b"cross-check"
    sig = c.do_sign(kp.private, msg)
    assert ed.verify(kp.public.encoded, msg, sig)
    assert ed.public_from_seed(kp.private.encoded) == kp.public.encoded
    assert ed.sign(kp.private.encoded, msg) == sig  # ed25519 is deterministic
    assert not ed.verify(kp.public.encoded, msg + b"!", sig)


def test_host_oracle_agrees_with_pure_python_ecdsa():
    from corda_tpu.core.crypto import secp_math as sm

    for scheme, curve in [
        (c.ECDSA_SECP256K1_SHA256, sm.SECP256K1),
        (c.ECDSA_SECP256R1_SHA256, sm.SECP256R1),
    ]:
        kp = c.generate_keypair(scheme)
        msg = b"ecdsa cross-check"
        sig = c.do_sign(kp.private, msg)
        r, s = sm.der_decode_sig(sig)
        pub = curve.decode_point(kp.public.encoded)
        assert sm.ecdsa_verify(curve, pub, msg, r, s)
        assert not sm.ecdsa_verify(curve, pub, msg + b"!", r, s)
        # our own signer also produces signatures the lib accepts
        d = int.from_bytes(kp.private.encoded, "big")
        r2, s2 = sm.ecdsa_sign(curve, d, msg)
        assert c.is_valid(kp.public, sm.der_encode_sig(r2, s2), msg)


def test_signature_value_types():
    from corda_tpu.core.crypto import signing

    kp = c.generate_keypair()
    ws = signing.sign_bytes(kp.private, kp.public, b"content")
    assert ws.verify(b"content")
    assert not ws.is_valid(b"evil")
    meta = signing.MetaData(
        scheme_code_name=kp.public.scheme_code_name,
        version_id="1",
        signature_type=signing.SignatureType.FULL,
        timestamp=None,
        visible_inputs=None,
        signed_inputs=None,
        merkle_root=b"\x01" * 32,
        public_key=kp.public,
    )
    tx_sig = signing.TransactionSignature(c.do_sign(kp.private, meta.bytes()), meta)
    assert tx_sig.verify()
    bad_meta = signing.MetaData(
        meta.scheme_code_name, "2", meta.signature_type, meta.timestamp,
        meta.visible_inputs, meta.signed_inputs, meta.merkle_root, meta.public_key,
    )
    assert not signing.TransactionSignature(tx_sig.bytes, bad_meta).is_valid()


def test_encodings_roundtrip():
    from corda_tpu.core.crypto import encodings as e

    for data in [b"", b"\x00\x00hi", b"hello world", bytes(range(256))]:
        assert e.from_base58(e.to_base58(data)) == data
        assert e.from_base64(e.to_base64(data)) == data
        assert e.from_hex(e.to_hex(data)) == data


@pytest.mark.slow
class TestSphincs256:
    """SPHINCS-256 (scheme id 5): full WOTS+/HORST hypertree implementation
    (reference Crypto.kt:134-151 binds BouncyCastle PQC; structure and
    parameter set are the parity surface here)."""

    def test_sign_verify_roundtrip_via_hub(self):
        from corda_tpu.core.crypto import crypto as c

        kp = c.generate_keypair(c.SUPPORTED_SIGNATURE_SCHEMES["SPHINCS-256_SHA512"])
        sig = c.do_sign(kp.private, b"post-quantum payload")
        assert c.do_verify(kp.public, sig, b"post-quantum payload")
        assert c.is_valid(kp.public, sig, b"post-quantum payload")

    def test_tamper_rejection_classes(self):
        from corda_tpu.core.crypto import sphincs

        kp = sphincs.generate_keypair(b"\x11" * 32)
        msg = b"m" * 100
        sig = sphincs.sign(kp.private, msg)
        assert sphincs.verify(kp.public, sig, msg)
        # wrong message
        assert not sphincs.verify(kp.public, sig, msg + b"!")
        # flipped bits in every structural region of the signature
        for pos in (5, 40, 1000, 18000, 44000):
            bad = sig[:pos] + bytes([sig[pos] ^ 1]) + sig[pos + 1:]
            assert not sphincs.verify(kp.public, bad, msg), pos
        # truncation / garbage
        assert not sphincs.verify(kp.public, sig[:-1], msg)
        assert not sphincs.verify(kp.public, b"", msg)
        # wrong key
        other = sphincs.generate_keypair(b"\x12" * 32)
        assert not sphincs.verify(other.public, sig, msg)

    def test_deterministic_and_distinct(self):
        from corda_tpu.core.crypto import sphincs

        kp = sphincs.generate_keypair(b"\x13" * 32)
        s1 = sphincs.sign(kp.private, b"a")
        s2 = sphincs.sign(kp.private, b"a")
        s3 = sphincs.sign(kp.private, b"b")
        assert s1 == s2          # stateless deterministic signing
        assert s1 != s3
        assert len(s1) == sphincs.SIGNATURE_SIZE

    def test_keypair_from_fixed_seed_is_stable(self):
        from corda_tpu.core.crypto import sphincs

        a = sphincs.generate_keypair(b"\x14" * 32)
        b = sphincs.generate_keypair(b"\x14" * 32)
        assert a.public.encoded == b.public.encoded

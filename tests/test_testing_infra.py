"""Test-infrastructure tests: ledger DSL, Generator monad, Expect recorder,
GeneratedLedger property data, clauses framework.
(Reference coverage: TestDSL usage in CashTests, Generator.kt,
Expect.kt, GeneratedLedger.kt.)
"""
import random

import pytest

from corda_tpu.core.contracts import Amount, Issued, TransactionVerificationError
from corda_tpu.core.contracts.clauses import (
    AllOf,
    AnyOf,
    Clause,
    FirstOf,
    verify_clause,
)
from corda_tpu.core.crypto import crypto
from corda_tpu.core.identity import Party
from corda_tpu.finance.cash import CashCommand, CashState
from corda_tpu.testing import (
    ExpectRecorder,
    Generator,
    generate_ledger,
    ledger,
)
from corda_tpu.utils.observable import Observable

BANK_KP = crypto.entropy_to_keypair(700)
ALICE_KP = crypto.entropy_to_keypair(701)
NOTARY_KP = crypto.entropy_to_keypair(702)
BANK = Party("O=Bank,L=London,C=GB", BANK_KP.public)
ALICE = Party("O=Alice,L=London,C=GB", ALICE_KP.public)
NOTARY = Party("O=Notary,L=Zurich,C=CH", NOTARY_KP.public)
TOKEN = Issued(BANK.ref(1), "USD")


class TestLedgerDSL:
    def test_issue_then_move(self):
        with ledger(notary=NOTARY) as l:
            with l.transaction() as tx:
                tx.output("alice cash", CashState(
                    amount=Amount(100, TOKEN), owner=ALICE))
                tx.command(BANK.owning_key, CashCommand.Issue())
                tx.verifies()
            with l.transaction() as tx:
                tx.input("alice cash")
                tx.output(state=CashState(amount=Amount(100, TOKEN), owner=BANK))
                tx.command(ALICE.owning_key, CashCommand.Move())
                tx.verifies()

    def test_fails_with(self):
        with ledger(notary=NOTARY) as l:
            with l.transaction() as tx:
                tx.output("c", CashState(amount=Amount(100, TOKEN), owner=ALICE))
                tx.command(BANK.owning_key, CashCommand.Issue())
                tx.verifies()
            with l.transaction() as tx:
                tx.input("c")
                tx.output(state=CashState(amount=Amount(90, TOKEN), owner=BANK))
                tx.command(ALICE.owning_key, CashCommand.Move())
                tx.fails_with("not conserved")

    def test_fails_with_wrong_substring_raises(self):
        with ledger(notary=NOTARY) as l:
            with l.transaction() as tx:
                tx.output("c", CashState(amount=Amount(100, TOKEN), owner=ALICE))
                tx.command(ALICE.owning_key, CashCommand.Issue())  # wrong signer
                with pytest.raises(AssertionError):
                    tx.fails_with("completely unrelated message")


class TestGenerator:
    def test_monad_laws_smoke(self):
        rng = random.Random(1)
        g = Generator.int_range(1, 6).bind(
            lambda n: Generator.list_of(Generator.choice("xyz"), n)
        )
        value = g.generate(rng)
        assert 1 <= len(value) <= 6
        assert set(value) <= set("xyz")

    def test_deterministic_given_seed(self):
        g = Generator.sized_list_of(Generator.int_range(0, 100), 5, 10)
        assert g.generate(random.Random(7)) == g.generate(random.Random(7))

    def test_frequency(self):
        g = Generator.frequency([(9, Generator.pure("a")), (1, Generator.pure("b"))])
        values = [g.generate(random.Random(i)) for i in range(50)]
        assert values.count("a") > values.count("b")


class TestExpect:
    def test_expect_event(self):
        obs = Observable()
        rec = ExpectRecorder(obs)
        obs.on_next({"n": 1})
        obs.on_next({"n": 2})
        assert rec.expect(lambda e: e["n"] == 2, timeout=1) == {"n": 2}

    def test_expect_sequence(self):
        obs = Observable()
        rec = ExpectRecorder(obs)
        for n in [1, 2, 3]:
            obs.on_next(n)
        rec.expect_sequence(lambda e: e == 1, lambda e: e == 3, timeout=1)

    def test_expect_timeout(self):
        rec = ExpectRecorder()
        with pytest.raises(AssertionError, match="expected"):
            rec.expect(lambda e: True, timeout=0.05)


class TestGeneratedLedger:
    def test_all_generated_transactions_verify(self):
        gl = generate_ledger(random.Random(3), n_parties=3, n_transactions=30)
        assert len(gl.transactions) == 30
        for stx in gl.transactions:
            ltx = stx.tx.to_ledger_transaction(
                resolve_state=gl.resolve_state,
                resolve_attachment=lambda h: None,
            )
            ltx.verify()  # contracts hold
            stx.verify_required_signatures()  # signatures hold

    def test_property_forged_signature_detected(self):
        gl = generate_ledger(random.Random(4), n_transactions=10)
        stx = gl.transactions[0]
        from corda_tpu.core.crypto.signing import DigitalSignatureWithKey
        from corda_tpu.core.transactions.signed import SignedTransaction

        bad_sig = DigitalSignatureWithKey(
            bytes(64), stx.sigs[0].by
        )
        forged = SignedTransaction(stx.tx_bits, (bad_sig,) + stx.sigs[1:])
        with pytest.raises(Exception):
            forged.verify_required_signatures()


class TestClauses:
    class IssueClause(Clause):
        required_commands = (CashCommand.Issue,)

        def verify(self, tx, inputs, outputs, commands, grouping_key):
            if inputs:
                raise TransactionVerificationError(None, "issue with inputs")
            return {c.value for c in commands
                    if isinstance(c.value, CashCommand.Issue)}

    class MoveClause(Clause):
        required_commands = (CashCommand.Move,)

        def verify(self, tx, inputs, outputs, commands, grouping_key):
            return {c.value for c in commands
                    if isinstance(c.value, CashCommand.Move)}

    def _fake_tx(self, commands, inputs=()):
        from corda_tpu.core.contracts.structures import AuthenticatedObject

        class FakeTx:
            id = None
            input_states = list(inputs)
            output_states = []

        FakeTx.commands = [
            AuthenticatedObject(signers=(), signing_parties=(), value=c)
            for c in commands
        ]
        return FakeTx()

    def test_first_of_picks_first_match(self):
        tx = self._fake_tx([CashCommand.Issue()])
        clause = FirstOf(self.IssueClause(), self.MoveClause())
        verify_clause(tx, clause, tx.commands)

    def test_any_of_requires_a_match(self):
        tx = self._fake_tx([CashCommand.Exit(Amount(1, TOKEN))])
        clause = AnyOf(self.IssueClause(), self.MoveClause())
        with pytest.raises(TransactionVerificationError, match="no clause"):
            verify_clause(tx, clause, tx.commands)

    def test_all_of_fails_if_one_missing(self):
        tx = self._fake_tx([CashCommand.Issue()])
        clause = AllOf(self.IssueClause(), self.MoveClause())
        with pytest.raises(TransactionVerificationError, match="did not match"):
            verify_clause(tx, clause, tx.commands)

    def test_unmatched_command_rejected(self):
        tx = self._fake_tx([CashCommand.Issue(), CashCommand.Move()])
        clause = FirstOf(self.IssueClause(), self.MoveClause())
        with pytest.raises(TransactionVerificationError, match="not matched"):
            verify_clause(tx, clause, tx.commands)

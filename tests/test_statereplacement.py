"""State-replacement flow tests: notary change + contract upgrade.

Reference parity: `core/src/test/kotlin/net/corda/core/flows/
NotaryChangeTests.kt` and `ContractUpgradeFlowTest.kt` — happy path over
MockNetwork, plus refusal cases (wrong notary, unauthorised upgrade).
"""
from dataclasses import dataclass
from typing import List

import pytest

from corda_tpu.core.contracts import (
    Amount,
    Contract,
    ContractState,
    StateAndRef,
    TypeOnlyCommandData,
    contract,
)
from corda_tpu.core.flows import (
    ContractUpgradeFlow,
    NotaryChangeFlow,
    StateReplacementException,
    UpgradeCommand,
    UpgradedContract,
)
from corda_tpu.core.serialization.codec import corda_serializable
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.core.transactions.notary_change import (
    NotaryChangeWireTransaction,
)
from corda_tpu.testing.mocknetwork import MockNetwork


@corda_serializable
@dataclass(frozen=True)
class DealStateV1(ContractState):
    parties: tuple = ()
    magic: int = 7
    contract_name = "DealV1"

    @property
    def participants(self) -> List:
        return list(self.parties)


@corda_serializable
@dataclass(frozen=True)
class DealStateV2(ContractState):
    parties: tuple = ()
    magic: int = 7
    version: int = 2
    contract_name = "DealV2"

    @property
    def participants(self) -> List:
        return list(self.parties)


@corda_serializable
@dataclass(frozen=True)
class DealCommand(TypeOnlyCommandData):
    pass


@contract(name="DealV1")
class DealV1(Contract):
    def verify(self, tx) -> None:
        # Accepts issuance and upgrade commands.
        pass


@contract(name="DealV2")
class DealV2(Contract, UpgradedContract):
    legacy_contract_name = "DealV1"

    def upgrade(self, state):
        return DealStateV2(parties=state.parties, magic=state.magic)

    def verify(self, tx) -> None:
        pass


class _Base:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary_a = self.net.create_notary_node(
            "O=Notary A,L=Zurich,C=CH", validating=True
        )
        self.notary_b = self.net.create_notary_node(
            "O=Notary B,L=Geneva,C=CH", validating=True
        )
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.bob = self.net.create_node("O=Bob,L=Paris,C=FR")

    def teardown_method(self):
        self.net.stop_nodes()

    def _issue_deal(self, parties, notary) -> StateAndRef:
        """Issue a two-party DealStateV1 signed by both (so both hold it)."""
        builder = TransactionBuilder(notary=notary.info)
        state = DealStateV1(parties=tuple(p.info for p in parties))
        builder.add_output_state(state)
        builder.add_command(
            DealCommand(), *[p.info.owning_key for p in parties]
        )
        stx = parties[0].services.sign_initial_transaction(builder)
        for p in parties[1:]:
            sig = p.services.key_management_service.sign(
                stx.id.bytes, p.info.owning_key
            )
            stx = stx.with_additional_signature(sig)
        for p in parties:
            p.services.record_transactions([stx])
        return stx.tx.out_ref(0)


class TestNotaryChange(_Base):
    def test_happy_path_two_participants(self):
        original = self._issue_deal([self.alice, self.bob], self.notary_a)
        assert original.state.notary == self.notary_a.info
        h = self.alice.start_flow(
            NotaryChangeFlow(original, self.notary_b.info)
        )
        self.net.run_network()
        new_ref = h.result.result(timeout=5)
        assert new_ref.state.notary == self.notary_b.info
        assert new_ref.state.data == original.state.data
        # Both nodes resolve the replacement state; the old one is consumed.
        for node in (self.alice, self.bob):
            ts = node.services.load_state(new_ref.ref)
            assert ts.notary == self.notary_b.info
        # The explorer summary endpoint must DEGRADE on the recorded
        # notary-change tx (no command list; outputs need resolution),
        # never crash the dashboard (review finding).
        from corda_tpu.rpc.ops import CordaRPCOps

        ops = CordaRPCOps(self.alice.services, self.alice.smm)
        rows = ops.recent_transactions(limit=10)
        kinds = {r["type"] for r in rows}
        assert "NotaryChangeWireTransaction" in kinds
        nc = next(
            r for r in rows if r["type"] == "NotaryChangeWireTransaction"
        )
        assert nc["outputs"] is None and nc["commands"] is None
        assert nc["signatures"] >= 2
        # The new state is usable: spend it with the NEW notary.
        builder = TransactionBuilder(notary=self.notary_b.info)
        builder.add_input_state(new_ref)
        builder.add_output_state(
            DealStateV1(parties=(self.alice.info,)), self.notary_b.info
        )
        builder.add_command(
            DealCommand(), self.alice.info.owning_key, self.bob.info.owning_key
        )
        stx = self.alice.services.sign_initial_transaction(builder)
        sig = self.bob.services.key_management_service.sign(
            stx.id.bytes, self.bob.info.owning_key
        )
        stx = stx.with_additional_signature(sig)
        from corda_tpu.core.flows import FinalityFlow

        h2 = self.alice.start_flow(FinalityFlow(stx))
        self.net.run_network()
        h2.result.result(timeout=5)

    def test_unknown_new_notary_refused(self):
        original = self._issue_deal([self.alice, self.bob], self.notary_a)
        # Bob refuses a change to a party that is not an advertised notary.
        h = self.alice.start_flow(NotaryChangeFlow(original, self.bob.info))
        self.net.run_network()
        with pytest.raises(Exception, match="not a known notary|notaries must be different|FlowException"):
            h.result.result(timeout=5)

    def test_old_notary_consumed_inputs(self):
        """After the change, the OLD notary must refuse a spend of the
        original ref (double-spend protection across the migration)."""
        original = self._issue_deal([self.alice, self.bob], self.notary_a)
        h = self.alice.start_flow(
            NotaryChangeFlow(original, self.notary_b.info)
        )
        self.net.run_network()
        h.result.result(timeout=5)
        builder = TransactionBuilder(notary=self.notary_a.info)
        builder.add_input_state(original)
        builder.add_output_state(
            DealStateV1(parties=(self.alice.info,)), self.notary_a.info
        )
        builder.add_command(
            DealCommand(), self.alice.info.owning_key, self.bob.info.owning_key
        )
        stx = self.alice.services.sign_initial_transaction(builder)
        sig = self.bob.services.key_management_service.sign(
            stx.id.bytes, self.bob.info.owning_key
        )
        stx = stx.with_additional_signature(sig)
        from corda_tpu.core.flows import FinalityFlow

        h2 = self.alice.start_flow(FinalityFlow(stx))
        self.net.run_network()
        with pytest.raises(Exception, match="[Cc]onflict|consumed"):
            h2.result.result(timeout=5)

    def test_transaction_type_invariants(self):
        with pytest.raises(ValueError, match="must have inputs"):
            NotaryChangeWireTransaction((), self.notary_a.info, self.notary_b.info)
        original = self._issue_deal([self.alice], self.notary_a)
        with pytest.raises(ValueError, match="must be different"):
            NotaryChangeWireTransaction(
                (original.ref,), self.notary_a.info, self.notary_a.info
            )


class TestContractUpgrade(_Base):
    def test_happy_path(self):
        original = self._issue_deal([self.alice, self.bob], self.notary_a)
        # the counterparty must explicitly consent (reference
        # authoriseContractUpgrade)
        self.bob.services.contract_upgrade_service.authorise(
            original.ref, "DealV2"
        )
        h = self.alice.start_flow(ContractUpgradeFlow(original, "DealV2"))
        self.net.run_network()
        new_ref = h.result.result(timeout=5)
        assert isinstance(new_ref.state.data, DealStateV2)
        assert new_ref.state.data.magic == 7
        # Both sides recorded the upgrade.
        for node in (self.alice, self.bob):
            ts = node.services.load_state(new_ref.ref)
            assert ts.data.contract_name == "DealV2"

    def test_unauthorised_upgrade_refused(self):
        """Registration alone is not consent: without an authorisation the
        acceptor rejects (reference ContractUpgradeService gate)."""
        original = self._issue_deal([self.alice, self.bob], self.notary_a)
        h = self.alice.start_flow(ContractUpgradeFlow(original, "DealV2"))
        self.net.run_network()
        with pytest.raises(Exception, match="not authorised"):
            h.result.result(timeout=5)

    def test_deauthorised_upgrade_refused(self):
        original = self._issue_deal([self.alice, self.bob], self.notary_a)
        svc = self.bob.services.contract_upgrade_service
        svc.authorise(original.ref, "DealV2")
        svc.deauthorise(original.ref)
        h = self.alice.start_flow(ContractUpgradeFlow(original, "DealV2"))
        self.net.run_network()
        with pytest.raises(Exception, match="not authorised"):
            h.result.result(timeout=5)

    def test_unregistered_contract_refused(self):
        original = self._issue_deal([self.alice, self.bob], self.notary_a)
        h = self.alice.start_flow(ContractUpgradeFlow(original, "NoSuchContract"))
        self.net.run_network()
        with pytest.raises(Exception, match="not a registered UpgradedContract"):
            h.result.result(timeout=5)

    def test_upgrade_command_rules(self):
        from corda_tpu.core.flows.statereplacement import verify_upgrade

        state = DealStateV1(parties=(self.alice.info, self.bob.info))
        upgraded = DealV2()
        good = upgraded.upgrade(state)
        verify_upgrade(
            state, good, upgraded,
            [self.alice.info.owning_key, self.bob.info.owning_key],
        )
        with pytest.raises(StateReplacementException, match="all participant keys"):
            verify_upgrade(state, good, upgraded, [self.alice.info.owning_key])
        with pytest.raises(StateReplacementException, match="upgraded version"):
            verify_upgrade(
                state, DealStateV2(parties=(), magic=99), upgraded,
                [self.alice.info.owning_key, self.bob.info.owning_key],
            )


class TestNotaryChangeSecurity(_Base):
    def test_wrong_old_notary_rejected(self):
        """A notary-change tx naming notary B as the 'old' notary for
        states actually governed by notary A must be rejected — otherwise
        inputs committed under A could be consumed through B, forking the
        ledger (round-2 review finding)."""
        from corda_tpu.core.transactions.signed import SignedTransaction

        original = self._issue_deal([self.alice], self.notary_a)
        wtx = NotaryChangeWireTransaction(
            (original.ref,), self.notary_b.info, self.notary_a.info
        )
        kms = self.alice.services.key_management_service
        sig = kms.sign(wtx.id.bytes, self.alice.info.owning_key)
        stx = SignedTransaction.of(wtx, (sig,))
        from corda_tpu.node.notary import NotaryClientFlow

        h = self.alice.start_flow(NotaryClientFlow(stx))
        self.net.run_network()
        with pytest.raises(Exception, match="not this notary|governed by"):
            h.result.result(timeout=5)

"""Process-separation tests: the broker TCP transport, standalone verifier
and node OS processes, and the driver DSL.

Reference parity: this is the integration tier the reference runs with the
driver DSL (`test-utils/.../driver/Driver.kt:252-263`), the verifier
elasticity suite (`verifier/src/integration-test/.../VerifierTests.kt:
54-101` — N workers, kill one mid-run, work redistributes) and the smoke
tests that treat a packaged node as a black box
(`smoke-test-utils/.../NodeProcess.kt`). Round 1 ran all of this inside
one interpreter; these tests cross real process boundaries.
"""
import os
import time

import pytest

from corda_tpu.core.crypto import crypto
from corda_tpu.messaging import Broker, UnknownQueueError
from corda_tpu.messaging.net import BrokerServer, RemoteBroker
from corda_tpu.testing.driver import driver
from corda_tpu.verifier import OutOfProcessTransactionVerifierService


@pytest.fixture()
def served_broker():
    broker = Broker()
    server = BrokerServer(broker, port=0).start()
    yield broker, server
    server.stop()
    broker.close()


class TestRemoteBroker:
    def test_roundtrip_over_tcp(self, served_broker):
        broker, server = served_broker
        rb = RemoteBroker(server.host, server.port)
        rb.create_queue("q1")
        assert rb.queue_exists("q1")
        assert "q1" in rb.queue_names()
        mid = rb.send("q1", b"hello", headers={"topic": "t", "n": "1"})
        assert mid
        assert rb.message_count("q1") == 1
        c = rb.create_consumer("q1")
        msg = c.receive(timeout=2)
        assert msg is not None
        assert msg.payload == b"hello"
        assert msg.headers["topic"] == "t"
        assert msg.message_id == mid
        c.ack(msg)
        assert c.receive(timeout=0.1) is None
        rb.close()

    def test_error_propagates(self, served_broker):
        _, server = served_broker
        rb = RemoteBroker(server.host, server.port)
        with pytest.raises(UnknownQueueError):
            rb.send("nope", b"x")
        rb.close()

    def test_consumer_socket_death_redelivers(self, served_broker):
        """A consumer whose connection dies without acking must have its
        message redelivered to a surviving consumer (VerifierTests.kt:73-101
        across a real socket)."""
        broker, server = served_broker
        rb1 = RemoteBroker(server.host, server.port)
        rb1.create_queue("work")
        rb1.send("work", b"job-1")
        doomed = rb1.create_consumer("work")
        msg = doomed.receive(timeout=2)
        assert msg is not None and msg.delivery_count == 1
        # Crash: close the socket without ack or polite OP_CLOSE.
        doomed._conn.sock.close()

        rb2 = RemoteBroker(server.host, server.port)
        survivor = rb2.create_consumer("work")
        redelivered = survivor.receive(timeout=10)
        assert redelivered is not None
        assert redelivered.payload == b"job-1"
        assert redelivered.delivery_count == 2
        survivor.ack(redelivered)
        rb1.close()
        rb2.close()

    def test_in_process_services_work_over_tcp(self, served_broker):
        """The out-of-process verifier service + worker pair, with BOTH ends
        talking through RemoteBroker (same code, real socket between)."""
        from corda_tpu.verifier import VerifierWorker

        _, server = served_broker
        svc_side = RemoteBroker(server.host, server.port)
        worker_side = RemoteBroker(server.host, server.port)
        svc = OutOfProcessTransactionVerifierService(svc_side, "nodeT")
        worker = VerifierWorker(worker_side).start()
        items = []
        for i in range(4):
            kp = crypto.entropy_to_keypair(900 + i)
            content = b"c-%d" % i
            items.append((kp.public, crypto.do_sign(kp.private, content), content))
        key, sig, _ = items[2]
        items[2] = (key, sig, b"forged")
        futures = svc.verify_signatures(items)
        assert [f.result(timeout=30) for f in futures] == [True, True, False, True]
        worker.stop()
        svc.stop()
        svc_side.close()
        worker_side.close()


@pytest.mark.slow
class TestStandaloneVerifier:
    def test_elasticity_kill_one_mid_burst(self, tmp_path):
        """Two standalone verifier processes compete on one queue; SIGKILL
        one mid-burst; every request still gets a response (redelivery to
        the survivor). Mirrors VerifierTests.kt:73-101 with OS processes."""
        with driver(str(tmp_path)) as d:
            bh = d.start_broker()
            v1 = d.start_verifier(bh.address, name="verifier-a")
            v2 = d.start_verifier(bh.address, name="verifier-b")

            svc = OutOfProcessTransactionVerifierService(bh.remote(), "reqNode")
            assert svc.worker_count() >= 2

            kp = crypto.entropy_to_keypair(1234)
            content = b"the-content"
            good = (kp.public, crypto.do_sign(kp.private, content), content)

            n_requests = 40
            futures = []
            for i in range(n_requests):
                futures.append(svc.verify_signatures([good, good, good]))
                if i == 5:
                    v1.kill()  # crash, no graceful close
            for fs in futures:
                for f in fs:
                    assert f.result(timeout=180) is True
            assert not v1.alive()
            assert v2.alive()
            svc.stop()


@pytest.mark.slow
class TestStandaloneNode:
    def test_node_process_rpc_smoke(self, tmp_path):
        """Black-box node: spawn `python -m corda_tpu.node`, connect RPC over
        TCP, check identity, issue cash via flow, query the vault, shut
        down cleanly (NodeProcess.kt smoke-test shape)."""
        with driver(str(tmp_path)) as d:
            node = d.start_node(
                {
                    "my_legal_name": "O=Bank A,L=London,C=GB",
                    "notary_type": "simple",
                    "identity_entropy": 4242,
                    "rpc_users": [
                        {"username": "admin", "password": "pw",
                         "permissions": ["ALL"]}
                    ],
                }
            )
            client = node.rpc()
            conn = client.start("admin", "pw")
            info = conn.proxy.node_info()
            assert "Bank A" in str(info)

            # Run a real flow through the wire: self-issue 1000 GBP, then
            # see it in the vault (RPC -> SMM -> flow -> vault, all in the
            # node process).
            from corda_tpu.core.contracts import Amount

            flow_id = conn.proxy.start_flow_dynamic(
                "CashIssueFlow",
                Amount(1000_00, "GBP"),
                b"ref-1",
                info,
                info,  # the node is its own (simple) notary
            )
            result = conn.proxy.flow_result(flow_id, 60)
            assert result is not None
            states = conn.proxy.vault_query("corda_tpu.finance.Cash")
            assert len(states) == 1
            client.close()
            rc = node.terminate()
            assert rc == 0, node.log()


@pytest.mark.slow
class TestMultiNodeNetwork:
    def test_discovery_and_cross_node_payment_tls(self, tmp_path):
        """Three real node processes with mutual-TLS broker transports:
        a directory node (network map + notary), Bank A and Bank B. The
        banks discover each other THROUGH the map node (signed
        registrations + push), then Bank A issues cash and pays Bank B —
        flow sessions, notarisation and broadcast all cross process
        boundaries over store-and-forward bridges.

        Reference shape: NetworkMapService.kt:65-71 (protocol),
        ArtemisMessagingServer.kt:299-412 (bridges + TLS),
        Driver.kt multi-node integration tests."""
        certs = str(tmp_path / "shared-certs")
        with driver(str(tmp_path)) as d:
            mapnode = d.start_node(
                {
                    "my_legal_name": "O=Notary Map,L=Zurich,C=CH",
                    "network_map_service": True,
                    "notary_type": "simple",
                    "identity_entropy": 9001,
                    "tls": True,
                    "certificates_dir": certs,
                },
                name="mapnode",
            )
            map_addr = f"127.0.0.1:{mapnode.broker_port}"
            common = {
                "network_map": map_addr,
                "tls": True,
                "certificates_dir": certs,
                "rpc_users": [{"username": "a", "password": "a"}],
            }
            bank_a = d.start_node(
                {**common, "my_legal_name": "O=Bank A,L=London,C=GB",
                 "identity_entropy": 9002},
                name="bank-a",
            )
            bank_b = d.start_node(
                {**common, "my_legal_name": "O=Bank B,L=Paris,C=FR",
                 "identity_entropy": 9003},
                name="bank-b",
            )

            import corda_tpu.finance.flows  # noqa: F401 — client-side types
            from corda_tpu.core.contracts import Amount, Issued
            from corda_tpu.core.identity import PartyAndReference

            rpc_a = bank_a.rpc(timeout=60)
            conn_a = rpc_a.start("a", "a")
            rpc_b = bank_b.rpc(timeout=60)
            conn_b = rpc_b.start("a", "a")

            # Discovery: A sees B and the notary through the map.
            me_a = conn_a.proxy.node_info()
            notary = conn_a.proxy.party_from_name("O=Notary Map,L=Zurich,C=CH")
            party_b = conn_a.proxy.party_from_name("O=Bank B,L=Paris,C=FR")
            assert notary is not None, "notary not discovered via network map"
            assert party_b is not None, "peer not discovered via network map"

            # Issue to self, then pay B (sessions + notary across processes).
            fid = conn_a.proxy.start_flow_dynamic(
                "CashIssueFlow", Amount(100_00, "GBP"), b"issue-1", me_a, notary
            )
            conn_a.proxy.flow_result(fid, 120)
            issued_token = Issued(PartyAndReference(me_a, b"issue-1"), "GBP")
            fid = conn_a.proxy.start_flow_dynamic(
                "CashPaymentFlow", Amount(30_00, issued_token), party_b, notary
            )
            conn_a.proxy.flow_result(fid, 120)

            # B's vault sees the payment (broadcast crossed the bridge).
            deadline = time.monotonic() + 60
            states_b = []
            while time.monotonic() < deadline:
                states_b = conn_b.proxy.vault_query("corda_tpu.finance.Cash")
                if states_b:
                    break
                time.sleep(0.5)
            assert states_b, f"Bank B never saw the cash\n{bank_b.log()[-2000:]}"
            rpc_a.close()
            rpc_b.close()
            assert bank_a.terminate() == 0
            assert bank_b.terminate() == 0


@pytest.mark.slow
class TestBridgeRecovery:
    def test_broadcast_survives_peer_restart(self, tmp_path):
        """Kill Bank B, pay it anyway (notarisation completes without it),
        restart B on the same port: the store-and-forward bridge delivers
        the queued broadcast and B's vault shows the cash. Regression for
        the startup race where the P2P pump consumed messages before flow
        handlers were installed (messages were acked into a void)."""
        from corda_tpu.core.contracts import Amount, Issued
        from corda_tpu.core.identity import PartyAndReference
        from corda_tpu.testing.driver import free_port

        certs = str(tmp_path / "shared-certs")
        with driver(str(tmp_path)) as d:
            mapnode = d.start_node(
                {"my_legal_name": "O=Map,L=Z,C=CH", "network_map_service": True,
                 "notary_type": "simple", "identity_entropy": 21,
                 "tls": True, "certificates_dir": certs},
                name="map",
            )
            b_port = free_port()
            common = {
                "network_map": f"127.0.0.1:{mapnode.broker_port}",
                "tls": True, "certificates_dir": certs,
                "rpc_users": [{"username": "u", "password": "p"}],
            }
            bank_a = d.start_node(
                {**common, "my_legal_name": "O=A,L=L,C=GB",
                 "identity_entropy": 22}, name="a")
            bank_b = d.start_node(
                {**common, "my_legal_name": "O=B,L=P,C=FR",
                 "identity_entropy": 23, "broker_port": b_port}, name="b")

            import corda_tpu.finance.flows  # noqa: F401

            conn = bank_a.rpc(timeout=60).start("u", "p")
            me = conn.proxy.node_info()
            notary = conn.proxy.party_from_name("O=Map,L=Z,C=CH")
            party_b = conn.proxy.party_from_name("O=B,L=P,C=FR")
            fid = conn.proxy.start_flow_dynamic(
                "CashIssueFlow", Amount(9000, "GBP"), b"r1", me, notary)
            conn.proxy.flow_result(fid, 120)

            bank_b.kill()  # crash before the payment
            token = Issued(PartyAndReference(me, b"r1"), "GBP")
            fid = conn.proxy.start_flow_dynamic(
                "CashPaymentFlow", Amount(4000, token), party_b, notary)
            conn.proxy.flow_result(fid, 120)

            b2 = d.start_node(
                {**common, "my_legal_name": "O=B,L=P,C=FR",
                 "identity_entropy": 23, "broker_port": b_port}, name="b")
            conn_b = b2.rpc(timeout=60).start("u", "p")
            deadline = time.monotonic() + 60
            states = []
            while time.monotonic() < deadline:
                states = conn_b.proxy.vault_query("corda_tpu.finance.Cash")
                if states:
                    break
                time.sleep(0.5)
            assert states, f"B never recovered the broadcast\n{b2.log()[-1500:]}"

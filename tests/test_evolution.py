"""Schema-evolution serialization tests (reference AMQP evolution +
class-carpenter suites, `core/src/test/.../serialization/`)."""
from dataclasses import dataclass, field

import pytest

from corda_tpu.core.serialization import codec
from corda_tpu.core.serialization.codec import (
    SerializationError,
    corda_serializable,
    deserialize,
    serialize,
)
from corda_tpu.core.serialization.evolution import (
    deserialize_evolvable,
    is_synthesized,
    schema_for,
    serialize_described,
)


def _swap_registration(type_name, new_cls):
    """Point an existing wire name at a different local class (simulates a
    receiver running another version of the type). Returns a restore fn."""
    old_by_name = codec._BY_NAME[type_name]
    old_cls = old_by_name[0]
    old_by_type = codec._BY_TYPE[old_cls]

    fields = [f.name for f in new_cls.__dataclass_fields__.values()]

    def to_dict(obj):
        return {fn: getattr(obj, fn) for fn in fields}

    def from_dict(d):
        return new_cls(**d)

    from_dict.__evolvable__ = True  # as @corda_serializable would mark it
    codec._BY_NAME[type_name] = (new_cls, to_dict, from_dict)
    codec._BY_TYPE[new_cls] = (type_name, to_dict, from_dict)

    def restore():
        codec._BY_NAME[type_name] = old_by_name
        codec._BY_TYPE[old_cls] = old_by_type
        codec._BY_TYPE.pop(new_cls, None)

    return restore


@corda_serializable(name="evo.RoundTrip")
@dataclass(frozen=True)
class RoundTrip:
    a: int
    b: str = "x"


class TestDescribedEnvelope:
    def test_round_trip(self):
        v = RoundTrip(3, "hi")
        blob = serialize_described([v, 7, "s"])
        assert deserialize_evolvable(blob) == [v, 7, "s"]

    def test_schema_for_captures_defaults(self):
        sch = schema_for(RoundTrip)
        assert sch["name"] == "evo.RoundTrip"
        assert sch["fields"] == ["a", "b"]
        assert sch["defaults"] == {"b": "x"}

    def test_standard_format_also_accepted(self):
        v = RoundTrip(1)
        assert deserialize_evolvable(serialize(v)) == v

    def test_nested_schema_collected_from_later_instances(self):
        @corda_serializable(name="evo.Inner")
        @dataclass(frozen=True)
        class Inner:
            n: int

        @corda_serializable(name="evo.Outer")
        @dataclass(frozen=True)
        class Outer:
            inner: object = None

        blob = serialize_described([Outer(None), Outer(Inner(5))])
        schemas, _ = codec._decode(blob, 3)
        assert "evo.Inner" in schemas and "evo.Outer" in schemas


class TestEvolution:
    def test_wire_extra_field_dropped(self):
        """Sender newer (has field c); receiver's class lacks it."""

        @corda_serializable(name="evo.Widen")
        @dataclass(frozen=True)
        class WidenV2:
            a: int
            c: int = 9

        blob = serialize(WidenV2(5, 6))

        @dataclass(frozen=True)
        class WidenV1:
            a: int

        restore = _swap_registration("evo.Widen", WidenV1)
        try:
            got = deserialize_evolvable(blob)
            assert got == WidenV1(5)
            # strict path must keep rejecting it
            with pytest.raises(SerializationError):
                deserialize(blob)
        finally:
            restore()

    def test_wire_missing_field_filled_from_local_default(self):
        """Sender older; receiver's class adds a defaulted field."""

        @corda_serializable(name="evo.Narrow")
        @dataclass(frozen=True)
        class NarrowV1:
            a: int

        blob = serialize(NarrowV1(5))

        @dataclass(frozen=True)
        class NarrowV2:
            a: int
            added: str = "default!"
            lst: tuple = field(default_factory=tuple)

        restore = _swap_registration("evo.Narrow", NarrowV2)
        try:
            got = deserialize_evolvable(blob)
            assert got == NarrowV2(5, "default!", ())
        finally:
            restore()

    def test_wire_missing_field_no_default_fails(self):
        @corda_serializable(name="evo.Hard")
        @dataclass(frozen=True)
        class HardV1:
            a: int

        blob = serialize(HardV1(5))

        @dataclass(frozen=True)
        class HardV2:
            a: int
            required: int  # no default anywhere

        restore = _swap_registration("evo.Hard", HardV2)
        try:
            with pytest.raises(SerializationError, match="no default"):
                deserialize_evolvable(blob)
        finally:
            restore()


class TestCustomAdapterTypes:
    def test_renamed_wire_fields_decode_via_adapter(self):
        """Custom adapters may rename wire fields; the evolvable path must
        use their from_dict, not dataclass field-matching."""
        from corda_tpu.rpc.ops import StateMachineInfo

        v = StateMachineInfo("f1", "Flow", False)
        assert deserialize_evolvable(serialize(v)) == v


class TestCarpenter:
    def test_unknown_type_synthesized(self):
        @corda_serializable(name="evo.Foreign")
        @dataclass(frozen=True)
        class Foreign:
            x: int
            y: str

        blob = serialize(Foreign(1, "two"))
        # simulate a receiver that has never seen the type
        del codec._BY_NAME["evo.Foreign"]
        del codec._BY_TYPE[Foreign]
        got = deserialize_evolvable(blob)
        assert is_synthesized(got)
        assert got.x == 1 and got.y == "two"
        # carpenter registration makes it re-serializable, byte-compatibly
        assert serialize(got) == blob
        # and a second decode now uses the synthesized class
        again = deserialize_evolvable(blob)
        assert again == got
        # but the strict (consensus) whitelist must NOT have been widened
        with pytest.raises(SerializationError, match="whitelist"):
            deserialize(blob)

    def test_unknown_type_strict_mode_rejects(self):
        @corda_serializable(name="evo.Foreign2")
        @dataclass(frozen=True)
        class Foreign2:
            x: int

        blob = serialize(Foreign2(1))
        del codec._BY_NAME["evo.Foreign2"]
        del codec._BY_TYPE[Foreign2]
        with pytest.raises(SerializationError, match="whitelist"):
            deserialize_evolvable(blob, synthesize_unknown=False)

    def test_bad_field_name_rejected(self):
        # OBJ with a non-identifier field name must not reach make_dataclass
        out = bytearray(codec._MAGIC)
        out.append(8)  # _OBJ
        name = b"evo.Nasty"
        out.append(len(name))
        out.extend(name)
        out.append(1)  # one field
        fn = b"not an ident"
        out.append(len(fn))
        out.extend(fn)
        out.append(0)  # NULL value
        with pytest.raises(SerializationError, match="bad field name"):
            deserialize_evolvable(bytes(out))


class TestConsensusPathUnchanged:
    def test_strict_bytes_stable(self):
        v = RoundTrip(3, "hi")
        blob = serialize(v)
        assert deserialize(blob) == v
        # described payload embeds the identical value encoding
        described = serialize_described(v)
        assert described.endswith(blob[len(codec._MAGIC):])

"""Flow framework + node runtime tests.

Layer parity: reference `node/src/test/.../statemachine/FlowFrameworkTests.kt`
(session handshake, responder spawn, errors), checkpoint restore semantics
(`StateMachineManager.kt:227-275`), and `NotaryServiceTests.kt` /
FinalityFlow end-to-end over MockNetwork.
"""
from dataclasses import dataclass, field as dc_field
from typing import List

import pytest

from corda_tpu.core.contracts import (
    Command,
    Contract,
    ContractState,
    TransactionState,
    TransactionVerificationError,
    TypeOnlyCommandData,
    contract,
)
from corda_tpu.core.flows import (
    FinalityFlow,
    FlowException,
    FlowLogic,
    initiated_by,
    initiating_flow,
)
from corda_tpu.core.identity import Party
from corda_tpu.core.serialization.codec import corda_serializable
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.node.notary import NotaryException
from corda_tpu.testing import MockNetwork


# ---------------------------------------------------------------------------
# Test states/contracts
# ---------------------------------------------------------------------------

@contract(name="OwnedContract")
class OwnedContract(Contract):
    def verify(self, tx) -> None:
        pass


@corda_serializable
@dataclass(frozen=True)
class OwnedState(ContractState):
    owner: Party = None
    value: int = 0
    contract_name = "OwnedContract"

    @property
    def participants(self) -> List:
        return [self.owner]


@corda_serializable
@dataclass(frozen=True)
class MoveCmd(TypeOnlyCommandData):
    pass


# ---------------------------------------------------------------------------
# Simple protocol flows
# ---------------------------------------------------------------------------

@initiating_flow
class PingFlow(FlowLogic):
    def __init__(self, party):
        self.party = party

    def call(self):
        answer = yield self.send_and_receive(self.party, b"ping", bytes)
        return answer


@initiated_by(PingFlow)
class PongFlow(FlowLogic):
    def __init__(self, counterparty):
        self.counterparty = counterparty

    def call(self):
        msg = yield self.receive(self.counterparty, bytes)
        assert msg == b"ping"
        yield self.send(self.counterparty, b"pong")


@initiating_flow
class TwoSendFlow(FlowLogic):
    """Two sends then a receive — exercises outbox buffering + flush."""

    def __init__(self, party):
        self.party = party

    def call(self):
        yield self.send(self.party, 40)
        yield self.send(self.party, 2)
        total = yield self.receive(self.party, int)
        return total


@initiated_by(TwoSendFlow)
class SumResponder(FlowLogic):
    def __init__(self, counterparty):
        self.counterparty = counterparty

    def call(self):
        a = yield self.receive(self.counterparty, int)
        b = yield self.receive(self.counterparty, int)
        yield self.send(self.counterparty, a + b)


@initiating_flow
class BadTypeFlow(FlowLogic):
    def __init__(self, party):
        self.party = party

    def call(self):
        # responder sends bytes; we demand an int -> FlowException
        answer = yield self.send_and_receive(self.party, b"ping", int)
        return answer


@initiated_by(BadTypeFlow)
class BadTypeResponder(FlowLogic):
    def __init__(self, counterparty):
        self.counterparty = counterparty

    def call(self):
        _ = yield self.receive(self.counterparty, bytes)
        yield self.send(self.counterparty, b"not-an-int")


@initiating_flow
class FailingResponderInitiator(FlowLogic):
    def __init__(self, party):
        self.party = party

    def call(self):
        answer = yield self.send_and_receive(self.party, b"die", bytes)
        return answer


@initiated_by(FailingResponderInitiator)
class FailingResponder(FlowLogic):
    def __init__(self, counterparty):
        self.counterparty = counterparty

    def call(self):
        _ = yield self.receive(self.counterparty, bytes)
        raise FlowException("I refuse")


class TestFlowFramework:
    def setup_method(self):
        self.net = MockNetwork()
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.bob = self.net.create_node("O=Bob,L=New York,C=US")

    def teardown_method(self):
        self.net.stop_nodes()

    def test_ping_pong(self):
        handle = self.alice.start_flow(PingFlow(self.bob.info), self.bob.info)
        self.net.run_network()
        assert handle.result.result(timeout=1) == b"pong"
        # both sides finished; no checkpoints left behind
        assert self.alice.checkpoint_storage.count() == 0
        assert self.bob.checkpoint_storage.count() == 0

    def test_buffered_sends_flush_on_confirm(self):
        handle = self.alice.start_flow(TwoSendFlow(self.bob.info), self.bob.info)
        self.net.run_network()
        assert handle.result.result(timeout=1) == 42

    def test_wrong_payload_type_raises(self):
        handle = self.alice.start_flow(BadTypeFlow(self.bob.info), self.bob.info)
        self.net.run_network()
        with pytest.raises(FlowException, match="expected int"):
            handle.result.result(timeout=1)

    def test_responder_flow_exception_propagates(self):
        handle = self.alice.start_flow(
            FailingResponderInitiator(self.bob.info), self.bob.info
        )
        self.net.run_network()
        with pytest.raises(FlowException, match="I refuse"):
            handle.result.result(timeout=1)

    def test_no_responder_registered_rejects(self):
        @initiating_flow
        class Orphan(FlowLogic):
            def __init__(self, party):
                self.party = party

            def call(self):
                answer = yield self.send_and_receive(self.party, b"x", bytes)
                return answer

        handle = self.alice.start_flow(Orphan(self.bob.info), self.bob.info)
        self.net.run_network()
        with pytest.raises(FlowException, match="no flow registered"):
            handle.result.result(timeout=1)


# ---------------------------------------------------------------------------
# Checkpoint restore
# ---------------------------------------------------------------------------

@initiating_flow
class WaitForTxFlow(FlowLogic):
    def __init__(self, tx_id):
        self.tx_id = tx_id

    def call(self):
        stx = yield self.wait_for_ledger_commit(self.tx_id)
        return stx.id


class TestCheckpointRestore:
    def test_wait_for_ledger_commit_survives_restart(self, tmp_path):
        db = str(tmp_path / "node.db")
        net = MockNetwork()
        node = net.create_node("O=Restart,L=Oslo,C=NO", db_path=db, entropy=77)

        # Build a tx the flow will wait for (notary field set but unused:
        # no inputs, so no notarisation needed).
        b = TransactionBuilder(notary=node.info)
        b.add_output_state(OwnedState(owner=node.info, value=1))
        b.add_command(MoveCmd(), node.info.owning_key)
        stx = node.services.sign_initial_transaction(b)

        handle = node.start_flow(WaitForTxFlow(stx.id), stx.id)
        assert not handle.result.done()
        assert node.checkpoint_storage.count() == 1

        node.stop()  # crash before the tx commits

        node2 = net.create_node("O=Restart,L=Oslo,C=NO", db_path=db, entropy=77)
        assert node2.checkpoint_storage.count() == 1
        restored = [f for f in node2.smm.flows.values() if not f.done]
        assert len(restored) == 1

        node2.services.record_transactions([stx])
        assert restored[0].result.result(timeout=1) == stx.id
        assert node2.checkpoint_storage.count() == 0
        node2.stop()

    def test_incremental_checkpoints_survive_restart(self, tmp_path):
        """The production fast path (dev_checkpoint_check=False) writes
        header-once + appended io entries + a session blob instead of one
        full blob per step; a restart must restore identically."""
        db = str(tmp_path / "inc.db")
        net = MockNetwork()
        node = net.create_node(
            "O=Inc,L=Oslo,C=NO", db_path=db, entropy=91,
            dev_checkpoint_check=False,
        )
        assert node.smm.dev_checkpoint_check is False

        b = TransactionBuilder(notary=node.info)
        b.add_output_state(OwnedState(owner=node.info, value=5))
        b.add_command(MoveCmd(), node.info.owning_key)
        stx = node.services.sign_initial_transaction(b)

        handle = node.start_flow(WaitForTxFlow(stx.id), stx.id)
        assert not handle.result.done()
        assert node.checkpoint_storage.count() == 1
        # the fast path must not have written a legacy full-blob row
        assert node.database.query("SELECT COUNT(*) FROM checkpoints")[0][0] == 0
        assert node.database.query("SELECT COUNT(*) FROM cp_header")[0][0] == 1

        node.stop()

        node2 = net.create_node(
            "O=Inc,L=Oslo,C=NO", db_path=db, entropy=91,
            dev_checkpoint_check=False,
        )
        restored = [f for f in node2.smm.flows.values() if not f.done]
        assert len(restored) == 1
        node2.services.record_transactions([stx])
        assert restored[0].result.result(timeout=1) == stx.id
        assert node2.checkpoint_storage.count() == 0
        node2.stop()

    def test_incremental_supersedes_legacy_row(self):
        """A flow that checkpointed as a full legacy blob (dev mode) and
        then progresses incrementally must NOT resurrect the stale legacy
        blob on restart (round-3 review finding): the first incremental
        write backfills everything and deletes the legacy row."""
        from corda_tpu.core.serialization.codec import deserialize, serialize
        from corda_tpu.node.database import CheckpointStorage, NodeDatabase

        db = NodeDatabase(":memory:")
        cs = CheckpointStorage(db)
        stale = {
            "flow_id": "f1", "flow_name": "X", "args": [], "kwargs": {},
            "is_responder": False, "io_log": [b"old"],
            "sessions": [], "session_keys": {}, "session_owner_flows": {},
        }
        cs.put("f1", serialize(stale))
        header = {
            "flow_id": "f1", "flow_name": "X", "args": [], "kwargs": {},
            "is_responder": False,
        }
        sessions = {
            "sessions": [], "session_keys": {"k": "s1"},
            "session_owner_flows": {},
        }
        cs.put_incremental(
            "f1", serialize(header),
            [(0, b"old"), (1, b"new")], serialize(sessions),
        )
        assert cs.count() == 1
        blobs = dict(cs.all_checkpoints())
        state = deserialize(blobs["f1"])
        assert state["io_log"] == [b"old", b"new"]
        assert state["session_keys"] == {"k": "s1"}
        # legacy row is gone
        assert db.query("SELECT COUNT(*) FROM checkpoints")[0][0] == 0

    def test_responder_restore_mid_session(self, tmp_path):
        db = str(tmp_path / "bob.db")
        net = MockNetwork()
        alice = net.create_node("O=Alice,L=London,C=GB")
        bob = net.create_node("O=Bob,L=New York,C=US", db_path=db, entropy=88)

        handle = alice.start_flow(TwoSendFlow(bob.info), bob.info)
        # Deliver only the SessionInit: bob's responder consumes 40, parks
        # for the second int (still in alice's outbox, flushed on confirm).
        net.pump()
        assert bob.checkpoint_storage.count() == 1

        bob.stop()  # crash with the responder parked mid-session

        bob2 = net.create_node("O=Bob,L=New York,C=US", db_path=db, entropy=88)
        restored = [f for f in bob2.smm.flows.values() if not f.done]
        assert len(restored) == 1

        net.run_network()  # confirm reaches alice; 2 flows in; reply flows out
        assert handle.result.result(timeout=1) == 42
        assert bob2.checkpoint_storage.count() == 0
        bob2.stop()
        alice.stop()


# ---------------------------------------------------------------------------
# Notarisation + finality
# ---------------------------------------------------------------------------

class TestNotaryAndFinality:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.bob = self.net.create_node("O=Bob,L=New York,C=US")

    def teardown_method(self):
        self.net.stop_nodes()

    def _issue(self, node, value=100):
        """Self-issue a state on `node` (no inputs -> no notarisation)."""
        b = TransactionBuilder(notary=self.notary.info)
        b.add_output_state(OwnedState(owner=node.info, value=value))
        b.add_command(MoveCmd(), node.info.owning_key)
        return node.services.sign_initial_transaction(b)

    def _move(self, node, input_ref, new_owner):
        b = TransactionBuilder(notary=self.notary.info)
        b.add_input_state(input_ref)
        b.add_output_state(
            OwnedState(owner=new_owner.info, value=input_ref.state.data.value)
        )
        b.add_command(MoveCmd(), node.info.owning_key)
        return node.services.sign_initial_transaction(b)

    def test_finality_issue_and_move(self):
        issue_stx = self._issue(self.alice)
        h1 = self.alice.start_flow(FinalityFlow(issue_stx), issue_stx)
        self.net.run_network()
        h1.result.result(timeout=1)
        # Alice's vault has the issued state.
        states = self.alice.services.vault_service.unconsumed_states(
            "OwnedContract"
        )
        assert len(states) == 1

        move_stx = self._move(self.alice, issue_stx.tx.out_ref(0), self.bob)
        h2 = self.alice.start_flow(FinalityFlow(move_stx), move_stx)
        self.net.run_network()
        h2.result.result(timeout=1)

        # Notary signed; bob received and recorded the tx + its dependency.
        assert self.bob.services.validated_transactions.get(move_stx.id) is not None
        assert self.bob.services.validated_transactions.get(issue_stx.id) is not None
        bob_states = self.bob.services.vault_service.unconsumed_states(
            "OwnedContract"
        )
        assert len(bob_states) == 1
        assert bob_states[0].state.data.owner == self.bob.info
        # Alice's copy is consumed now.
        assert (
            self.alice.services.vault_service.unconsumed_states("OwnedContract")
            == []
        )

    def test_double_spend_rejected(self):
        issue_stx = self._issue(self.alice)
        h1 = self.alice.start_flow(FinalityFlow(issue_stx), issue_stx)
        self.net.run_network()
        h1.result.result(timeout=1)

        ref = issue_stx.tx.out_ref(0)
        move1 = self._move(self.alice, ref, self.bob)
        h2 = self.alice.start_flow(FinalityFlow(move1), move1)
        self.net.run_network()
        h2.result.result(timeout=1)

        move2 = self._move(self.alice, ref, self.alice)  # spend again
        h3 = self.alice.start_flow(FinalityFlow(move2), move2)
        self.net.run_network()
        with pytest.raises(NotaryException, match="notary error"):
            h3.result.result(timeout=1)

    def test_non_validating_notary(self):
        net2 = MockNetwork()
        notary = net2.create_notary_node(
            "O=SimpleNotary,L=Oslo,C=NO", validating=False
        )
        alice = net2.create_node("O=Alice2,L=London,C=GB")

        b = TransactionBuilder(notary=notary.info)
        b.add_output_state(OwnedState(owner=alice.info, value=5))
        b.add_command(MoveCmd(), alice.info.owning_key)
        issue = alice.services.sign_initial_transaction(b)
        h1 = alice.start_flow(FinalityFlow(issue), issue)
        net2.run_network()
        h1.result.result(timeout=1)

        b2 = TransactionBuilder(notary=notary.info)
        b2.add_input_state(issue.tx.out_ref(0))
        b2.add_output_state(OwnedState(owner=alice.info, value=5))
        b2.add_command(MoveCmd(), alice.info.owning_key)
        move = alice.services.sign_initial_transaction(b2)
        h2 = alice.start_flow(FinalityFlow(move), move)
        net2.run_network()
        h2.result.result(timeout=1)  # tear-off notarisation succeeded

        # Privacy regression (advisor, round 1): the client tear-off must
        # hide outputs/commands from the notary while revealing all inputs,
        # the time window, and the notary identity.
        from corda_tpu.node.notary import notary_tearoff_filter

        ftx = move.tx.build_filtered_transaction(notary_tearoff_filter)
        ftx.verify()
        ftx.check_all_inputs_revealed()
        assert ftx.inputs == list(move.tx.inputs)
        assert ftx.outputs == []
        assert ftx.commands == []
        net2.stop_nodes()


class TestMultiHopResolution:
    """Regression: a dependency chain needing multiple fetch rounds must not
    reuse the completed fetch session (session-per-exchange semantics)."""

    def test_three_hop_chain_reaches_third_party(self):
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        alice = net.create_node("O=Alice,L=London,C=GB")
        bob = net.create_node("O=Bob,L=New York,C=US")
        charlie = net.create_node("O=Charlie,L=Paris,C=FR")

        def issue(node):
            b = TransactionBuilder(notary=notary.info)
            b.add_output_state(OwnedState(owner=node.info, value=7))
            b.add_command(MoveCmd(), node.info.owning_key)
            return node.services.sign_initial_transaction(b)

        def move(node, ref, to):
            b = TransactionBuilder(notary=notary.info)
            b.add_input_state(ref)
            b.add_output_state(OwnedState(owner=to.info, value=7))
            b.add_command(MoveCmd(), node.info.owning_key)
            return node.services.sign_initial_transaction(b)

        stx0 = issue(alice)
        h0 = alice.start_flow(FinalityFlow(stx0), stx0)
        net.run_network()
        h0.result.result(timeout=1)

        stx1 = move(alice, stx0.tx.out_ref(0), bob)
        h1 = alice.start_flow(FinalityFlow(stx1), stx1)
        net.run_network()
        h1.result.result(timeout=1)

        # Bob moves to Charlie: Charlie must resolve a 2-deep chain from Bob
        # (two FetchTransactionsFlow rounds over two distinct sessions).
        stx2 = move(bob, stx1.tx.out_ref(0), charlie)
        h2 = bob.start_flow(FinalityFlow(stx2), stx2)
        net.run_network()
        h2.result.result(timeout=1)

        assert charlie.services.validated_transactions.get(stx2.id) is not None
        assert charlie.services.validated_transactions.get(stx1.id) is not None
        assert charlie.services.validated_transactions.get(stx0.id) is not None
        states = charlie.services.vault_service.unconsumed_states("OwnedContract")
        assert len(states) == 1 and states[0].state.data.owner == charlie.info
        net.stop_nodes()


class TestDeepBackchainResolution:
    """The framework's 'long-context' axis (SURVEY §5): transaction
    back-chains resolved recursively. A 40-deep chain bounced between
    two parties must resolve completely for a third party that has seen
    NONE of it; and the BFS transaction-count bound must refuse a chain
    that exceeds it rather than downloading unboundedly."""

    def _chain(self, net, notary, alice, bob, depth):
        b = TransactionBuilder(notary=notary.info)
        b.add_output_state(OwnedState(owner=alice.info, value=1))
        b.add_command(MoveCmd(), alice.info.owning_key)
        stx = alice.services.sign_initial_transaction(b)
        h = alice.start_flow(FinalityFlow(stx), stx)
        net.run_network()
        h.result.result(timeout=5)
        owner, other = alice, bob
        for _ in range(depth):
            b = TransactionBuilder(notary=notary.info)
            b.add_input_state(stx.tx.out_ref(0))
            b.add_output_state(OwnedState(owner=other.info, value=1))
            b.add_command(MoveCmd(), owner.info.owning_key)
            nxt = owner.services.sign_initial_transaction(b)
            h = owner.start_flow(FinalityFlow(nxt), nxt)
            net.run_network()
            h.result.result(timeout=5)
            stx, (owner, other) = nxt, (other, owner)
        return stx, owner

    def test_forty_deep_chain_resolves_for_stranger(self):
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        alice = net.create_node("O=DeepAlice,L=London,C=GB")
        bob = net.create_node("O=DeepBob,L=New York,C=US")
        stx, owner = self._chain(net, notary, alice, bob, depth=40)

        charlie = net.create_node("O=DeepCharlie,L=Paris,C=FR")
        b = TransactionBuilder(notary=notary.info)
        b.add_input_state(stx.tx.out_ref(0))
        b.add_output_state(OwnedState(owner=charlie.info, value=1))
        b.add_command(MoveCmd(), owner.info.owning_key)
        final = owner.services.sign_initial_transaction(b)
        h = owner.start_flow(FinalityFlow(final), final)
        net.run_network()
        h.result.result(timeout=10)
        # the stranger holds the full 42-tx history and the live state
        assert charlie.services.validated_transactions.get(final.id) is not None
        assert charlie.services.validated_transactions.get(stx.id) is not None
        states = charlie.services.vault_service.unconsumed_states("OwnedContract")
        assert len(states) == 1 and states[0].state.data.owner == charlie.info
        net.stop_nodes()

    def test_transaction_count_bound_refuses_oversized_chain(self, monkeypatch):
        from corda_tpu.core.flows.library import ResolveTransactionsFlow

        net = MockNetwork()
        notary = net.create_notary_node(validating=False)
        alice = net.create_node("O=CapAlice,L=London,C=GB")
        bob = net.create_node("O=CapBob,L=New York,C=US")
        stx, owner = self._chain(net, notary, alice, bob, depth=12)

        monkeypatch.setattr(ResolveTransactionsFlow, "MAX_TRANSACTIONS", 6)
        charlie = net.create_node("O=CapCharlie,L=Paris,C=FR")
        b = TransactionBuilder(notary=notary.info)
        b.add_input_state(stx.tx.out_ref(0))
        b.add_output_state(OwnedState(owner=charlie.info, value=1))
        b.add_command(MoveCmd(), owner.info.owning_key)
        final = owner.services.sign_initial_transaction(b)
        h = owner.start_flow(FinalityFlow(final), final)
        import logging

        # capture charlie's responder-side failure: the refusal must be
        # SPECIFICALLY the graph-size bound, not a broken delivery (the
        # initiator's finality deliberately survives a recipient refusing
        # a broadcast — the tx is already notarised and recorded locally)
        records = []

        class _Trap(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        trap = _Trap()
        logging.getLogger().addHandler(trap)
        try:
            net.run_network()
        finally:
            logging.getLogger().removeHandler(trap)
        h.result.result(timeout=5)  # sender side completed
        assert any("dependency graph exceeded" in m for m in records), (
            records[-5:]
        )
        assert charlie.services.validated_transactions.get(final.id) is None
        net.stop_nodes()


class TestTearOffCompleteness:
    """Regression: a tear-off hiding inputs must not obtain a notary
    signature (hidden inputs would stay spendable: signed double spend)."""

    def test_hidden_input_tear_off_rejected(self):
        from corda_tpu.core.contracts import StateRef, TransactionState
        from corda_tpu.core.transactions.filtered import (
            FilteredTransaction,
            FilteredTransactionVerificationError,
        )

        net = MockNetwork()
        notary = net.create_notary_node(validating=False)
        alice = net.create_node("O=Alice,L=London,C=GB")

        b = TransactionBuilder(notary=notary.info)
        b.add_output_state(OwnedState(owner=alice.info, value=1))
        b.add_output_state(OwnedState(owner=alice.info, value=2))
        b.add_command(MoveCmd(), alice.info.owning_key)
        issue = alice.services.sign_initial_transaction(b)
        h = alice.start_flow(FinalityFlow(issue), issue)
        net.run_network()
        h.result.result(timeout=1)

        b2 = TransactionBuilder(notary=notary.info)
        b2.add_input_state(issue.tx.out_ref(0))
        b2.add_input_state(issue.tx.out_ref(1))
        b2.add_output_state(OwnedState(owner=alice.info, value=3))
        b2.add_command(MoveCmd(), alice.info.owning_key)
        spend = alice.services.sign_initial_transaction(b2)

        # Malicious tear-off: hide the second input.
        hidden_ref = issue.tx.out_ref(1).ref
        ftx = FilteredTransaction.build(
            spend.tx,
            lambda c: not (isinstance(c, StateRef) and c == hidden_ref),
        )
        ftx.verify()  # Merkle proof still holds (inclusion only)...
        with pytest.raises(FilteredTransactionVerificationError, match="reveals 1 of 2"):
            ftx.check_all_inputs_revealed()  # ...but completeness fails
        net.stop_nodes()


class TestPerFlowLogging:
    def test_flow_logger_named_by_id_and_records(self, caplog):
        import logging

        from corda_tpu.core.flows import FlowLogic, startable_by_rpc
        from corda_tpu.testing import MockNetwork

        @startable_by_rpc
        class LoggedFlow(FlowLogic):
            def call(self):
                return 1
                yield  # pragma: no cover

        net = MockNetwork()
        node = net.create_node("O=Logged,L=London,C=GB")
        with caplog.at_level(logging.INFO, logger="corda_tpu.flow"):
            h = node.start_flow(LoggedFlow())
            net.run_network()
            h.result.result(timeout=5)
        records = [
            r for r in caplog.records
            if r.name == f"corda_tpu.flow.{h.flow_id}"
        ]
        assert records and "completed" in records[-1].message
        net.stop_nodes()


class TestDevCheckpointChecker:
    def test_unregistered_flow_warned_at_write_time(self, caplog):
        """A flow whose class is not in the registry checkpoints fine
        byte-wise but could never restore; dev mode logs a loud warning
        at the first suspension instead of a silent restart failure
        (reference dev-mode checkpoint deserializability checker)."""
        import logging

        from corda_tpu.core.flows import FlowLogic
        from corda_tpu.core.flows.api import flow_registry, initiating_flow
        from corda_tpu.testing import MockNetwork

        from corda_tpu.core.crypto.secure_hash import SecureHash
        from corda_tpu.core.flows.api import WaitForLedgerCommit

        @initiating_flow
        class EphemeralFlow(FlowLogic):
            def call(self):
                yield WaitForLedgerCommit(SecureHash.sha256(b"never"))

        net = MockNetwork()
        node = net.create_node("O=Dev,L=London,C=GB")
        # simulate a flow registered in another process only
        name = EphemeralFlow.flow_name()
        del flow_registry[name]
        try:
            with caplog.at_level(logging.WARNING, logger="corda_tpu.flow"):
                node.start_flow(EphemeralFlow())
            assert any(
                "not in the flow registry" in r.message for r in caplog.records
            )
        finally:
            flow_registry[name] = EphemeralFlow
            net.stop_nodes()


# ---------------------------------------------------------------------------
# FinalityFlow restart-restorability (r3 VERDICT #3)
# ---------------------------------------------------------------------------

class TestFinalityFlowRestore:
    """The reference restores ANY checkpointed fiber
    (StateMachineManager.kt:227-241). FinalityFlow is not
    @initiating_flow (its sub-flows open the sessions), so before r4 it
    never entered the flow registry and a node dying inside it could not
    restore — now every FlowLogic subclass registers at class-definition
    time (FlowLogic.__init_subclass__)."""

    def test_finality_flow_is_registered(self):
        from corda_tpu.core.flows.api import flow_registry
        from corda_tpu.core.flows.library import FinalityFlow

        assert flow_registry.get(FinalityFlow.flow_name()) is FinalityFlow

    def test_kill_after_notarise_before_broadcast_restores(self, tmp_path, caplog):
        """Kill the initiating node at the exact seam the r3 MULTICHIP
        artifact warned about: the notary cluster has COMMITTED the spend
        but the initiator has not yet processed the reply (so the
        broadcast to the counterparty never went out). The restored
        FinalityFlow must re-announce its notary session, absorb the
        idempotent re-commit, and finish the broadcast."""
        import logging

        from corda_tpu.core.flows.library import FinalityFlow

        db = str(tmp_path / "alice.db")
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        alice = net.create_node("O=Alice,L=London,C=GB", db_path=db, entropy=31)
        bob = net.create_node("O=Bob,L=New York,C=US")

        # Issue (no inputs -> no notarisation) and finalise so the chain
        # resolves for both the validating notary and bob later.
        b = TransactionBuilder(notary=notary.info)
        b.add_output_state(OwnedState(owner=alice.info, value=9))
        b.add_command(MoveCmd(), alice.info.owning_key)
        issue_stx = alice.services.sign_initial_transaction(b)
        h1 = alice.start_flow(FinalityFlow(issue_stx), issue_stx)
        net.run_network()
        h1.result.result(timeout=1)

        # The move spends the issued state: notarisation required.
        b = TransactionBuilder(notary=notary.info)
        b.add_input_state(issue_stx.tx.out_ref(0))
        b.add_output_state(OwnedState(owner=bob.info, value=9))
        b.add_command(MoveCmd(), alice.info.owning_key)
        move_stx = alice.services.sign_initial_transaction(b)

        with caplog.at_level(logging.WARNING, logger="corda_tpu.flow"):
            alice.start_flow(FinalityFlow(move_stx), move_stx)
        # the r3 artifact's warning must be gone: the checkpoint is
        # restorable because FinalityFlow now registers at import
        assert not any(
            "not in the flow registry" in r.message for r in caplog.records
        )
        assert alice.checkpoint_storage.count() == 1

        # Pump one message at a time until the notary's commit log holds
        # the spend, then crash alice WITHOUT letting her see the reply.
        provider = notary.notary_service.uniqueness_provider
        key = provider._key(move_stx.tx.inputs[0])
        for _ in range(500):
            if provider._map.get(key) is not None:
                break
            assert net.pump(), "network quiesced before the notary committed"
        assert provider._map.get(key) is not None
        assert bob.services.validated_transactions.get(move_stx.id) is None

        alice.stop()  # crash: committed at the notary, never broadcast

        alice2 = net.create_node(
            "O=Alice,L=London,C=GB", db_path=db, entropy=31
        )
        restored = [f for f in alice2.smm.flows.values() if not f.done]
        assert len(restored) == 1
        net.run_network()
        assert restored[0].result.result(timeout=1).id == move_stx.id
        assert alice2.checkpoint_storage.count() == 0

        # bob received the broadcast and recorded the full chain
        assert bob.services.validated_transactions.get(move_stx.id) is not None
        bob_states = bob.services.vault_service.unconsumed_states("OwnedContract")
        assert [s.state.data.value for s in bob_states] == [9]
        net.stop_nodes()

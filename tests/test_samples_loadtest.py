"""Samples + loadtest + webserver + shell + jackson tests."""
import io
import json
import random
import urllib.request

import pytest

from corda_tpu.client.jackson import (
    from_json,
    parse_flow_start,
    to_json,
)
from corda_tpu.core.contracts import Amount, Issued
from corda_tpu.core.crypto import crypto
from corda_tpu.core.identity import Party
from corda_tpu.loadtest import (
    NotaryLoadTest,
    Nodes,
    SelfIssueLoadTest,
    StabilityLoadTest,
    kill_flow_storm,
)
from corda_tpu.rpc.ops import CordaRPCOps
from corda_tpu.samples import attachment_demo, bank_of_corda, notary_demo, trader_demo
from corda_tpu.testing import MockNetwork


class TestSamples:
    def test_trader_demo(self):
        result = trader_demo.main(verbose=False)
        assert result["buyer_paper"] == 1

    def test_notary_demo(self):
        result = notary_demo.main(n_transactions=3, verbose=False)
        assert result["notarised"] == 3
        assert result["double_spend_rejected"]

    def test_bank_of_corda(self):
        result = bank_of_corda.main(verbose=False)
        assert result["issued"] == 1_000_00

    def test_attachment_demo(self):
        result = attachment_demo.main(verbose=False)
        assert result["received"]


class TestLoadtest:
    def _nodes(self, n=3):
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        parties = [
            net.create_node(f"O=Load{i},L=City{i},C=GB") for i in range(n)
        ]
        return Nodes(network=net, notary=notary, nodes=parties)

    def test_self_issue_consistency(self):
        nodes = self._nodes()
        result = SelfIssueLoadTest().run(nodes, iterations=10, parallelism=6)
        assert result.consistent, result.errors
        assert result.commands_executed > 0
        nodes.network.stop_nodes()

    def test_notary_throughput(self):
        nodes = self._nodes()
        result = NotaryLoadTest().run(nodes, iterations=5, parallelism=4)
        assert not result.errors, result.errors
        assert result.commands_per_sec > 0
        nodes.network.stop_nodes()

    def test_committee_consensus_aggregate_path(self):
        """The round-12 committee scenario: a BLS notary committee
        serves blocks with ONE aggregate check each, proven through the
        scenario's own SLO machinery (docs/bls-aggregation.md)."""
        from corda_tpu.loadtest.tests import CommitteeConsensusLoadTest

        nodes = self._nodes(n=1)
        result = CommitteeConsensusLoadTest(n_members=4).run(
            nodes, iterations=2, parallelism=2,
            slos={
                "vote_scheme_bls": {"min": 1},
                "vote_verifies": {"max": 0},
                "agg_checks": {"min": 1},
                "aggregate_speedup": {"min": 1.5},
            },
        )
        assert result.consistent, result.errors
        assert not result.errors, result.errors
        assert result.slo_violations == [], result.slo_violations
        m = result.metrics
        assert m["blocks_notarised"] >= 2
        assert m["naive_votes_avoided"] >= m["agg_checks"] * 3
        nodes.network.stop_nodes()

    def test_stability_under_message_drop(self):
        nodes = self._nodes()
        result = StabilityLoadTest().run(
            nodes, iterations=10, parallelism=4,
            disruptions=[kill_flow_storm(probability=0.3)],
        )
        assert result.consistent, result.errors
        nodes.network.stop_nodes()


class TestJackson:
    def test_roundtrip_party_amount(self):
        kp = crypto.entropy_to_keypair(800)
        party = Party("O=X,L=Y,C=GB", kp.public)
        amount = Amount(100, Issued(party.ref(1), "USD"))
        text = to_json({"party": party, "amount": amount})
        decoded = from_json(text)
        assert decoded["party"] == party
        assert decoded["amount"] == amount

    def test_parse_flow_start_kwargs(self):
        kp = crypto.entropy_to_keypair(801)
        alice = Party("O=Alice,L=London,C=GB", kp.public)
        name, kwargs = parse_flow_start(
            "CashIssueFlow amount: 100 USD, recipient: O=Alice,L=London,C=GB",
            identity_lookup=lambda n: alice if n == alice.name else None,
        )
        assert name == "CashIssueFlow"
        assert kwargs["amount"].quantity == 100_00  # cents
        assert kwargs["recipient"] == alice

    def test_parse_flow_start_positional(self):
        name, args = parse_flow_start("SomeFlow 42, hello, 2.5")
        assert name == "SomeFlow"
        assert args == [42, "hello", 2.5]


class TestWebServer:
    def test_endpoints(self):
        from corda_tpu.webserver import WebServer

        net = MockNetwork()
        node = net.create_node("O=Web,L=London,C=GB")
        ops = CordaRPCOps(node.services, node.smm)
        server = WebServer(ops)
        base = f"http://127.0.0.1:{server.port}"
        try:
            assert urllib.request.urlopen(f"{base}/api/status").read() == b"started"
            info = json.loads(urllib.request.urlopen(f"{base}/api/info").read())
            assert info["name"] == "O=Web,L=London,C=GB"
            # attachment upload + download
            req = urllib.request.Request(
                f"{base}/api/attachments", data=b"some jar", method="POST"
            )
            att = json.loads(urllib.request.urlopen(req).read())
            att_hash = att["id"]["value"]
            got = urllib.request.urlopen(
                f"{base}/api/attachments/{att_hash}"
            ).read()
            assert got == b"some jar"
            # vault is empty (paged shape)
            vault = json.loads(urllib.request.urlopen(f"{base}/api/vault").read())
            assert vault["total"] == 0 and vault["states"] == []
        finally:
            server.stop()
            net.stop_nodes()


class TestShell:
    def test_shell_commands(self):
        from corda_tpu.node.shell import InteractiveShell

        net = MockNetwork()
        net.create_notary_node(validating=True)
        node = net.create_node("O=ShellNode,L=London,C=GB")
        ops = CordaRPCOps(node.services, node.smm)
        out = io.StringIO()
        shell = InteractiveShell(ops, stdout=out, pump=net.run_network)
        shell.onecmd("network")
        assert "ShellNode" in out.getvalue()
        shell.onecmd("flow list")
        shell.onecmd("vault")
        assert shell.onecmd("bye") is True
        net.stop_nodes()


class TestNotariseLatency:
    def test_latency_percentiles(self):
        from corda_tpu.loadtest.latency import measure_notarise_latency

        out = measure_notarise_latency(n_tx=16)
        assert out["n_tx"] == 16
        assert 0 < out["p50_ms"] <= out["p95_ms"]
        assert out["notarisations_per_sec"] > 0

    def test_uniqueness_batch_percentiles(self):
        from corda_tpu.loadtest.latency import measure_uniqueness_batch

        out = measure_uniqueness_batch(n_tx=64)
        assert out["n_tx"] == 64
        assert 0 < out["raft_p50_ms"]
        assert 0 < out["single_p50_ms"]
        assert out["raft_commits_s"] > 0
        assert out["single_commits_s"] > 0

    def test_settlement_burst_feeds_batcher(self):
        """r3 VERDICT #7: a bulk-settlement notarise round must hand the
        notary's cross-transaction batcher a single >= n_signers-item
        flush through the production NotaryFlow path."""
        from corda_tpu.loadtest.latency import measure_notarise_burst

        out = measure_notarise_burst(n_signers=48, n_tx=2)
        assert out["batcher_largest_batch"] >= 49  # 48 signers + bank
        assert out["batcher_flushes"] >= 1
        assert out["batcher_items"] >= 2 * 49
        assert out["sigs_per_sec"] > 0

    def test_settlement_burst_rejects_tampered_signer(self, monkeypatch):
        """The NOTARY-side batcher path must keep exact per-signature
        accept/reject semantics: one corrupt settlement signature fails
        notarisation (the client's own pre-check is disabled so the bad
        signature actually reaches the notary)."""
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.core.contracts.structures import StateAndRef, StateRef
        from corda_tpu.core.crypto import crypto
        from corda_tpu.core.crypto.schemes import EDDSA_ED25519_SHA512
        from corda_tpu.core.crypto.signing import DigitalSignatureWithKey
        from corda_tpu.core.transactions import TransactionBuilder
        from corda_tpu.finance.cash import CashCommand, CashState
        from corda_tpu.node.notary import NotaryClientFlow, NotaryException
        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        bank = net.create_node("O=TamperBank,L=London,C=GB")
        token = Issued(bank.info.ref(1), "USD")
        signers = [
            crypto.generate_keypair(EDDSA_ED25519_SHA512) for _ in range(40)
        ]
        b = TransactionBuilder(notary=notary.info)
        b.add_output_state(CashState(amount=Amount(5, token), owner=bank.info))
        b.add_command(CashCommand.Issue(), bank.info.owning_key)
        issue = bank.services.sign_initial_transaction(b)
        bank.services.record_transactions([issue])

        ref = StateRef(issue.id, 0)
        ts = bank.services.load_state(ref)
        b = TransactionBuilder(notary=notary.info)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(CashState(amount=Amount(5, token), owner=bank.info))
        b.add_command(
            CashCommand.Move(), bank.info.owning_key,
            *[kp.public for kp in signers],
        )
        stx = bank.services.sign_initial_transaction(b)
        sigs = [
            DigitalSignatureWithKey(
                bytes=crypto.do_sign(kp.private, stx.id.bytes), by=kp.public
            )
            for kp in signers
        ]
        sigs[17] = DigitalSignatureWithKey(
            bytes=b"\x00" * 64, by=signers[17].public
        )
        stx = stx.with_additional_signatures(sigs)

        from corda_tpu.core.flows import FlowException
        from corda_tpu.core.transactions.signed import SignedTransaction

        monkeypatch.setattr(
            SignedTransaction, "verify_signatures_except",
            lambda self, *a: None,
        )
        h = bank.start_flow(NotaryClientFlow(stx), stx)
        net.run_network()
        with pytest.raises(FlowException, match="invalid signature"):
            h.result.result(timeout=60)
        net.stop_nodes()


class TestNotaryDemoClusterModes:
    def test_raft_mode(self):
        result = notary_demo.main(n_transactions=2, verbose=False, mode="raft")
        assert result["notarised"] == 2
        assert result["double_spend_rejected"] is True

    def test_bft_mode(self):
        result = notary_demo.main(n_transactions=2, verbose=False, mode="bft")
        assert result["notarised"] == 2
        assert result["double_spend_rejected"] is True

"""Bank-side flow hot path (ISSUE 15, docs/perf-system.md round 20):
multi-lane flow executor, indexed vault selection, group-committed
checkpoints — plus the gate coverage for their bench keys.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from corda_tpu.core.contracts import Amount
from corda_tpu.core.contracts.amount import Issued
from corda_tpu.core.flows import FlowLogic
from corda_tpu.finance.cash import CashCommand, CashState
from corda_tpu.finance.flows import CashIssueFlow, CashPaymentFlow
from corda_tpu.testing.mocknetwork import MockNetwork


class WaitForTxFlow(FlowLogic):
    def __init__(self, tx_id):
        self.tx_id = tx_id

    def call(self):
        stx = yield self.wait_for_ledger_commit(self.tx_id)
        return stx.id


# ---------------------------------------------------------------------------
# FlowLaneExecutor units
# ---------------------------------------------------------------------------

class TestFlowLaneExecutor:
    def test_lane_key_strips_hint_prefix_and_session_ordinal(self):
        from corda_tpu.node.flowlanes import lane_key

        assert lane_key("h:abc-123:0") == "abc-123"
        assert lane_key("t:w0-deadbeef:7") == "w0-deadbeef"
        assert lane_key("bare") == "bare"

    def test_affinity_same_key_same_lane_and_fifo_order(self):
        from corda_tpu.node.flowlanes import FlowLaneExecutor

        ex = FlowLaneExecutor(3, name="t")
        try:
            assert ex.lane_of("flow-a") == ex.lane_of("flow-a")
            seen = {}
            done = threading.Event()
            total = 60

            def task(key, i):
                seen.setdefault(key, []).append(i)
                if sum(len(v) for v in seen.values()) == total:
                    done.set()

            for i in range(total):
                key = f"flow-{i % 3}"
                ex.submit(key, lambda k=key, i=i: task(k, i))
            assert done.wait(timeout=10)
            # per-key order preserved (same key -> same FIFO lane)
            for key, order in seen.items():
                assert order == sorted(order), (key, order)
        finally:
            ex.stop(drain=True)

    def test_submit_blocks_at_depth_then_resumes(self):
        from corda_tpu.node.flowlanes import FlowLaneExecutor

        ex = FlowLaneExecutor(1, name="t", depth=2)
        gate = threading.Event()
        try:
            ex.submit("k", gate.wait)  # occupies the lane
            time.sleep(0.05)
            ex.submit("k", lambda: None)
            ex.submit("k", lambda: None)  # queue now at depth

            t0 = time.perf_counter()
            unblocked = threading.Event()

            def submitter():
                ex.submit("k", lambda: None)
                unblocked.set()

            t = threading.Thread(target=submitter, daemon=True,
                                 name="lane-submitter")
            t.start()
            assert not unblocked.wait(timeout=0.2), (
                "submit must block while the lane is at depth"
            )
            gate.set()
            assert unblocked.wait(timeout=5)
            assert time.perf_counter() - t0 >= 0.2
        finally:
            gate.set()
            ex.stop(drain=True)

    def test_stop_drain_runs_queued_and_refuses_new(self):
        from corda_tpu.node.flowlanes import FlowLaneExecutor

        ex = FlowLaneExecutor(2, name="t")
        ran = []
        for i in range(20):
            ex.submit(f"k{i % 4}", lambda i=i: ran.append(i))
        assert ex.stop(drain=True, timeout=10)
        assert len(ran) == 20
        with pytest.raises(RuntimeError):
            ex.submit("k", lambda: None)

    def test_error_in_continuation_keeps_lane_alive(self):
        from corda_tpu.node.flowlanes import FlowLaneExecutor

        ex = FlowLaneExecutor(1, name="t")
        done = threading.Event()
        try:
            ex.submit("k", lambda: 1 / 0)
            ex.submit("k", done.set)
            assert done.wait(timeout=5)
            assert ex.stats()["errors"] == 1
        finally:
            ex.stop(drain=True)


# ---------------------------------------------------------------------------
# Laned dispatch on the broker transport (the production path)
# ---------------------------------------------------------------------------

def _broker_trio(broker):
    from corda_tpu.node.network import BrokerMessagingService
    from corda_tpu.node.node import AbstractNode, NodeConfiguration

    nodes = []

    def mk(name, entropy, notary_type=None, **cfg):
        node = AbstractNode(
            NodeConfiguration(
                my_legal_name=name, identity_entropy=entropy,
                notary_type=notary_type, **cfg,
            ),
            messaging_factory=lambda me: BrokerMessagingService(broker, me),
            broker=broker,
        )
        nodes.append(node)
        return node

    notary = mk("O=FPNotary,L=Zurich,C=CH", 71, "validating")
    bank_a = mk("O=FPBankA,L=London,C=GB", 72)
    bank_b = mk("O=FPBankB,L=Paris,C=FR", 73)
    for n in nodes:
        n.start()
    for x in nodes:
        for y in nodes:
            if x is not y:
                x.register_peer(y.info, y.config.advertised_services)
    return notary, bank_a, bank_b, nodes


def _run_pairs(bank_a, bank_b, notary, pairs, threads=2):
    token = Issued(bank_a.info.ref(1), "USD")
    errors = []

    def worker(count):
        try:
            for _ in range(count):
                h = bank_a.start_flow(
                    CashIssueFlow(Amount(100, "USD"), b"\x01", bank_a.info,
                                  notary.info),
                    Amount(100, "USD"), b"\x01", bank_a.info, notary.info,
                )
                h.result.result(timeout=60)
                h = bank_a.start_flow(
                    CashPaymentFlow(Amount(100, token), bank_b.info,
                                    notary.info),
                    Amount(100, token), bank_b.info, notary.info,
                )
                h.result.result(timeout=60)
        except BaseException as exc:
            errors.append(exc)

    per = pairs // threads
    counts = [per + (1 if i < pairs % threads else 0) for i in range(threads)]
    ts = [
        threading.Thread(target=worker, args=(c,), daemon=True,
                         name=f"fp-pair-{i}")
        for i, c in enumerate(counts) if c
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errors, errors[0]


class TestLanedBrokerDispatch:
    def test_laned_issue_pay_pairs_complete_and_ack(self, monkeypatch):
        from corda_tpu.messaging import Broker

        monkeypatch.setenv("CORDA_TPU_FLOW_LANES", "4")
        broker = Broker()
        notary, bank_a, bank_b, nodes = _broker_trio(broker)
        try:
            assert bank_a.network._lanes is not None
            assert bank_a.network._lanes.n_lanes == 4
            _run_pairs(bank_a, bank_b, notary, pairs=6, threads=2)
            # every pair landed at the counterparty
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if len(bank_b.services.vault_service.unconsumed_states()) >= 6:
                    break
                time.sleep(0.05)
            assert len(
                bank_b.services.vault_service.unconsumed_states()
            ) == 6
            # continuations really ran on lanes, and every laned message
            # was ACKED after processing (no unacked/undelivered leak)
            assert bank_a.network._lanes.stats()["dispatched"] > 0
            assert notary.network._lanes.stats()["dispatched"] > 0
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                depths = [n.network.queue_depth() for n in nodes]
                if all(d == 0 for d in depths):
                    break
                time.sleep(0.05)
            assert all(n.network.queue_depth() == 0 for n in nodes)
        finally:
            for n in nodes:
                n.stop()
            broker.close()

    def test_lanes_zero_restores_on_pump_dispatch(self, monkeypatch):
        from corda_tpu.messaging import Broker

        monkeypatch.setenv("CORDA_TPU_FLOW_LANES", "0")
        broker = Broker()
        notary, bank_a, bank_b, nodes = _broker_trio(broker)
        try:
            assert bank_a.network._lanes is None  # today's inline path
            _run_pairs(bank_a, bank_b, notary, pairs=2, threads=1)
        finally:
            for n in nodes:
                n.stop()
            broker.close()

    def test_group_commit_armed_on_async_transport_only(self, monkeypatch):
        from corda_tpu.messaging import Broker

        broker = Broker()
        notary, bank_a, _bank_b, nodes = _broker_trio(broker)
        try:
            # async transport: group commit armed by default
            assert bank_a.checkpoint_storage.group_commit_stats is not None
        finally:
            for n in nodes:
                n.stop()
            broker.close()

        monkeypatch.setenv("CORDA_TPU_CP_GROUP_COMMIT", "0")
        broker = Broker()
        notary, bank_a, _bank_b, nodes = _broker_trio(broker)
        try:
            assert bank_a.checkpoint_storage.group_commit_stats is None
        finally:
            for n in nodes:
                n.stop()
            broker.close()

    def test_mocknetwork_stays_per_op_checkpoints(self):
        net = MockNetwork()
        try:
            node = net.create_node("O=PerOp,L=Oslo,C=NO")
            assert node.checkpoint_storage.group_commit_stats is None
        finally:
            net.stop_nodes()


# ---------------------------------------------------------------------------
# MockNetwork: inline by default (determinism pin), lanes opt-in
# ---------------------------------------------------------------------------

class TestMockNetworkLanes:
    def test_default_transport_is_inline_and_deterministic(self):
        """Determinism pin: the default in-memory transport has NO lane
        executor — session handlers run inline on the pumping thread,
        so the existing tier-1 flow-ordering suites (tests/test_flows.py
        et al.) run unmodified under the default config."""
        net = MockNetwork()
        try:
            assert net.messaging_network.lane_executor is None
            notary = net.create_notary_node()
            bank = net.create_node("O=InlineBank,L=London,C=GB")
            handler_threads = set()
            orig = bank.smm._on_session_message

            def spy(sender, payload):
                handler_threads.add(threading.current_thread().name)
                orig(sender, payload)

            bank.smm.messaging._handlers["platform.session"] = [spy]
            h = bank.start_flow(CashIssueFlow(
                Amount(100, "USD"), b"\x01", bank.info, notary.info,
            ))
            net.run_network()
            h.result.result(timeout=10)
            # every delivery ran on THIS thread (the pumping caller)
            assert handler_threads <= {threading.current_thread().name}
        finally:
            net.stop_nodes()

    def test_optin_lanes_notarise_pairs(self):
        net = MockNetwork(flow_lanes=2)
        try:
            assert net.messaging_network.lane_executor is not None
            notary = net.create_notary_node()
            bank_a = net.create_node("O=LaneA,L=London,C=GB")
            bank_b = net.create_node("O=LaneB,L=Paris,C=FR")
            token = Issued(bank_a.info.ref(1), "USD")
            for i in range(3):
                h = bank_a.start_flow(CashIssueFlow(
                    Amount(100, "USD"), b"\x01", bank_a.info, notary.info,
                ))
                net.run_network()
                h.result.result(timeout=10)
                h2 = bank_a.start_flow(CashPaymentFlow(
                    Amount(100, token), bank_b.info, notary.info,
                ))
                net.run_network()
                h2.result.result(timeout=10)
            assert len(
                bank_b.services.vault_service.unconsumed_states()
            ) == 3
            assert net.messaging_network.lane_executor.stats()[
                "dispatched"
            ] > 0
        finally:
            net.stop_nodes()

    def test_optin_lanes_lockcheck_zero_cycles(self):
        """ISSUE 15 satellite: the armed lock-order detector over a
        multi-lane notarise run — lane threads + step locks + vault
        cache (db lock) + group-commit machinery — with ZERO ordering
        cycles."""
        from corda_tpu.utils import lockorder

        lockorder.enable(True)
        lockorder.reset()
        try:
            net = MockNetwork(flow_lanes=2)
            try:
                notary = net.create_notary_node()
                bank = net.create_node("O=LockLane,L=London,C=GB")
                # group commit on the in-memory node too: the detector
                # must see the committer's lock in the running order
                bank.checkpoint_storage.enable_group_commit()
                token = Issued(bank.info.ref(1), "USD")
                for i in range(2):
                    h = bank.start_flow(CashIssueFlow(
                        Amount(100, "USD"), b"\x01", bank.info, notary.info,
                    ))
                    net.run_network()
                    h.result.result(timeout=10)
                    h2 = bank.start_flow(CashPaymentFlow(
                        Amount(100, token), bank.info, notary.info,
                    ))
                    net.run_network()
                    h2.result.result(timeout=10)
                assert lockorder.meta()["nodes"] > 10
                assert lockorder.cycles() == [], lockorder.cycles()
            finally:
                net.stop_nodes()
        finally:
            lockorder.enable(None)
            lockorder.reset()


# ---------------------------------------------------------------------------
# Indexed vault selection
# ---------------------------------------------------------------------------

def _vault_with(net, size, db_path=":memory:"):
    from corda_tpu.core.transactions.builder import TransactionBuilder

    notary = net.create_notary_node()
    bank = net.create_node("O=VaultBank,L=London,C=GB", db_path=db_path)
    token = Issued(bank.info.ref(1), "USD")
    builder = TransactionBuilder(notary=notary.info)
    for _ in range(size):
        builder.add_output_state(
            CashState(amount=Amount(100, token), owner=bank.info)
        )
    builder.add_command(CashCommand.Issue(), bank.info.owning_key)
    bank.services.record_transactions(
        [bank.services.sign_initial_transaction(builder)]
    )
    return notary, bank, token


class TestIndexedVaultSelection:
    def test_payment_deserializes_o_selected_not_o_vault(self):
        """The counter-instrumented O(selected) proof: a one-state spend
        against a warm vault deserializes ZERO blobs (notify_all warmed
        the decoded cache), and against a COLD cache deserializes only
        the states it touched — in both cases independent of vault
        size."""
        deltas = {}
        cold = {}
        for size in (40, 400):
            net = MockNetwork()
            try:
                notary, bank, token = _vault_with(net, size)
                vault = bank.services.vault_service

                def pay():
                    h = bank.start_flow(CashPaymentFlow(
                        Amount(100, token), bank.info, notary.info,
                    ))
                    net.run_network()
                    h.result.result(timeout=10)

                d0 = vault.stats["decodes"]
                pay()
                deltas[size] = vault.stats["decodes"] - d0

                # cold cache: only the touched candidates decode
                with vault.db.lock:
                    vault._decoded.clear()
                    vault._avail.clear()
                d0 = vault.stats["decodes"]
                pay()
                cold[size] = vault.stats["decodes"] - d0
            finally:
                net.stop_nodes()
        assert deltas[40] == deltas[400] == 0, deltas
        # cold pick touches O(selected): 1 input + the handful the
        # notarised tx re-reads — nowhere near the vault size
        assert cold[40] == cold[400], cold
        assert cold[400] < 10, cold

    def test_consume_invalidates_cache_and_bucket(self):
        net = MockNetwork()
        try:
            notary, bank, token = _vault_with(net, 3)
            vault = bank.services.vault_service
            before = vault.unlocked_unconsumed_states(
                CashState.contract_name
            )
            assert len(before) == 3
            h = bank.start_flow(CashPaymentFlow(
                Amount(100, token), bank.info, notary.info,
            ))
            net.run_network()
            h.result.result(timeout=10)
            after = vault.unlocked_unconsumed_states(CashState.contract_name)
            # one input consumed, one payment output produced -> still 3,
            # but the consumed ref is gone from bucket AND decoded cache
            consumed_key = None
            after_keys = {vault._refkey(sr.ref) for sr in after}
            for sr in before:
                k = vault._refkey(sr.ref)
                if k not in after_keys:
                    consumed_key = k
            assert consumed_key is not None
            with vault.db.lock:
                assert consumed_key not in vault._decoded
                for bucket in vault._avail.values():
                    assert consumed_key not in bucket
        finally:
            net.stop_nodes()

    def test_mark_notary_consumed_evicts(self):
        net = MockNetwork()
        try:
            _notary, bank, _token = _vault_with(net, 2)
            vault = bank.services.vault_service
            states = vault.unlocked_unconsumed_states(
                CashState.contract_name
            )
            flipped = vault.mark_notary_consumed([states[0].ref])
            assert flipped == [states[0].ref]
            remaining = list(vault.iter_unlocked_unconsumed(
                CashState.contract_name
            ))
            assert states[0].ref not in {sr.ref for sr in remaining}
            assert len(remaining) == 1
            # idempotent
            assert vault.mark_notary_consumed([states[0].ref]) == []
        finally:
            net.stop_nodes()

    def test_soft_lock_interaction(self):
        net = MockNetwork()
        try:
            _notary, bank, _token = _vault_with(net, 3)
            vault = bank.services.vault_service
            states = vault.unlocked_unconsumed_states(
                CashState.contract_name
            )
            vault.soft_lock_reserve("L1", [states[0].ref])
            # another flow's view skips the locked state...
            other = list(vault.iter_unlocked_unconsumed(
                CashState.contract_name, lock_id="L2"
            ))
            assert states[0].ref not in {sr.ref for sr in other}
            # ...the holder's view includes it
            mine = list(vault.iter_unlocked_unconsumed(
                CashState.contract_name, lock_id="L1"
            ))
            assert states[0].ref in {sr.ref for sr in mine}
            # targeted release restores availability
            vault.soft_lock_release("L1", [states[0].ref])
            other = list(vault.iter_unlocked_unconsumed(
                CashState.contract_name, lock_id="L2"
            ))
            assert states[0].ref in {sr.ref for sr in other}
            # release-all (the flow-failure path) also clears buckets
            vault.soft_lock_reserve("L3", [states[1].ref])
            vault.soft_lock_release("L3")
            free = list(vault.iter_unlocked_unconsumed(
                CashState.contract_name
            ))
            assert len(free) == 3
        finally:
            net.stop_nodes()

    def test_concurrent_eviction_behind_cursor_skips_nothing(self):
        """Review pin: entries consumed BEHIND an in-progress iterator's
        position shift the bucket left; a positional cursor would skip
        still-available states (spurious InsufficientBalance). The
        cursorless re-scan must yield every remaining state exactly
        once."""
        net = MockNetwork()
        try:
            _notary, bank, _token = _vault_with(net, 150)
            vault = bank.services.vault_service
            it = vault.iter_unlocked_unconsumed(CashState.contract_name)
            got = [next(it) for _ in range(70)]  # past the first chunk
            # consume 50 of the ALREADY-YIELDED refs (positions < cursor)
            vault.mark_notary_consumed([sr.ref for sr in got[:50]])
            rest = list(it)
            keys = [vault._refkey(sr.ref) for sr in got + rest]
            assert len(keys) == len(set(keys))  # exactly once
            # nothing still-available was skipped: 150 total, all seen
            assert len(got) + len(rest) == 150
        finally:
            net.stop_nodes()

    def test_sibling_connection_write_flushes_buckets(self, tmp_path):
        """Cross-PROCESS coherence (the shardhost shape: worker
        processes share one vault file): a write by another connection
        bumps sqlite's data_version, and the next selection rebuilds
        its buckets instead of serving stale availability."""
        from corda_tpu.node.database import NodeDatabase

        db_file = str(tmp_path / "vault.db")
        net = MockNetwork()
        try:
            _notary, bank, _token = _vault_with(net, 3, db_path=db_file)
            vault = bank.services.vault_service
            states = vault.unlocked_unconsumed_states(
                CashState.contract_name
            )
            assert len(states) == 3
            flushes0 = vault.stats["generation_flushes"]

            sibling = NodeDatabase(db_file)
            sibling.execute(
                "UPDATE vault_states SET consumed = 1 "
                "WHERE tx_id = ? AND output_index = ?",
                (states[0].ref.txhash.bytes, states[0].ref.index),
            )
            sibling.close()

            now = list(vault.iter_unlocked_unconsumed(
                CashState.contract_name
            ))
            assert states[0].ref not in {sr.ref for sr in now}
            assert len(now) == 2
            assert vault.stats["generation_flushes"] == flushes0 + 1
        finally:
            net.stop_nodes()

    def test_cache_kill_switch_matches_indexed_results(self, monkeypatch):
        """CORDA_TPU_VAULT_CACHE=0 disables the index (the comparator
        config), and on ONE identical vault the indexed listing equals
        the legacy full-scan — same refs, same order."""
        monkeypatch.setenv("CORDA_TPU_VAULT_CACHE", "0")
        net = MockNetwork()
        try:
            _notary, bank, _token = _vault_with(net, 5)
            legacy_vault = bank.services.vault_service
            assert not legacy_vault._indexed
            legacy = [
                (sr.ref.txhash.bytes, sr.ref.index)
                for sr in legacy_vault.unlocked_unconsumed_states(
                    CashState.contract_name
                )
            ]
            # an indexed VaultService over the SAME database
            monkeypatch.delenv("CORDA_TPU_VAULT_CACHE")
            from corda_tpu.node.services import VaultService

            indexed_vault = VaultService(
                bank.database, bank.services._is_relevant,
                bank.services.load_state,
            )
            assert indexed_vault._indexed
            indexed = [
                (sr.ref.txhash.bytes, sr.ref.index)
                for sr in indexed_vault.unlocked_unconsumed_states(
                    CashState.contract_name
                )
            ]
            assert legacy == indexed
            assert len(legacy) == 5
        finally:
            net.stop_nodes()

    def test_unconsumed_states_second_read_is_decode_free(self):
        net = MockNetwork()
        try:
            _notary, bank, _token = _vault_with(net, 10)
            vault = bank.services.vault_service
            with vault.db.lock:  # start cold
                vault._decoded.clear()
            d0 = vault.stats["decodes"]
            vault.unconsumed_states(CashState.contract_name)
            assert vault.stats["decodes"] - d0 == 10
            d1 = vault.stats["decodes"]
            vault.unconsumed_states(CashState.contract_name)
            assert vault.stats["decodes"] == d1  # all cache hits
        finally:
            net.stop_nodes()


# ---------------------------------------------------------------------------
# Group-committed checkpoints
# ---------------------------------------------------------------------------

class TestGroupCommittedCheckpoints:
    def test_concurrent_writers_durable_on_fresh_connection(self, tmp_path):
        """The crash-durability pin: after put_incremental RETURNS, the
        checkpoint is committed — a brand-new connection (a restarted
        process) reads it back. Suspend durability is therefore
        unchanged by the coalescing."""
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.database import CheckpointStorage, NodeDatabase

        path = str(tmp_path / "cp.db")
        db = NodeDatabase(path)
        storage = CheckpointStorage(db)
        storage.enable_group_commit()
        header = serialize({
            "flow_id": "f", "flow_name": "X", "args": [], "kwargs": {},
            "is_responder": False,
        })
        sessions = serialize({
            "sessions": [], "session_keys": {}, "session_owner_flows": {},
        })
        errors = []

        def worker(w):
            try:
                for f in range(4):
                    fid = f"w{w}-f{f}"
                    storage.put_incremental(
                        fid, header, [(0, b"io")], sessions
                    )
                    for s in range(1, 6):
                        storage.put_incremental(
                            fid, None, [(s, b"io%d" % s)], sessions
                        )
                    if f % 2:
                        storage.remove(fid)
            except BaseException as exc:
                errors.append(exc)

        ts = [
            threading.Thread(target=worker, args=(w,), daemon=True,
                             name=f"gc-{w}")
            for w in range(8)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errors, errors[0]
        assert storage.group_commit_stats["ops"] > 0

        fresh = NodeDatabase(path)
        fresh_storage = CheckpointStorage(fresh)
        kept = dict(fresh_storage.all_checkpoints())
        assert len(kept) == 8 * 2  # the even-numbered flows per worker
        for fid in kept:
            io = fresh.query(
                "SELECT COUNT(*) FROM cp_io WHERE flow_id = ?", (fid,)
            )[0][0]
            assert io == 6
        fresh.close()
        db.close()

    def test_poisoned_op_does_not_fail_siblings(self, tmp_path):
        from corda_tpu.node.database import CheckpointStorage, NodeDatabase

        db = NodeDatabase(str(tmp_path / "p.db"))
        storage = CheckpointStorage(db)
        # a linger window so the bad op shares a batch with good ones
        storage.enable_group_commit(linger_ms=50)
        errors = {}
        start = threading.Barrier(5)

        def good(w):
            start.wait(timeout=10)
            storage.put_incremental(f"g{w}", b"h", [(0, b"io")], b"s")

        def bad():
            start.wait(timeout=10)
            try:
                # dict is not a sqlite-bindable blob -> InterfaceError
                storage.put_incremental("bad", {"not": "blob"}, [], b"s")
            except Exception as exc:
                errors["bad"] = exc

        ts = [
            threading.Thread(target=good, args=(w,), daemon=True,
                             name=f"gc-good-{w}")
            for w in range(4)
        ] + [threading.Thread(target=bad, daemon=True, name="gc-bad")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert "bad" in errors  # the poisoned op's OWN caller sees it
        kept = {
            row[0] for row in db.query("SELECT flow_id FROM cp_header")
        }
        assert kept == {"g0", "g1", "g2", "g3"}
        db.close()

    def test_reentrant_caller_bypasses_group(self, tmp_path):
        from corda_tpu.node.database import CheckpointStorage, NodeDatabase

        db = NodeDatabase(str(tmp_path / "r.db"))
        storage = CheckpointStorage(db)
        storage.enable_group_commit()
        with db.transaction():
            # inside an open transaction: a follower wait would deadlock
            # against our own held db lock — must execute directly
            storage.put_incremental("re", b"h", [(0, b"io")], b"s")
        assert db.query("SELECT COUNT(*) FROM cp_header")[0][0] == 1
        assert db.query("SELECT COUNT(*) FROM cp_io")[0][0] == 1
        db.close()

    def test_restore_from_group_committed_checkpoints(self, tmp_path):
        """End-to-end crash-redelivery shape: a flow checkpoints THROUGH
        the group committer, the node dies parked, and a restarted node
        restores and completes it."""
        from corda_tpu.core.transactions.builder import TransactionBuilder

        db = str(tmp_path / "gcrestore.db")
        net = MockNetwork()
        try:
            node = net.create_node(
                "O=GCRestore,L=Oslo,C=NO", db_path=db, entropy=97,
                dev_checkpoint_check=False,
            )
            node.checkpoint_storage.enable_group_commit()

            b = TransactionBuilder(notary=node.info)
            b.add_output_state(
                CashState(
                    amount=Amount(1, Issued(node.info.ref(1), "USD")),
                    owner=node.info,
                )
            )
            b.add_command(CashCommand.Issue(), node.info.owning_key)
            stx = node.services.sign_initial_transaction(b)

            handle = node.start_flow(WaitForTxFlow(stx.id), stx.id)
            assert not handle.result.done()
            assert node.checkpoint_storage.count() == 1
            assert node.checkpoint_storage.group_commit_stats["ops"] >= 1
            node.stop()  # crash while parked

            node2 = net.create_node(
                "O=GCRestore,L=Oslo,C=NO", db_path=db, entropy=97,
                dev_checkpoint_check=False,
            )
            restored = [f for f in node2.smm.flows.values() if not f.done]
            assert len(restored) == 1
            node2.services.record_transactions([stx])
            assert restored[0].result.result(timeout=5) == stx.id
        finally:
            net.stop_nodes()


# ---------------------------------------------------------------------------
# Gate coverage for the new bench keys (ISSUE 15 satellite)
# ---------------------------------------------------------------------------

def _flowpath_record():
    return {
        "metric": "ed25519-sig-verifies/sec/chip",
        "value": 1000.0,
        "stage_timings": {
            "coin_select_us_per_pick": 70.0,
            "checkpoint_group_commit_flows_s": 600.0,
            "checkpoint_per_step_flows_s": 250.0,
            "checkpoint_group_commit_speedup_x": 2.4,
            "flow_lane_pairs_s": 40.0,
            "flow_lane_sync_pairs_s": 38.0,
        },
    }


class TestFlowpathGate:
    def test_direction_classes(self):
        from corda_tpu.loadtest import gate

        assert gate.direction("coin_select_us_per_pick") == "lower"
        assert gate.direction("checkpoint_group_commit_flows_s") == "higher"
        assert gate.direction("checkpoint_per_step_flows_s") == "higher"
        assert gate.direction("flow_lane_pairs_s") == "higher"
        assert gate.direction("checkpoint_group_commit_speedup_x") == "higher"

    def test_synthetic_coin_select_regression_fails_gate(self, tmp_path):
        """A 2x coin-selection slowdown (the O(vault) failure mode this
        PR removes) must fail tools/bench_gate.py; the clean run
        passes."""
        prev, cur = _flowpath_record(), _flowpath_record()
        cur["stage_timings"]["coin_select_us_per_pick"] *= 2
        cur_p, prev_p = tmp_path / "cur.json", tmp_path / "prev.json"
        cur_p.write_text(json.dumps(cur))
        prev_p.write_text(json.dumps({"parsed": prev, "rc": 0}))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--current", str(cur_p), "--baseline", str(prev_p)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1, proc.stderr
        assert "coin_select_us_per_pick" in proc.stderr
        # clean run passes
        cur_p.write_text(json.dumps(prev))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--current", str(cur_p), "--baseline", str(prev_p)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr

    def test_group_commit_throughput_drop_flags(self):
        from corda_tpu.loadtest import gate

        prev, cur = _flowpath_record(), _flowpath_record()
        cur["stage_timings"]["checkpoint_group_commit_flows_s"] /= 2
        keys = {r["key"] for r in gate.compare_records(prev, cur)}
        assert "stage_timings.checkpoint_group_commit_flows_s" in keys


# ---------------------------------------------------------------------------
# The shared measurement helpers (bench + tests, one implementation)
# ---------------------------------------------------------------------------

class TestMeasurementHelpers:
    def test_coin_selection_helper_flat_and_decode_free(self):
        from corda_tpu.loadtest.latency import measure_coin_selection

        out = measure_coin_selection(vault_sizes=(50, 500), picks=10)
        assert out["coin_select_us_per_pick"] > 0
        assert out["coin_select_decodes_per_pick"] == 0.0
        # 10x the vault must not 2x the pick (the legacy path measures
        # ~8x growth here; see docs/perf-system.md round 20)
        assert out["coin_select_growth"] < 2.0, out

    def test_checkpoint_group_commit_helper_coalesces(self):
        from corda_tpu.loadtest.latency import (
            measure_checkpoint_group_commit,
        )

        out = measure_checkpoint_group_commit(threads=8, flows=2, steps=8)
        assert out["checkpoint_group_commit_flows_s"] > 0
        assert out["checkpoint_gc_mean_batch"] > 1.0  # real coalescing
        # directional sanity, loose on a loaded 1-core box: grouped must
        # not be dramatically slower than per-step at FULL durability
        assert out["checkpoint_group_commit_speedup_x"] > 0.8, out

    def test_flow_lane_ab_helper_runs_both_legs(self):
        from corda_tpu.loadtest.latency import measure_flow_lane_ab

        out = measure_flow_lane_ab(pairs=4, parallelism=2, lanes=2)
        assert out["flow_lane_pairs_s"] > 0
        assert out["flow_lane_sync_pairs_s"] > 0

"""Identity cert-path validation tests (reference
`InMemoryIdentityServiceTests` + X509Utilities cert hierarchy)."""
import pytest

from corda_tpu.core.crypto import crypto, pki
from corda_tpu.core.crypto.schemes import (
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
)
from corda_tpu.core.identity import Party, PartyAndCertificate
from corda_tpu.node.services import IdentityService

pytestmark = pytest.mark.skipif(
    not pki.OPENSSL_AVAILABLE,
    reason="X.509 PKI requires the 'cryptography' package",
)


@pytest.fixture(scope="module")
def hierarchy():
    root = pki.create_self_signed_ca("Corda TPU Root CA")
    intermediate = pki.create_intermediate_ca(root)
    node_ca = pki.create_node_ca(intermediate, "O=CertNode,L=London,C=GB")
    return root, intermediate, node_ca


def _certified(node_ca, hierarchy, name="O=CertNode,L=London,C=GB",
               scheme=EDDSA_ED25519_SHA512):
    kp = crypto.generate_keypair(scheme)
    party = Party(name, kp.public)
    cert = pki.create_identity_cert(node_ca, name, kp.public)
    root, intermediate, _ = hierarchy
    return PartyAndCertificate(
        party, cert, (node_ca.cert, intermediate.cert)
    )


class TestVerifyAndRegister:
    def test_valid_ed25519_identity(self, hierarchy):
        root, _, node_ca = hierarchy
        svc = IdentityService(trust_root=root.cert)
        identity = _certified(node_ca, hierarchy)
        svc.verify_and_register_identity(identity)
        assert svc.party_from_name(identity.party.name) == identity.party
        assert svc.certificate_from_party(identity.party) is not None

    def test_valid_ecdsa_identity(self, hierarchy):
        root, _, node_ca = hierarchy
        svc = IdentityService(trust_root=root.cert)
        identity = _certified(
            node_ca, hierarchy, scheme=ECDSA_SECP256R1_SHA256
        )
        svc.verify_and_register_identity(identity)
        assert svc.party_from_key(identity.party.owning_key) is not None

    def test_wrong_root_rejected(self, hierarchy):
        _, _, node_ca = hierarchy
        other_root = pki.create_self_signed_ca("Evil Root")
        svc = IdentityService(trust_root=other_root.cert)
        identity = _certified(node_ca, hierarchy)
        with pytest.raises(ValueError, match="does not verify"):
            svc.verify_and_register_identity(identity)

    def test_key_substitution_rejected(self, hierarchy):
        """A valid cert for key A must not register a party claiming key B."""
        root, _, node_ca = hierarchy
        svc = IdentityService(trust_root=root.cert)
        identity = _certified(node_ca, hierarchy)
        other = crypto.generate_keypair(EDDSA_ED25519_SHA512)
        forged = PartyAndCertificate(
            Party(identity.party.name, other.public),
            identity.certificate,
            identity.cert_path,
        )
        with pytest.raises(ValueError, match="bind"):
            svc.verify_and_register_identity(forged)

    def test_name_mismatch_rejected(self, hierarchy):
        root, _, node_ca = hierarchy
        svc = IdentityService(trust_root=root.cert)
        identity = _certified(node_ca, hierarchy)
        renamed = PartyAndCertificate(
            Party("O=Somebody Else,L=Paris,C=FR", identity.party.owning_key),
            identity.certificate,
            identity.cert_path,
        )
        with pytest.raises(ValueError, match="does not match party"):
            svc.verify_and_register_identity(renamed)

    def test_no_trust_root_refuses_verified_path(self, hierarchy):
        _, _, node_ca = hierarchy
        svc = IdentityService()
        identity = _certified(node_ca, hierarchy)
        with pytest.raises(ValueError, match="no trust root"):
            svc.verify_and_register_identity(identity)
        # dev-mode bare registration still works
        svc.register_identity(identity.party)
        assert svc.party_from_name(identity.party.name) == identity.party

    def test_leaf_signed_by_non_ca_rejected(self, hierarchy):
        """A leaf cannot issue identities: chain through a leaf must fail
        (path-length / CA constraints)."""
        root, intermediate, node_ca = hierarchy
        svc = IdentityService(trust_root=root.cert)
        kp = crypto.generate_keypair(EDDSA_ED25519_SHA512)
        # mint a fake "CA" from the identity leaf's own EC key: the TLS
        # cert is a non-CA leaf under node_ca
        tls = pki.create_tls_cert(node_ca, "O=CertNode,L=London,C=GB")
        fake = pki.CertAndKey(cert=tls.cert, key=tls.key)
        cert = pki.create_identity_cert(
            fake, "O=Mallory,L=X,C=GB", kp.public
        )
        identity = PartyAndCertificate(
            Party("O=Mallory,L=X,C=GB", kp.public),
            cert,
            (tls.cert, node_ca.cert, intermediate.cert),
        )
        with pytest.raises(ValueError, match="does not verify"):
            svc.verify_and_register_identity(identity)

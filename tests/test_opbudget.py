"""Kernel op-budget attestation gate (corda_tpu/ops/opbudget.py).

THE tier-1 regress-proofing deliverable of ROADMAP item 1: the
docs/perf-roofline.md op budget is pinned in
corda_tpu/ops/opbudget_manifest.json and any kernel whose traced
multiply count grows >5% over its pin must fail here — on the CPU-only
CI box, no hardware needed (tracing is abstract: no compile, no
device).

Counts are cached per process by the module, so the manifest test and
the gauge/gate tests share one trace per kernel.
"""
import json

import pytest

from corda_tpu.ops import opbudget


class TestCounts:
    def test_ed25519_counts_match_manifest(self):
        manifest = opbudget.load_manifest()
        violations = opbudget.check_budget("ed25519_xla", manifest)
        assert violations == [], violations
        counts = opbudget.cached_counts("ed25519_xla")
        assert counts["u32_mul_elems_per_sig"] > 0
        assert counts["dynamic_loops"] == 0, (
            "an un-countable while loop appeared in the ed25519 kernel"
        )

    def test_ecdsa_counts_match_manifest(self):
        violations = opbudget.check_budget("ecdsa_secp256r1_xla")
        assert violations == [], violations
        counts = opbudget.cached_counts("ecdsa_secp256r1_xla")
        # the roofline note's estimate: ~2x the ed25519 per-mul cost at
        # the same 256-step ladder shape — the Montgomery CIOS family
        # must stay an order-of-magnitude match, not drift silently
        assert counts["field_mul_equiv_per_sig"] > 5_000

    def test_pallas_budget_matches_pin_and_roofline(self):
        violations = opbudget.check_budget("ed25519_pallas")
        assert violations == [], violations
        counts = opbudget.cached_counts("ed25519_pallas")
        reference = opbudget.load_manifest()["roofline_reference"][
            "ed25519_pallas_field_muls_per_sig"
        ]
        # the traced count must agree with the hand-derived ≈3,300
        # budget docs/perf-roofline.md argues from (measured 3,504:
        # the hand count rounds the decompress chain + table build)
        assert counts["field_mul_equiv_per_sig"] == pytest.approx(
            reference, rel=0.20
        )

    def test_unknown_kernel_raises(self):
        with pytest.raises(KeyError):
            opbudget.count_kernel("no-such-kernel")

    def test_unpinned_kernel_is_a_violation(self):
        violations = opbudget.check_budget(
            "ed25519_xla", manifest={"kernels": {}, "tolerance": 0.05}
        )
        assert violations and violations[0]["kind"] == "unpinned"
        assert opbudget.fatal_violations(violations)


class TestSyntheticGrowth:
    """The gate's teeth: dummy field muls injected via the test hook
    must fail the pinned budget with a diff naming kernel + delta."""

    @pytest.fixture(autouse=True)
    def _restore_hook(self):
        yield
        opbudget._TEST_EXTRA_MULS = 0
        opbudget._clear_cache("ed25519_xla")

    def test_inflated_ed25519_ladder_fails_gate(self):
        baseline = opbudget.count_kernel("ed25519_xla")
        opbudget._TEST_EXTRA_MULS = 600  # ≈10% of the ~5.7k-mul budget
        opbudget._clear_cache("ed25519_xla")
        violations = opbudget.check_budget("ed25519_xla")
        assert violations, "synthetic ladder growth passed the gate"
        v = violations[0]
        assert v["kernel"] == "ed25519_xla"
        assert v["kind"] == "grew"
        assert v["metric"] == "u32_mul_elems_per_sig"
        assert v["change"] > 0.05
        assert v["measured"] > v["pinned"]
        assert opbudget.fatal_violations(violations)
        # and the inflated trace really did grow vs the clean one
        opbudget._TEST_EXTRA_MULS = 0
        opbudget._clear_cache("ed25519_xla")
        clean = opbudget.count_kernel("ed25519_xla")
        assert clean["u32_mul_elems_per_sig"] == pytest.approx(
            baseline["u32_mul_elems_per_sig"]
        )


class TestManifestAndGauges:
    def test_manifest_covers_every_registered_kernel(self):
        manifest = opbudget.load_manifest()
        assert set(manifest["kernels"]) == set(opbudget.KERNEL_NAMES)
        for name, pinned in manifest["kernels"].items():
            for metric in opbudget.PINNED_METRICS:
                assert metric in pinned, (name, metric)

    def test_pin_manifest_roundtrip_and_partial_merge(self, tmp_path):
        path = str(tmp_path / "manifest.json")
        manifest = opbudget.pin_manifest(path=path, names=["ed25519_xla"])
        with open(path) as fh:
            reloaded = json.load(fh)
        assert reloaded["kernels"] == manifest["kernels"]
        assert opbudget.check_budget("ed25519_xla", reloaded) == []
        # a partial re-pin MERGES: pinning one kernel must not delete
        # the other kernels' pins (counts cached — no re-trace here)
        merged = opbudget.pin_manifest(
            path=path, names=["ecdsa_secp256r1_xla"]
        )
        assert set(merged["kernels"]) == {
            "ed25519_xla", "ecdsa_secp256r1_xla",
        }
        with open(path) as fh:
            assert set(json.load(fh)["kernels"]) == set(merged["kernels"])

    def test_gauge_values_follow_the_cache(self):
        # earlier tests traced ed25519_xla in this process
        assert opbudget.gauge_value(
            "ed25519_xla", "u32_mul_elems_per_sig"
        ) > 0
        opbudget._clear_cache("ed25519_xla")
        assert opbudget.gauge_value(
            "ed25519_xla", "u32_mul_elems_per_sig"
        ) == -1.0
        opbudget.count_kernel("ed25519_xla")
        assert opbudget.gauge_value(
            "ed25519_xla", "field_mul_equiv_per_sig"
        ) > 0

    def test_check_all_clean(self):
        violations = opbudget.check_all()
        assert opbudget.fatal_violations(violations) == [], violations

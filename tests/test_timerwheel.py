"""Shared timer wheel (utils/timerwheel.py): one thread serves every
timeout instead of threading.Timer's thread-per-call."""
import threading
import time

from corda_tpu.utils.timerwheel import SharedTimer


def test_fires_in_order_and_cancel_suppresses():
    w = SharedTimer("test-wheel")
    fired = []
    ev = threading.Event()
    w.call_later(0.01, lambda: fired.append("a"))
    h = w.call_later(0.02, lambda: fired.append("cancelled"))
    w.call_later(0.03, lambda: (fired.append("b"), ev.set()))
    h.cancel()
    assert ev.wait(5)
    time.sleep(0.05)
    assert fired == ["a", "b"]
    w.stop()


def test_slow_callback_does_not_stall_other_timers():
    """Callbacks run on a pool, not the deadline thread: a heavy flush
    must not delay an unrelated timeout (review finding r5)."""
    w = SharedTimer("test-wheel-2")
    order = []
    done = threading.Event()
    w.call_later(0.01, lambda: time.sleep(0.5))  # heavy callback
    w.call_later(0.05, lambda: (order.append("fast"), done.set()))
    assert done.wait(5)
    # the fast timer fired while the heavy one was still sleeping
    assert order == ["fast"]
    w.stop()


def test_cancelled_entries_are_compacted():
    w = SharedTimer("test-wheel-3")
    w.COMPACT_AT = 8
    handles = [w.call_later(3600, lambda: None) for _ in range(20)]
    for h in handles:
        h.cancel()
    time.sleep(0.05)
    with w._cv:
        assert len(w._heap) < 20  # long-deadline closures were released
    w.stop()

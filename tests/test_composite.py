"""CompositeKey tests, mirroring reference CompositeKeyTests.kt."""
import pytest

from corda_tpu.core import crypto as c
from corda_tpu.core.crypto.composite import (
    CompositeKey,
    CompositeSignaturesWithKeys,
    NodeAndWeight,
    decode_composite_key,
)


@pytest.fixture(scope="module")
def keys():
    return [c.derive_keypair_from_entropy(c.EDDSA_ED25519_SHA512, 1000 + i) for i in range(5)]


def test_threshold_evaluation(keys):
    a, b, x = keys[0].public, keys[1].public, keys[2].public
    two_of_three = CompositeKey.Builder().add_keys(a, b, x).build(threshold=2)
    assert not two_of_three.is_fulfilled_by([a])
    assert two_of_three.is_fulfilled_by([a, b])
    assert two_of_three.is_fulfilled_by([a, x])
    assert two_of_three.is_fulfilled_by([a, b, x])
    assert not two_of_three.is_fulfilled_by([keys[3].public, keys[4].public])


def test_weighted_threshold(keys):
    a, b, x = keys[0].public, keys[1].public, keys[2].public
    # a alone (weight 2) meets threshold; b+x (1+1) also meets it
    k = (
        CompositeKey.Builder()
        .add_key(a, weight=2)
        .add_key(b, weight=1)
        .add_key(x, weight=1)
        .build(threshold=2)
    )
    assert k.is_fulfilled_by([a])
    assert k.is_fulfilled_by([b, x])
    assert not k.is_fulfilled_by([b])


def test_nested_trees(keys):
    a, b, x, y = (k.public for k in keys[:4])
    inner = CompositeKey.Builder().add_keys(x, y).build(threshold=1)
    outer = CompositeKey.Builder().add_key(a).add_key(inner).build(threshold=2)
    assert outer.is_fulfilled_by([a, x])
    assert outer.is_fulfilled_by([a, y])
    assert not outer.is_fulfilled_by([a])
    assert not outer.is_fulfilled_by([x, y])
    assert outer.keys == {a, x, y}


def test_single_key_collapses(keys):
    a = keys[0].public
    assert CompositeKey.Builder().add_key(a).build() is a


def test_validation_rules(keys):
    a, b = keys[0].public, keys[1].public
    with pytest.raises(ValueError):
        CompositeKey.Builder().build()
    with pytest.raises(ValueError):
        CompositeKey.Builder().add_keys(a, b).build(threshold=3)  # > total weight
    with pytest.raises(ValueError):
        CompositeKey.Builder().add_keys(a, b).build(threshold=0)
    with pytest.raises(ValueError):
        CompositeKey.Builder().add_key(a, weight=-1).build()
    with pytest.raises(ValueError):
        CompositeKey.Builder().add_keys(a, a).build(threshold=1)  # duplicate leaf


def test_encoding_roundtrip(keys):
    a, b, x = (k.public for k in keys[:3])
    inner = CompositeKey.Builder().add_keys(b, x).build(threshold=1)
    k = CompositeKey.Builder().add_key(a, weight=3).add_key(inner, weight=2).build(threshold=4)
    decoded = decode_composite_key(k.encoded)
    assert decoded == k
    assert decoded.threshold == 4
    assert decoded.is_fulfilled_by([a, b])


def test_composite_signature_verification(keys):
    a_kp, b_kp, x_kp = keys[:3]
    k = CompositeKey.Builder().add_keys(a_kp.public, b_kp.public, x_kp.public).build(threshold=2)
    msg = b"multi-sig payload"
    sigs = CompositeSignaturesWithKeys(
        (
            (a_kp.public, c.do_sign(a_kp.private, msg)),
            (b_kp.public, c.do_sign(b_kp.private, msg)),
        )
    )
    assert c.is_valid(k, sigs.serialize(), msg)
    # one sig only: threshold not met
    one = CompositeSignaturesWithKeys(((a_kp.public, c.do_sign(a_kp.private, msg)),))
    assert not c.is_valid(k, one.serialize(), msg)
    # a corrupted constituent signature fails the whole composite
    bad = CompositeSignaturesWithKeys(
        (
            (a_kp.public, c.do_sign(a_kp.private, msg)),
            (b_kp.public, b"\x00" * 64),
        )
    )
    assert not c.is_valid(k, bad.serialize(), msg)


def test_is_fulfilled_by_on_plain_key(keys):
    a, b = keys[0].public, keys[1].public
    assert a.is_fulfilled_by([a, b])
    assert not a.is_fulfilled_by([b])
    assert a.keys == {a}

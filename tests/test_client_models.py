"""Observable view-model tests (reference client/jfx model tests —
NodeMonitorModel feed aggregation, ContractStateModel cash folding)."""
from corda_tpu.client.models import (
    ContractStateModel,
    NetworkIdentityModel,
    NodeMonitorModel,
    ObservableList,
    ObservableValue,
    filter_observable,
    map_observable,
)
from corda_tpu.core.contracts import Amount
from corda_tpu.core.contracts.amount import Issued
from corda_tpu.core.flows import FlowLogic, startable_by_rpc
from corda_tpu.finance.flows import CashIssueFlow, CashPaymentFlow
from corda_tpu.rpc import CordaRPCOps
from corda_tpu.testing import MockNetwork
from corda_tpu.utils.observable import Observable


class TestCombinators:
    def test_map_and_filter(self):
        src = Observable()
        seen = []
        filter_observable(
            map_observable(src, lambda x: x * 10), lambda x: x > 15
        ).subscribe(seen.append)
        for i in range(4):
            src.on_next(i)
        assert seen == [20, 30]

    def test_observable_value(self):
        v = ObservableValue(1)
        seen = []
        v.updates.subscribe(seen.append)
        v.set(2)
        assert v.value == 2 and seen == [2]

    def test_observable_list_ops(self):
        xs = ObservableList()
        snapshots = []
        xs.updates.subscribe(snapshots.append)
        xs.append("a")
        xs.append("b")
        xs.replace_where(lambda x: x == "a", "A")
        xs.remove_where(lambda x: x == "b")
        assert xs.items == ["A"]
        assert snapshots[-1] == ["A"]
        assert len(xs) == 1


@startable_by_rpc
class _PingFlow(FlowLogic):
    def call(self):
        return "pong"
        yield  # pragma: no cover


class TestNodeMonitorModel:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.node = self.net.create_node("O=Monitor,L=London,C=GB")
        self.ops = CordaRPCOps(self.node.services, self.node.smm)

    def teardown_method(self):
        self.net.stop_nodes()

    def test_state_machines_and_transactions_fold(self):
        model = NodeMonitorModel(self.ops)
        self.ops.start_flow_dynamic("_PingFlow")
        self.net.run_network()
        # flow finished -> removed from the in-flight collection
        assert len(model.state_machines) == 0
        # issue cash -> a verified transaction + vault update appear
        usd = Amount(100_000, "USD")
        h = self.node.start_flow(
            CashIssueFlow(usd, b"\x01", self.node.info, self.notary.info)
        )
        self.net.run_network()
        h.result.result(timeout=10)
        assert len(model.transactions) == 1
        assert len(model.vault_updates) == 1
        assert any(
            n.name == self.node.info.name
            for n in model.network_identities.items
        )
        model.close()


class TestContractStateModel:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.bank = self.net.create_node("O=BankM,L=London,C=GB")
        self.alice = self.net.create_node("O=AliceM,L=Paris,C=FR")
        self.ops = CordaRPCOps(self.bank.services, self.bank.smm)

    def teardown_method(self):
        self.net.stop_nodes()

    def _issue(self, qty: int, ccy: str = "USD"):
        amt = Amount(qty, ccy)
        h = self.bank.start_flow(
            CashIssueFlow(amt, b"\x01", self.bank.info, self.notary.info)
        )
        self.net.run_network()
        h.result.result(timeout=10)

    def test_balances_fold_across_issues_and_payments(self):
        model = ContractStateModel(self.ops)
        assert model.balances.value == {}
        self._issue(500_00, "USD")
        self._issue(250_00, "USD")
        self._issue(100_00, "GBP")
        assert model.balances.value == {"USD": 750_00, "GBP": 100_00}
        assert len(model.cash_states) == 3

        # pay away 600.00 USD: consumed + change states fold through
        pay = Amount(600_00, Issued(self.bank.info.ref(1), "USD"))
        h = self.bank.start_flow(
            CashPaymentFlow(pay, self.alice.info, self.notary.info)
        )
        self.net.run_network()
        h.result.result(timeout=10)
        assert model.balances.value["USD"] == 150_00
        assert model.balances.value["GBP"] == 100_00
        model.close()


class TestNetworkIdentityModel:
    def test_lookup_and_refresh(self):
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        a = net.create_node("O=IdA,L=London,C=GB")
        ops = CordaRPCOps(a.services, a.smm)
        model = NetworkIdentityModel(ops)
        assert model.lookup(a.info.name) is not None
        assert model.lookup("O=Nobody,L=X,C=YY") is None
        assert any(
            n.name == notary.info.name for n in model.notaries.items
        )
        b = net.create_node("O=IdB,L=Berlin,C=DE")
        model.refresh()
        assert model.lookup(b.info.name) is not None
        net.stop_nodes()


class TestExchangeRateModel:
    def test_identity_default_and_rate_table(self):
        from corda_tpu.client.models import ExchangeRateModel

        m = ExchangeRateModel()
        assert m.exchange_amount(12_345, "USD", "EUR") == 12_345  # identity
        m.set_rates({"USD": 1.0, "EUR": 1.25, "GBP": 1.5})
        assert m.exchange_amount(100, "GBP", "USD") == 150
        assert m.exchange_amount(125, "EUR", "GBP") == 104  # 156.25/1.5
        seen = []
        m.exchange_rate.updates.subscribe(lambda fn: seen.append(fn("EUR")))
        m.set_rates({"EUR": 2.0})
        assert seen and seen[-1] == 2.0


class TestTransactionDataModel:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.bank = self.net.create_node("O=TxD,L=London,C=GB")
        self.peer = self.net.create_node("O=TxDPeer,L=Paris,C=FR")
        self.ops = CordaRPCOps(self.bank.services, self.bank.smm)

    def teardown_method(self):
        self.net.stop_nodes()

    def test_inputs_resolve_incrementally(self):
        from corda_tpu.client.models import TransactionDataModel
        from corda_tpu.finance.flows import CashPaymentFlow
        from corda_tpu.core.contracts.amount import Issued

        model = TransactionDataModel(self.ops)
        usd = Amount(50_000, "USD")
        h = self.bank.start_flow(
            CashIssueFlow(usd, b"\x01", self.bank.info, self.notary.info)
        )
        self.net.run_network()
        h.result.result(timeout=10)
        assert len(model.partially_resolved) == 1
        issue = model.partially_resolved.items[0]
        assert issue.fully_resolved  # no inputs at all
        token = Issued(self.bank.info.ref(1), "USD")
        h = self.bank.start_flow(
            CashPaymentFlow(
                Amount(50_000, token), self.peer.info, self.notary.info
            ),
            Amount(50_000, token), self.peer.info, self.notary.info,
        )
        self.net.run_network()
        h.result.result(timeout=10)
        assert len(model.partially_resolved) == 2
        pay = model.partially_resolved.items[1]
        # the payment's input resolves against the issue tx in the map
        assert pay.inputs and pay.fully_resolved
        resolved = pay.inputs[0].state_and_ref
        assert resolved is not None
        assert resolved.ref.txhash == issue.id
        assert model.lookup(pay.id) is not None
        model.close()

    def test_out_of_order_arrival_notifies_late_resolution(self):
        """Review finding (r5): when a dependency arrives AFTER its
        spender, subscribers must see an update event for the earlier
        entry, not just the new append."""
        from types import SimpleNamespace

        from corda_tpu.client.models import TransactionDataModel

        # build issue + spend via a private mock feed so we control order
        class _Feed:
            def __init__(self):
                self.snapshot = []
                from corda_tpu.utils.observable import Observable
                self.updates = Observable()

        feed = _Feed()
        ops = SimpleNamespace(verified_transactions_feed=lambda: feed)
        model = TransactionDataModel(ops)

        # craft real issue + spend txs with the mocknetwork machinery
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        bank = net.create_node("O=OO,L=London,C=GB")
        peer = net.create_node("O=OOP,L=Paris,C=FR")
        ops_real = CordaRPCOps(bank.services, bank.smm)
        usd = Amount(10_000, "USD")
        h = bank.start_flow(
            CashIssueFlow(usd, b"\x01", bank.info, notary.info)
        )
        net.run_network(); h.result.result(timeout=10)
        from corda_tpu.finance.flows import CashPaymentFlow
        from corda_tpu.core.contracts.amount import Issued
        token = Issued(bank.info.ref(1), "USD")
        h = bank.start_flow(
            CashPaymentFlow(Amount(10_000, token), peer.info, notary.info),
            Amount(10_000, token), peer.info, notary.info,
        )
        net.run_network(); h.result.result(timeout=10)
        txs = [sar for sar in ops_real.verified_transactions_feed().snapshot]
        net.stop_nodes()
        assert len(txs) >= 2
        issue, spend = txs[0], txs[1]
        events = []
        model.partially_resolved.updates.subscribe(events.append)
        # deliver OUT OF ORDER: spender first
        feed.updates.on_next(spend)
        entry = model.partially_resolved.items[0]
        assert not entry.fully_resolved
        n_before = len(events)
        feed.updates.on_next(issue)
        # the earlier entry resolved AND an event announced it
        assert entry.fully_resolved
        assert len(events) > n_before + 1  # replace event + append event
        model.close()

"""Raft consensus tests: election, replication, conflict detection,
leader failover, partition catch-up (reference coverage parity:
`RaftValidatingNotaryServiceTests.kt` + DistributedImmutableMap tests).
Fully deterministic: ticks + manual message pumping, no wall-clock."""
from collections import deque

import pytest

from corda_tpu.core.contracts import StateRef
from corda_tpu.core.crypto import SecureHash, crypto
from corda_tpu.core.identity import Party
from corda_tpu.node.database import NodeDatabase
from corda_tpu.node.notary import RaftUniquenessProvider, UniquenessException
from corda_tpu.node.raft import LEADER, NotLeaderError, RaftNode


class Cluster:
    """N RaftNodes over a deterministic in-memory transport."""

    def __init__(self, n=3, with_db=False, apply_fn=None):
        self.queue = deque()  # (src, dst, payload)
        self.partitioned = set()  # node ids cut off from the world
        self.nodes = {}
        self.applied = {i: [] for i in range(n)}
        ids = [f"n{i}" for i in range(n)]
        for i, node_id in enumerate(ids):
            db = NodeDatabase(":memory:") if with_db else None

            def make_apply(idx):
                def apply(cmd):
                    self.applied[idx].append(cmd)
                    return {"conflicts": {}}
                return apply

            def make_transport(src):
                def transport(dst, payload):
                    self.queue.append((src, dst, payload))
                return transport

            self.nodes[node_id] = RaftNode(
                node_id, ids, make_transport(node_id),
                apply_fn(i) if apply_fn else make_apply(i),
                db=db, seed=i,
            )

    def pump(self, max_rounds=200):
        rounds = 0
        while self.queue and rounds < max_rounds:
            src, dst, payload = self.queue.popleft()
            if src in self.partitioned or dst in self.partitioned:
                continue
            self.nodes[dst].on_message(src, payload)
            rounds += 1

    def tick_all(self, now):
        for node_id, node in self.nodes.items():
            if node_id not in self.partitioned:
                node.tick(now)
        self.pump()

    def elect(self, start=0.0):
        """Advance time until someone wins an election."""
        t = start
        for _ in range(100):
            t += 5
            self.tick_all(t)
            leaders = [n for n in self.nodes.values()
                       if n.is_leader and n.node_id not in self.partitioned]
            if leaders:
                return leaders[0], t
        raise AssertionError("no leader elected")


class TestRaft:
    def test_leader_election(self):
        c = Cluster(3)
        leader, _ = c.elect()
        followers = [n for n in c.nodes.values() if n is not leader]
        assert all(f.leader_id == leader.node_id for f in followers)

    def test_replication_and_apply_on_all(self):
        c = Cluster(3)
        leader, t = c.elect()
        fut = leader.submit({"kind": "putall", "entries": {"aa": b"x"}})
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}
        # Followers learn the commit index on the next heartbeat.
        for _ in range(3):
            t += 5
            c.tick_all(t)
        applied_counts = [len(v) for v in c.applied.values()]
        assert applied_counts == [1, 1, 1]

    def test_submit_to_follower_fails_fast(self):
        c = Cluster(3)
        leader, _ = c.elect()
        follower = next(n for n in c.nodes.values() if n is not leader)
        fut = follower.submit({"kind": "putall", "entries": {}})
        with pytest.raises(NotLeaderError) as err:
            fut.result(timeout=0)
        assert err.value.leader_hint == leader.node_id

    def test_leader_failover(self):
        c = Cluster(3)
        leader, t = c.elect()
        fut = leader.submit({"kind": "putall", "entries": {"k1": b"1"}})
        c.pump()
        fut.result(timeout=0)

        c.partitioned.add(leader.node_id)  # kill the leader
        new_leader, t = c.elect(start=t)
        assert new_leader.node_id != leader.node_id
        fut2 = new_leader.submit({"kind": "putall", "entries": {"k2": b"2"}})
        c.pump()
        assert fut2.result(timeout=0) == {"conflicts": {}}

        # Old leader rejoins and catches up.
        c.partitioned.discard(leader.node_id)
        for _ in range(10):
            t += 5
            c.tick_all(t)
        old = c.nodes[leader.node_id]
        assert not old.is_leader
        assert old.last_applied == new_leader.last_applied

    def test_log_survives_restart_with_db(self):
        c = Cluster(3, with_db=True)
        leader, _ = c.elect()
        fut = leader.submit({"kind": "putall", "entries": {"p": b"q"}})
        c.pump()
        fut.result(timeout=0)
        assert len(leader.log) == 1
        # New node instance from the same DB sees the persisted log/term.
        reloaded = RaftNode(
            leader.node_id, list(c.nodes), lambda d, p: None,
            lambda cmd: None, db=leader._meta.db, seed=99,
        )
        assert len(reloaded.log) == 1
        assert reloaded.current_term == leader.current_term


class TestRaftUniquenessProvider:
    def _provider_cluster(self):
        dbs = [NodeDatabase(":memory:") for _ in range(3)]
        providers = {}
        c = Cluster(3, apply_fn=lambda i: lambda cmd: providers[f"n{i}"].apply(cmd))
        for i, (node_id, node) in enumerate(c.nodes.items()):
            providers[node_id] = RaftUniquenessProvider(node, dbs[i])
        return c, providers

    def test_commit_and_conflict(self):
        c, providers = self._provider_cluster()
        leader, _ = c.elect()
        provider = providers[leader.node_id]
        party = Party(
            "O=Notary,L=Zurich,C=CH", crypto.entropy_to_keypair(1).public
        )
        tx1 = SecureHash.sha256(b"tx1")
        tx2 = SecureHash.sha256(b"tx2")
        ref = StateRef(SecureHash.sha256(b"issue"), 0)

        import threading
        done = []
        thread = threading.Thread(
            target=lambda: done.append(provider.commit([ref], tx1, party))
        )
        thread.start()
        for _ in range(50):
            c.pump()
            if done:
                break
            import time
            time.sleep(0.01)
        thread.join(timeout=5)
        assert done  # committed

        # Same ref, same tx -> idempotent re-commit succeeds.
        t2 = threading.Thread(
            target=lambda: done.append(provider.commit([ref], tx1, party))
        )
        t2.start()
        for _ in range(50):
            c.pump()
            if len(done) > 1:
                break
            import time
            time.sleep(0.01)
        t2.join(timeout=5)
        assert len(done) == 2

        # Different tx consuming the same ref -> conflict.
        errs = []
        def try_conflict():
            try:
                provider.commit([ref], tx2, party)
            except UniquenessException as e:
                errs.append(e)
        t3 = threading.Thread(target=try_conflict)
        t3.start()
        for _ in range(50):
            c.pump()
            if errs:
                break
            import time
            time.sleep(0.01)
        t3.join(timeout=5)
        assert errs and errs[0].conflict.consumed

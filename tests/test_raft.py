"""Raft consensus tests: election, replication, conflict detection,
leader failover, partition catch-up (reference coverage parity:
`RaftValidatingNotaryServiceTests.kt` + DistributedImmutableMap tests).
Fully deterministic: ticks + manual message pumping, no wall-clock."""
from collections import deque

import pytest

from corda_tpu.core.contracts import StateRef
from corda_tpu.core.crypto import SecureHash, crypto
from corda_tpu.core.identity import Party
from corda_tpu.node.database import NodeDatabase
from corda_tpu.node.notary import RaftUniquenessProvider, UniquenessException
from corda_tpu.node.raft import LEADER, NotLeaderError, RaftNode


class Cluster:
    """N RaftNodes over a deterministic in-memory transport."""

    def __init__(self, n=3, with_db=False, apply_fn=None):
        self.queue = deque()  # (src, dst, payload)
        self.partitioned = set()  # node ids cut off from the world
        self.nodes = {}
        self.applied = {i: [] for i in range(n)}
        ids = [f"n{i}" for i in range(n)]
        for i, node_id in enumerate(ids):
            db = NodeDatabase(":memory:") if with_db else None

            def make_apply(idx):
                def apply(cmd):
                    self.applied[idx].append(cmd)
                    return {"conflicts": {}}
                return apply

            def make_transport(src):
                def transport(dst, payload):
                    self.queue.append((src, dst, payload))
                return transport

            self.nodes[node_id] = RaftNode(
                node_id, ids, make_transport(node_id),
                apply_fn(i) if apply_fn else make_apply(i),
                db=db, seed=i,
            )

    def pump(self, max_rounds=200):
        rounds = 0
        while self.queue and rounds < max_rounds:
            src, dst, payload = self.queue.popleft()
            if src in self.partitioned or dst in self.partitioned:
                continue
            self.nodes[dst].on_message(src, payload)
            rounds += 1

    def tick_all(self, now):
        for node_id, node in self.nodes.items():
            if node_id not in self.partitioned:
                node.tick(now)
        self.pump()

    def elect(self, start=0.0):
        """Advance time until someone wins an election."""
        t = start
        for _ in range(100):
            t += 5
            self.tick_all(t)
            leaders = [n for n in self.nodes.values()
                       if n.is_leader and n.node_id not in self.partitioned]
            if leaders:
                return leaders[0], t
        raise AssertionError("no leader elected")


class TestRaft:
    def test_leader_election(self):
        c = Cluster(3)
        leader, _ = c.elect()
        followers = [n for n in c.nodes.values() if n is not leader]
        assert all(f.leader_id == leader.node_id for f in followers)

    def test_replication_and_apply_on_all(self):
        c = Cluster(3)
        leader, t = c.elect()
        fut = leader.submit({"kind": "putall", "entries": {"aa": b"x"}})
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}
        # Followers learn the commit index on the next heartbeat.
        for _ in range(3):
            t += 5
            c.tick_all(t)
        applied_counts = [len(v) for v in c.applied.values()]
        assert applied_counts == [1, 1, 1]

    def test_submit_to_follower_fails_fast(self):
        c = Cluster(3)
        leader, _ = c.elect()
        follower = next(n for n in c.nodes.values() if n is not leader)
        fut = follower.submit({"kind": "putall", "entries": {}})
        with pytest.raises(NotLeaderError) as err:
            fut.result(timeout=0)
        assert err.value.leader_hint == leader.node_id

    def test_leader_failover(self):
        c = Cluster(3)
        leader, t = c.elect()
        fut = leader.submit({"kind": "putall", "entries": {"k1": b"1"}})
        c.pump()
        fut.result(timeout=0)

        c.partitioned.add(leader.node_id)  # kill the leader
        new_leader, t = c.elect(start=t)
        assert new_leader.node_id != leader.node_id
        fut2 = new_leader.submit({"kind": "putall", "entries": {"k2": b"2"}})
        c.pump()
        assert fut2.result(timeout=0) == {"conflicts": {}}

        # Old leader rejoins and catches up.
        c.partitioned.discard(leader.node_id)
        for _ in range(10):
            t += 5
            c.tick_all(t)
        old = c.nodes[leader.node_id]
        assert not old.is_leader
        assert old.last_applied == new_leader.last_applied

    def test_log_survives_restart_with_db(self):
        c = Cluster(3, with_db=True)
        leader, _ = c.elect()
        fut = leader.submit({"kind": "putall", "entries": {"p": b"q"}})
        c.pump()
        fut.result(timeout=0)
        assert len(leader.log) == 1
        # New node instance from the same DB sees the persisted log/term.
        reloaded = RaftNode(
            leader.node_id, list(c.nodes), lambda d, p: None,
            lambda cmd: None, db=leader._meta.db, seed=99,
        )
        assert len(reloaded.log) == 1
        assert reloaded.current_term == leader.current_term


class TestRaftUniquenessProvider:
    def _provider_cluster(self):
        dbs = [NodeDatabase(":memory:") for _ in range(3)]
        providers = {}
        c = Cluster(3, apply_fn=lambda i: lambda cmd: providers[f"n{i}"].apply(cmd))
        for i, (node_id, node) in enumerate(c.nodes.items()):
            providers[node_id] = RaftUniquenessProvider(node, dbs[i])
        return c, providers

    def test_commit_and_conflict(self):
        c, providers = self._provider_cluster()
        leader, _ = c.elect()
        provider = providers[leader.node_id]
        party = Party(
            "O=Notary,L=Zurich,C=CH", crypto.entropy_to_keypair(1).public
        )
        tx1 = SecureHash.sha256(b"tx1")
        tx2 = SecureHash.sha256(b"tx2")
        ref = StateRef(SecureHash.sha256(b"issue"), 0)

        import threading
        done = []
        thread = threading.Thread(
            target=lambda: done.append(provider.commit([ref], tx1, party))
        )
        thread.start()
        for _ in range(50):
            c.pump()
            if done:
                break
            import time
            time.sleep(0.01)
        thread.join(timeout=5)
        assert done  # committed

        # Same ref, same tx -> idempotent re-commit succeeds.
        t2 = threading.Thread(
            target=lambda: done.append(provider.commit([ref], tx1, party))
        )
        t2.start()
        for _ in range(50):
            c.pump()
            if len(done) > 1:
                break
            import time
            time.sleep(0.01)
        t2.join(timeout=5)
        assert len(done) == 2

        # Different tx consuming the same ref -> conflict.
        errs = []
        def try_conflict():
            try:
                provider.commit([ref], tx2, party)
            except UniquenessException as e:
                errs.append(e)
        t3 = threading.Thread(target=try_conflict)
        t3.start()
        for _ in range(50):
            c.pump()
            if errs:
                break
            import time
            time.sleep(0.01)
        t3.join(timeout=5)
        assert errs and errs[0].conflict.consumed


class TestSnapshotting:
    """Raft section-7 log compaction: applied prefixes fold into state-
    machine snapshots; lagging followers receive InstallSnapshot
    (reference: Copycat's log-compacting snapshottable
    DistributedImmutableMap)."""

    def _snap_cluster(self, n=3, threshold=5):
        state = {i: {} for i in range(n)}

        def make_apply(idx):
            def apply(cmd):
                state[idx].update(cmd["entries"])
                return {"conflicts": {}}
            return apply

        def make_snapshot(idx):
            def snap():
                from corda_tpu.core.serialization.codec import serialize
                return serialize(dict(state[idx]))
            return snap

        def make_restore(idx):
            def restore(data):
                from corda_tpu.core.serialization.codec import deserialize
                state[idx].clear()
                state[idx].update(deserialize(data))
            return restore

        c = Cluster(n, apply_fn=make_apply)
        for i, (node_id, node) in enumerate(c.nodes.items()):
            node.SNAPSHOT_THRESHOLD = threshold
            node.snapshot_fn = make_snapshot(i)
            node.restore_fn = make_restore(i)
        return c, state

    def test_log_truncates_after_threshold(self):
        c, state = self._snap_cluster(threshold=5)
        leader, _ = c.elect()
        for i in range(12):
            fut = leader.submit({"entries": {f"k{i}": f"v{i}"}})
            c.pump()
            assert fut.result(timeout=1) == {"conflicts": {}}
        # the leader's log folded its applied prefix into snapshots
        assert leader.snap_index >= 5
        assert len(leader.log) < 12
        # logical bookkeeping intact
        assert leader.last_index() == 11
        assert leader.commit_index == 11
        # state machine saw everything exactly once
        leader_idx = list(c.nodes).index(leader.node_id)
        assert state[leader_idx] == {f"k{i}": f"v{i}" for i in range(12)}

    def test_replication_continues_across_snapshots(self):
        c, state = self._snap_cluster(threshold=4)
        leader, _ = c.elect()
        for i in range(10):
            fut = leader.submit({"entries": {f"x{i}": "1"}})
            c.pump()
            fut.result(timeout=1)
        # heartbeat so followers learn the final commit index
        c.tick_all(leader._now + 4)
        for idx, s in state.items():
            assert len(s) == 10, f"replica {idx} diverged: {len(s)}"

    def test_lagging_follower_installs_snapshot(self):
        c, state = self._snap_cluster(n=3, threshold=3)
        leader, _ = c.elect()
        # partition one follower, commit enough to snapshot past its log
        follower_id = next(iter(set(c.nodes) - {leader.node_id}))
        c.partitioned.add(follower_id)
        for i in range(8):
            fut = leader.submit({"entries": {f"p{i}": "1"}})
            c.pump()
            fut.result(timeout=1)
        assert leader.snap_index >= 3
        # heal: the follower is behind the leader's snapshot boundary
        c.partitioned.clear()
        for _ in range(6):
            c.tick_all(c.nodes[leader.node_id]._now + 4)
        follower = c.nodes[follower_id]
        follower_idx = list(c.nodes).index(follower_id)
        assert follower.snap_index >= 3  # InstallSnapshot arrived
        assert state[follower_idx] == state[list(c.nodes).index(leader.node_id)]

    def test_snapshot_survives_restart(self):
        from corda_tpu.core.serialization.codec import deserialize, serialize
        from corda_tpu.node.database import NodeDatabase
        from corda_tpu.node.raft import RaftNode

        db = NodeDatabase(":memory:")
        state = {}

        def apply(cmd):
            state.update(cmd["entries"])
            return {}

        node = RaftNode(
            "solo", ["solo"], lambda d, p: None, apply, db=db, seed=1,
            snapshot_fn=lambda: serialize(dict(state)),
            restore_fn=lambda data: (state.clear(), state.update(deserialize(data)))[0],
        )
        node.SNAPSHOT_THRESHOLD = 3
        node.tick(100)  # single-node cluster elects itself
        assert node.is_leader
        for i in range(7):
            fut = node.submit({"entries": {f"s{i}": "1"}})
            fut.result(timeout=1)
        assert node.snap_index >= 3
        # restart from the same db: snapshot restores + tail replays
        state2 = {}

        def apply2(cmd):
            state2.update(cmd["entries"])
            return {}

        restored_from = {}
        node2 = RaftNode(
            "solo", ["solo"], lambda d, p: None, apply2, db=db, seed=1,
            snapshot_fn=lambda: serialize(dict(state2)),
            restore_fn=lambda data: restored_from.update(deserialize(data)),
        )
        assert node2.snap_index == node.snap_index
        assert restored_from  # snapshot content restored
        assert len(node2.log) == node.last_index() - node.snap_index

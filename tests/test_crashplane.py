"""Crash-consistency plane (ISSUE 20, docs/robustness.md §7): the
simulated power-cut storage semantics, the CRC frame + quarantine
contract, the crash-point recovery matrix (including the pinned
--break-recovery RED verdict — proof the matrix has teeth), the
restart-storm disruption, and the gate/bench wiring.
"""
import json
import os
import random
import struct
import tempfile
import uuid

import pytest

from corda_tpu.node import recovery
from corda_tpu.testing import crashstore
from corda_tpu.utils import atomicfile, faultpoints


# ---------------------------------------------------------------------------
# crashstore: the power-cut model itself


class TestCrashDiskSemantics:
    def setup_method(self):
        self.wd = tempfile.mkdtemp(prefix="crashplane-")

    def p(self, name):
        return os.path.join(self.wd, name)

    def test_unsynced_writes_can_vanish_fsynced_cannot(self):
        lost_any = False
        for seed in range(20):
            disk = crashstore.CrashDisk(rng=random.Random(seed))
            with disk.open(self.p(f"durable-{seed}"), "wb") as fh:
                fh.write(b"D" * 2048)
                disk.fsync_fh(fh)
            disk.fsync_dir(self.wd)
            with disk.open(self.p(f"loose-{seed}"), "wb") as fh:
                fh.write(b"L" * 2048)
            disk.power_cut()
            with open(self.p(f"durable-{seed}"), "rb") as fh:
                assert fh.read() == b"D" * 2048, "fsync'd data damaged"
            loose = self.p(f"loose-{seed}")
            if not os.path.exists(loose):
                lost_any = True
            else:
                with open(loose, "rb") as fh:
                    if fh.read() != b"L" * 2048:
                        lost_any = True
        assert lost_any, "20 seeds never lost an unsynced write"

    def test_unsynced_pages_tear_at_byte_boundaries(self):
        torn = False
        for seed in range(30):
            disk = crashstore.CrashDisk(rng=random.Random(seed))
            with disk.open(self.p(f"t-{seed}"), "wb") as fh:
                fh.write(bytes(range(256)) * 16)  # 4 KiB, 8 pages
            stats = disk.power_cut()
            if any(s["torn"] for s in stats.values()):
                torn = True
                break
        assert torn, "30 seeds never produced a torn page"

    def test_app_buffer_lost_on_proc_crash_unless_flushed(self):
        disk = crashstore.CrashDisk(rng=random.Random(0))
        f1 = disk.open(self.p("flushed"), "wb")
        f1.write(b"F" * 100)
        f1.flush()
        f2 = disk.open(self.p("buffered"), "wb")
        f2.write(b"B" * 100)
        # no flush: the bytes live in the app buffer only
        disk.proc_crash()
        with open(self.p("flushed"), "rb") as fh:
            assert fh.read() == b"F" * 100
        assert (not os.path.exists(self.p("buffered"))
                or open(self.p("buffered"), "rb").read() == b"")

    def test_fsynced_file_pins_its_own_create(self):
        """ext4 auto_da_alloc rule: a CREATE whose file data was later
        fsync'd survives the cut even without fsync(dir) — the journal
        orders the dirent before the data commit."""
        for seed in range(10):
            disk = crashstore.CrashDisk(rng=random.Random(seed))
            path = self.p(f"pinned-{seed}")
            with disk.open(path, "wb") as fh:
                fh.write(b"P" * 512)
                disk.fsync_fh(fh)
            disk.power_cut()
            assert os.path.exists(path), (
                f"seed {seed}: fsync'd file's create vanished"
            )
            with open(path, "rb") as fh:
                assert fh.read() == b"P" * 512

    def test_atomic_write_with_fsync_survives_every_seed(self):
        for seed in range(25):
            target = self.p(f"atomic-{seed}.json")
            disk = crashstore.CrashDisk(rng=random.Random(seed))
            with crashstore.interpose(disk):
                atomicfile.write_json_atomic(target, {"v": 1})
                atomicfile.write_json_atomic(target, {"v": 2})
                disk.power_cut()
            with open(target) as fh:
                assert json.load(fh)["v"] in (1, 2)

    def test_snapshot_sqlite_images_the_live_wal(self):
        from corda_tpu.node.database import NodeDatabase

        dbp = self.p("live.db")
        db = NodeDatabase(dbp)
        db.execute("CREATE TABLE t (n INTEGER)")
        for i in range(300):
            db.execute("INSERT INTO t VALUES (?)", (i,))
        disk = crashstore.CrashDisk(rng=random.Random(1))
        disk.sqlite_paths.append(dbp)
        snap = disk.snapshot_sqlite(self.p("img"))
        torn = disk.tear_sqlite_wal(snap.values())
        db.close()
        assert torn, "no WAL to tear — snapshot missed the live image"
        db2 = NodeDatabase(snap[dbp])
        rows = db2.query("SELECT COUNT(*) FROM t")
        db2.close()
        # sqlite's per-frame WAL checksums absorb the torn tail: SOME
        # prefix of the rows is recovered, never an error, never more
        assert 0 <= rows[0][0] <= 300


# ---------------------------------------------------------------------------
# CRC frame + quarantine (satellite 2)


class TestFrameQuarantine:
    def test_frame_round_trip_and_legacy_passthrough(self):
        payload = b"checkpoint-blob" * 10
        assert recovery.unframe(recovery.frame(payload)) == payload
        legacy = b"not-framed-legacy-blob"
        assert recovery.unframe(legacy) == legacy

    def test_truncated_and_corrupt_frames_raise_typed(self):
        framed = recovery.frame(b"x" * 100)
        with pytest.raises(recovery.CorruptRecordError):
            recovery.unframe(framed[: len(framed) // 2])
        flipped = bytearray(framed)
        flipped[-1] ^= 0xFF
        with pytest.raises(recovery.CorruptRecordError):
            recovery.unframe(bytes(flipped))

    def test_hand_truncated_checkpoint_blob_quarantines_not_wedges(self):
        """The regression pin: a checkpoint row whose framed blob was
        torn mid-payload must be skipped-and-quarantined by
        all_checkpoints/get — never an exception out of startup."""
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.database import CheckpointStorage, NodeDatabase

        db = NodeDatabase(":memory:")
        store = CheckpointStorage(db)
        store.put("good", serialize({"flow_name": "G", "step": 1}))
        store.put("torn", serialize({"flow_name": "T", "step": 2}))
        row = db.query(
            "SELECT blob FROM checkpoints WHERE flow_id='torn'"
        )[0][0]
        with db.transaction() as cur:
            cur.execute(
                "UPDATE checkpoints SET blob=? WHERE flow_id='torn'",
                (row[: len(row) - 7],),
            )
        before = recovery.quarantined_records.value
        cps = dict(store.all_checkpoints())
        assert "good" in cps and "torn" not in cps
        assert store.get("torn") is None
        assert recovery.quarantined_records.value > before
        quarantined = store.quarantined()
        assert any(fid == "torn" for fid, _, _ in quarantined)
        db.close()

    def test_hand_truncated_journal_tail_replays_prefix(self):
        from corda_tpu.messaging.broker import Message, _Journal

        wd = tempfile.mkdtemp(prefix="crashplane-j-")
        jp = os.path.join(wd, "q.journal")
        j = _Journal(jp)
        ids = []
        for i in range(10):
            m = Message(payload=b"p%d" % i, headers={},
                        message_id=str(uuid.uuid4()))
            j.append_enqueue(m)
            ids.append(m.message_id)
        j.close()
        size = os.path.getsize(jp)
        with open(jp, "r+b") as fh:
            fh.truncate(size - 11)  # tear the last record mid-body
        pending = _Journal.replay(jp)
        got = [m.message_id for m in pending]
        assert got == ids[:9], "prefix replay broke on a torn tail"

    def test_corrupt_mid_journal_record_quarantines_the_tail(self):
        from corda_tpu.messaging.broker import (
            JOURNAL_MAGIC,
            Message,
            _Journal,
        )

        wd = tempfile.mkdtemp(prefix="crashplane-j2-")
        jp = os.path.join(wd, "q.journal")
        j = _Journal(jp)
        ids = []
        for i in range(6):
            m = Message(payload=b"payload-%d" % i, headers={},
                        message_id=str(uuid.uuid4()))
            j.append_enqueue(m)
            ids.append(m.message_id)
        j.close()
        with open(jp, "rb") as fh:
            data = bytearray(fh.read())
        assert data.startswith(JOURNAL_MAGIC)
        # flip one byte INSIDE record 4's body (after its crc) — frames
        # still parse, the crc catches it, the tail is set aside
        pos = len(JOURNAL_MAGIC)
        for _ in range(3):
            _, length = struct.unpack_from(">BI", data, pos)
            pos += 5 + length
        _, length = struct.unpack_from(">BI", data, pos)
        data[pos + 5 + 4 + 2] ^= 0xFF
        with open(jp, "wb") as fh:
            fh.write(bytes(data))
        before = recovery.quarantined_records.value
        pending = _Journal.replay(jp)
        assert [m.message_id for m in pending] == ids[:3]
        assert recovery.quarantined_records.value > before


# ---------------------------------------------------------------------------
# the verify_* detectors must actually detect (seeded violations)


class TestVerifyDetectors:
    def test_broker_verifier_catches_loss_and_ghost(self):
        wd = tempfile.mkdtemp(prefix="crashplane-v-")
        from corda_tpu.messaging.broker import Message, _Journal

        jp = os.path.join(wd, "q.journal")
        j = _Journal(jp)
        m = Message(payload=b"x", headers={}, message_id=str(uuid.uuid4()))
        j.append_enqueue(m)
        j.close()
        lost_id = str(uuid.uuid4())
        probs = recovery.verify_broker_journal(
            wd, sent={m.message_id, lost_id}, acked=set(),
            durable_sent={m.message_id, lost_id},
        )
        assert any("lost" in p for p in probs), probs
        probs = recovery.verify_broker_journal(
            wd, sent=set(), acked=set(), durable_sent=set(),
        )
        assert any("ghost" in p or "never sent" in p for p in probs), probs

    def test_consumption_verifier_catches_wrong_tx_owner(self):
        import hashlib

        from corda_tpu.core.contracts.structures import StateRef
        from corda_tpu.core.crypto.secure_hash import SecureHash
        from corda_tpu.node.database import NodeDatabase
        from corda_tpu.node.notary import PersistentUniquenessProvider

        class _P:
            name = "O=CrashPlane,L=Testland,C=ZZ"

        p = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        h = hashlib.sha256(b"crashplane-state").digest()
        tx_a = SecureHash(hashlib.sha256(b"tx-a").digest())
        p.commit([StateRef(SecureHash(h), 0)], tx_a, _P())
        key = h + (0).to_bytes(4, "big")
        expect_b = hashlib.sha256(b"tx-b").digest().hex()
        probs = recovery.verify_consumption([p], {key: expect_b})
        assert any("expected" in p for p in probs), probs
        # and the matching expectation is clean
        assert recovery.verify_consumption(
            [p], {key: tx_a.bytes.hex()}
        ) == []

    def test_flow_results_verifier_catches_duplicates(self):
        probs = recovery.verify_flow_results(
            {"f-1": ["tx-a"], "f-2": ["tx-b", "tx-b2"]}
        )
        assert any("exactly-once" in p for p in probs), probs


# ---------------------------------------------------------------------------
# crashmc: the matrix (subset in-process) + the pinned RED self-test


def _crashmc():
    import importlib
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    return importlib.import_module("crashmc")


class TestCrashMatrix:
    def test_registry_meets_coverage_floor(self):
        mc = _crashmc()
        mc._import_stores()
        assert len(faultpoints.CRASH_POINTS) >= mc.MIN_POINTS
        assert len(set(faultpoints.CRASH_POINTS.values())) >= mc.MIN_STORES

    def test_atomic_and_journal_points_recover_clean(self):
        mc = _crashmc()
        report = mc.run_matrix(
            points=["atomicfile.*", "journal.append_*"],
            seeds=2, require_coverage=False,
        )
        assert report.ok, report.failed_cells
        assert report.torn_stores.get("broker_journal", 0) > 0

    def test_checkpoint_point_recovers_clean(self):
        mc = _crashmc()
        report = mc.run_matrix(
            points=["checkpoint.put", "checkpoint.group_commit.drain"],
            seeds=2, require_coverage=False,
        )
        assert report.ok, report.failed_cells

    def test_break_recovery_turns_the_matrix_red(self):
        """The acceptance pin: a deliberately broken recovery path MUST
        fail the matrix. A matrix that stays green under sabotage is a
        rubber stamp, not a check."""
        mc = _crashmc()
        # crash at the first ACK append: all 30 enqueues are already
        # fsync-durable, so a replay sabotaged to return [] loses them
        report = mc.run_matrix(
            points=["journal.append_ack"], seeds=1,
            require_coverage=False, break_recovery="broker_journal",
        )
        assert not report.ok, (
            "sabotaged broker replay still passed the matrix"
        )
        assert any(
            "lost" in p for probs in report.failed_cells.values()
            for p in probs
        )

    def test_break_recovery_checkpoints_turns_red(self):
        mc = _crashmc()
        report = mc.run_matrix(
            points=["checkpoint.put"], seeds=1,
            require_coverage=False, break_recovery="checkpoints",
        )
        assert not report.ok

    def test_scenario_exception_is_a_red_cell_not_a_crash(self):
        mc = _crashmc()
        res = mc.run_cell("no.such.point", "broker_journal", 0)
        assert res["problems"], "a never-firing point must be red"


# ---------------------------------------------------------------------------
# restart_storm (satellite 1) with a deterministic fake victim


class _StormVictim:
    def __init__(self):
        self.kills = 0
        self.relaunches = 0
        self.alive = False
        self.completions = 0

    def kill(self):
        assert self.alive or self.kills == 0, "kill on a dead victim"
        self.kills += 1
        self.alive = False

    def relaunch(self):
        self.relaunches += 1
        self.alive = True
        self.completions += 3  # recovery makes progress


class TestRestartStorm:
    def test_storm_fires_n_relaunches_and_heal_asserts_progress(self):
        from corda_tpu.loadtest.disruption import restart_storm

        v = _StormVictim()
        v.alive = True
        d = restart_storm(
            v, probe=lambda: v.completions, relaunches=5,
            recovery_deadline_s=5,
        )
        rng = random.Random(0)
        d.fire(rng)
        assert d.state["fired"]
        assert v.kills == 5, "storm must kill 5 times"
        assert v.relaunches == 4, "4 mid-storm relaunches before heal"
        assert not v.alive, "last kill lands before the heal"
        d.heal(rng)
        assert v.alive, "heal leaves the final relaunch running"
        assert v.relaunches == 5

    def test_storm_heal_runs_the_invariant_verify(self):
        from corda_tpu.loadtest.disruption import restart_storm

        v = _StormVictim()
        v.alive = True
        d = restart_storm(
            v, probe=lambda: v.completions, relaunches=3,
            verify=lambda: ["seeded durability violation"],
            recovery_deadline_s=5,
        )
        rng = random.Random(1)
        d.fire(rng)
        with pytest.raises(AssertionError, match="durability"):
            d.heal(rng)

    def test_storm_heal_fails_on_no_progress(self):
        from corda_tpu.loadtest.disruption import restart_storm

        v = _StormVictim()
        v.alive = True
        d = restart_storm(
            v, probe=lambda: 0, relaunches=2, recovery_deadline_s=0.5,
        )
        rng = random.Random(2)
        d.fire(rng)
        with pytest.raises(AssertionError, match="no recovery"):
            d.heal(rng)


# ---------------------------------------------------------------------------
# soak gate --require (satellite 1) + bench direction (satellite 4)


class TestGateWiring:
    def _gate(self, record, argv):
        import importlib
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        os.pardir, "tools"))
        soak_gate = importlib.import_module("soak_gate")
        wd = tempfile.mkdtemp(prefix="crashplane-g-")
        path = os.path.join(wd, "rec.json")
        with open(path, "w") as fh:
            json.dump(record, fh)
        return soak_gate.main(["--current", path] + argv)

    def _record(self, events):
        return {
            "pairs": 10, "hard_error_rate": 0.0, "consistent": True,
            "events": events,
        }

    def test_require_passes_when_kind_fired_and_recovered(self):
        rec = self._record([
            [1.0, "restart_storm", "fired"],
            [4.0, "restart_storm", "recovered+5"],
        ])
        assert self._gate(rec, ["--require", "restart_storm"]) == 0

    def test_require_breaches_when_kind_absent(self):
        rec = self._record([[1.0, "restart", "fired"],
                            [2.0, "restart", "recovered+2"]])
        assert self._gate(rec, ["--require", "restart_storm"]) == 1

    def test_require_breaches_on_fired_without_recovery(self):
        rec = self._record([[1.0, "restart_storm", "fired"]])
        assert self._gate(rec, ["--require", "restart_storm"]) == 1

    def test_recovery_replay_gates_lower_is_better(self):
        from corda_tpu.loadtest.gate import direction

        assert direction("recovery_replay_ms") == "lower"

    def test_recovery_replay_stage_measures(self):
        from corda_tpu.loadtest.latency import measure_recovery_replay

        out = measure_recovery_replay(
            n_enqueued=300, n_acked=100, n_checkpoints=10,
        )
        assert out["recovery_replay_ms"] > 0
        assert out["recovery_pending_msgs"] == 200
        assert out["recovery_checkpoints"] == 10


# ---------------------------------------------------------------------------
# the env crash hook (the real-process slice's trigger)


class TestEnvCrashHook:
    def test_unset_env_does_not_arm(self, monkeypatch):
        monkeypatch.delenv("CORDA_TPU_CRASH_AT", raising=False)
        prev = faultpoints.hook
        assert faultpoints.install_env_crash_hook() is False
        assert faultpoints.hook is prev

    def test_armed_hook_ignores_other_points(self, monkeypatch):
        """The hook must pass every NON-matching point through — firing
        the matching point would SIGKILL this test process, which is
        exactly what tests/test_real_tier1.py exercises for real."""
        monkeypatch.setenv(
            "CORDA_TPU_CRASH_AT", "crashplane.never.fired:1"
        )
        prev = faultpoints.hook
        try:
            assert faultpoints.install_env_crash_hook() is True
            assert faultpoints.hook is not prev
            # any OTHER point is a no-op passthrough
            assert faultpoints.fire("some.other.point") is None
        finally:
            faultpoints.set_hook(prev)


# ---------------------------------------------------------------------------
# the atomic_write lint pass (satellite 3)


class TestAtomicWriteLint:
    def _run(self, src):
        from corda_tpu.analysis import astlint

        wd = tempfile.mkdtemp(prefix="crashplane-l-")
        path = os.path.join(wd, "mod.py")
        with open(path, "w") as fh:
            fh.write(src)
        return astlint.run_passes(
            paths=[path], root=wd, passes=["atomic_write"]
        )

    def test_direct_os_replace_is_flagged(self):
        findings = self._run(
            "import os\n\ndef f(a, b):\n    os.replace(a, b)\n"
        )
        assert len(findings) == 1
        assert findings[0].pass_id == "atomic_write"

    def test_suppression_with_reason_is_honoured(self):
        findings = self._run(
            "import os\n\ndef f(a, b):\n"
            "    os.replace(a, b)  # lint: allow(atomic_write) — seam\n"
        )
        assert findings == []

    def test_atomicfile_itself_is_exempt_in_repo_scan(self):
        from corda_tpu.analysis import astlint

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        findings = astlint.run_passes(
            paths=[os.path.join(repo, "corda_tpu/utils/atomicfile.py"),
                   os.path.join(repo, "corda_tpu/messaging/broker.py")],
            root=repo, passes=["atomic_write"],
        )
        assert findings == [], [f.message for f in findings]

"""Tools-tier tests: explorer, demobench (scripted), cordform deployment,
smoke-test NodeProcess (reference tools/explorer, tools/demobench,
cordformation, smoke-test-utils)."""
import io
import json
import os
import urllib.request

import pytest

from corda_tpu.core.contracts import Amount
from corda_tpu.rpc import CordaRPCOps
from corda_tpu.testing import MockNetwork
from corda_tpu.tools.cordform import deploy_nodes
from corda_tpu.tools.explorer import Explorer


class TestExplorer:
    """Explorer over in-process ops (same surface the RPC proxy serves)."""

    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.node = self.net.create_node("O=Exp,L=London,C=GB")
        self.ops = CordaRPCOps(self.node.services, self.node.smm)
        self.out = io.StringIO()
        self.ex = Explorer(self.ops, out=self.out)

    def teardown_method(self):
        self.net.stop_nodes()

    def _text(self) -> str:
        return self.out.getvalue()

    def test_info_network_flows(self):
        self.ex.info()
        assert "O=Exp,L=London,C=GB" in self._text()
        self.ex.network()
        assert "[notary]" in self._text()
        self.ex.flows()
        assert "0 flows in flight" in self._text()

    def test_balances_and_vault_after_issue(self):
        from corda_tpu.finance.flows import CashIssueFlow

        h = self.node.start_flow(CashIssueFlow(
            Amount(123_00, "USD"), b"\x01", self.node.info, self.notary.info
        ))
        self.net.run_network()
        h.result.result(timeout=10)
        self.ex.balances()
        assert "USD: 123.00" in self._text()
        self.ex.vault()
        assert "CashState" in self._text()
        self.ex.txs()
        assert "1 verified transactions" in self._text()

    def test_start_flow_and_metrics(self):
        from corda_tpu.core.flows import FlowLogic, startable_by_rpc

        @startable_by_rpc
        class ExpEcho(FlowLogic):
            def __init__(self, v):
                self.v = v

            def call(self):
                return self.v
                yield  # pragma: no cover

        # flow runs on the pumped network: pre-pump in the background is
        # unnecessary because the flow completes without suspending
        self.ex.start("ExpEcho", json.dumps([7]))
        assert "result: 7" in self._text()
        self.ex.metrics()
        assert "Flows.Started" in self._text()

    def test_unknown_command(self):
        assert self.ex.run_command(["bogus"]) is True
        assert "unknown command" in self._text()
        assert self.ex.run_command(["quit"]) is False


class TestCordform:
    def test_deploy_nodes_layout(self, tmp_path):
        spec = {
            "nodes": [
                {"name": "O=Notary,L=Zurich,C=CH", "notary": "validating",
                 "network_map_service": True},
                {"name": "O=Bank A,L=London,C=GB", "web": True},
                {"name": "O=Bank B,L=New York,C=US"},
            ]
        }
        resolved = deploy_nodes(spec, str(tmp_path))
        assert len(resolved) == 3
        assert (tmp_path / "runnodes").exists()
        assert os.access(tmp_path / "runnodes", os.X_OK)
        notary_conf = json.load(open(tmp_path / "Notary" / "node.conf"))
        assert notary_conf["network_map_service"] is True
        assert notary_conf["notary_type"] == "validating"
        map_addr = f"127.0.0.1:{notary_conf['broker_port']}"
        for d in ("BankA", "BankB"):
            conf = json.load(open(tmp_path / d / "node.conf"))
            assert conf["network_map"] == map_addr
            assert conf["rpc_users"][0]["username"] == "admin"

    def test_empty_descriptor_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            deploy_nodes({}, str(tmp_path))


@pytest.mark.slow
class TestSmokeAndDemobench:
    """Real OS processes: deploy via cordform, launch as a black box via
    NodeProcess, drive demobench scripted (reference smoke tests +
    DemoBench's node lifecycle)."""

    def test_node_process_black_box(self, tmp_path):
        from corda_tpu.testing.driver import free_port
        from corda_tpu.testing.smoketesting import Factory

        factory = Factory(str(tmp_path))
        conf = {
            "my_legal_name": "O=Smoke,L=London,C=GB",
            "broker_port": free_port(),
            "network_map_service": True,
            "rpc_users": [{"username": "admin", "password": "admin",
                           "permissions": ["ALL"]}],
        }
        with factory.create(conf) as node:
            assert node.alive()
            conn = node.connect()
            info = conn.proxy.node_info()
            assert info.name == "O=Smoke,L=London,C=GB"
            assert conn.proxy.network_map_snapshot()
            conn.close()
        assert not node.alive()

    def test_demobench_scripted(self, tmp_path):
        from corda_tpu.tools.demobench import DemoBench

        out = io.StringIO()
        bench = DemoBench(base_dir=str(tmp_path), out=out)
        try:
            script = io.StringIO("add Alpha --web\nlist\n")
            bench.repl(stream=script)
            text = out.getvalue()
            assert "node Alpha up" in text
            assert "webserver ready" in text
            assert "Alpha" in bench.nodes
            # the webserver really serves the node's RPC surface
            url = next(
                line.split()[-1] for line in text.splitlines()
                if "webserver ready" in line
            )
            with urllib.request.urlopen(url, timeout=10) as resp:
                assert resp.read() == b"started"
            bench.kill("Alpha")
            assert "Alpha stopped" in out.getvalue()
        finally:
            bench.shutdown()


@pytest.mark.slow
class TestCordformDeploymentBoots:
    """Capstone: a cordform-materialised network boots as real OS
    processes and settles a cross-node payment (reference
    TraderDemoTest-style integration over deployNodes output)."""

    def test_deployed_network_trades(self, tmp_path):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.testing.smoketesting import Factory

        spec = {
            "nodes": [
                {"name": "O=DeployNotary,L=Zurich,C=CH",
                 "notary": "validating", "network_map_service": True},
                {"name": "O=DeployBankA,L=London,C=GB"},
                {"name": "O=DeployBankB,L=Paris,C=FR"},
            ]
        }
        resolved = deploy_nodes(spec, str(tmp_path))
        factory = Factory(str(tmp_path))
        nodes = []
        try:
            # boot the directory node first so others can register
            for conf in resolved:
                nodes.append(factory.launch(conf["dir"]))
            conn_a = nodes[1].connect()
            conn_b = nodes[2].connect()
            ops_a, ops_b = conn_a.proxy, conn_b.proxy
            info_b = ops_b.node_info()
            notary_party = ops_a.notary_identities()[0]

            flow_id = ops_a.start_flow_dynamic(
                "CashIssueFlow", Amount(500_00, "USD"), b"\x01",
                ops_a.node_info(), notary_party,
            )
            ops_a.flow_result(flow_id, 60)
            token = Issued(ops_a.node_info().ref(1), "USD")
            flow_id = ops_a.start_flow_dynamic(
                "CashPaymentFlow", Amount(500_00, token), info_b,
                notary_party,
            )
            ops_a.flow_result(flow_id, 60)

            deadline = 30
            import time as _time

            t0 = _time.monotonic()
            while _time.monotonic() - t0 < deadline:
                states = ops_b.vault_query()
                if states:
                    break
                _time.sleep(0.3)
            assert states, "payment never reached bank B's vault"
            assert states[0].state.data.amount.quantity == 500_00
        finally:
            for n in nodes:
                n.close()


@pytest.mark.slow
class TestRealProcessLoadtest:
    def test_small_burst_consistent(self):
        from corda_tpu.loadtest.real import run

        result = run(pairs=6, parallelism=2)
        assert result["completed"] == 6
        assert result["errors"] == 0
        assert result["received_at_counterparty"] >= 6
        assert result["pairs_per_sec"] > 0


class TestExplorerAttachments:
    def test_put_and_exists(self, tmp_path):
        net = MockNetwork()
        node = net.create_node("O=ExpAtt,L=London,C=GB")
        ops = CordaRPCOps(node.services, node.smm)
        out = io.StringIO()
        ex = Explorer(ops, out=out)
        f = tmp_path / "doc.bin"
        f.write_bytes(b"attachment-payload")
        ex.attachments("PUT", str(f))
        text = out.getvalue()
        assert "uploaded" in text
        att_hex = text.split()[-1]
        from corda_tpu.core.crypto.secure_hash import SecureHash

        assert ops.attachment_exists(SecureHash(bytes.fromhex(att_hex)))
        net.stop_nodes()


@pytest.mark.slow
class TestShellAgainstLiveNode:
    """InteractiveShell over RPC to a REAL node process: flow start with
    live ProgressTracker rendering, flow watch, vault and network views
    (round-2 VERDICT weak #8 — the shell's flow watch was untested
    against an OS-process node)."""

    def test_shell_flow_start_watch_and_vault(self):
        import io
        import tempfile

        from corda_tpu.node.shell import InteractiveShell
        from corda_tpu.testing.smoketesting import Factory
        from corda_tpu.tools.cordform import deploy_nodes

        base = tempfile.mkdtemp(prefix="shell-live-")
        spec = {
            "nodes": [
                {"name": "O=ShellNotary,L=Zurich,C=CH",
                 "notary": "validating", "network_map_service": True},
                {"name": "O=ShellBank,L=London,C=GB"},
            ]
        }
        resolved = deploy_nodes(spec, base)
        factory = Factory(base)
        nodes = [factory.launch(conf["dir"]) for conf in resolved]
        try:
            conn = nodes[1].connect()
            try:
                out = io.StringIO()
                shell = InteractiveShell(conn.proxy, stdout=out)

                shell.onecmd("flow list")
                assert "CashIssueFlow" in out.getvalue()

                me = conn.proxy.node_info().name
                notary = conn.proxy.notary_identities()[0].name
                shell.onecmd(
                    "flow start CashIssueFlow amount: 500 USD, "
                    f"issuer_ref: 0x01, recipient: {me}, notary: {notary}"
                )
                text = out.getvalue()
                # the tracked start completed and printed the result line
                # (CashIssueFlow carries no ProgressTracker steps; the
                # tracked feed itself is exercised end-to-end over RPC)
                assert "returned:" in text, text
                assert "SignedTransaction" in text, text
                assert "error:" not in text, text

                shell.onecmd("vault")
                assert "USD" in out.getvalue()  # the issued cash state

                shell.onecmd("flow watch")  # live SMM feed: no crash
                shell.onecmd("network")
                assert "ShellNotary" in out.getvalue()
            finally:
                conn.close()
        finally:
            for n in nodes:
                n.close()


class TestBFTClusterExpansion:
    """cordform's BFT expansion: per-member RANDOM signing seeds (private
    seed only in the member's own conf), shared publics, and seed/pub
    consistency — the key-distribution contract _make_bft_notary_service
    relies on."""

    def test_seeds_unique_and_consistent(self, tmp_path):
        import json
        import os

        from corda_tpu.core.crypto import ed25519_math
        from corda_tpu.tools.cordform import deploy_nodes

        resolved = deploy_nodes(
            {"nodes": [{"name": "O=ExpBFT,L=Zurich,C=CH", "notary": "bft",
                        "cluster_size": 4}]},
            str(tmp_path),
        )
        assert len(resolved) == 4
        seeds, pubs = [], []
        shared_pub_lists = []
        for i, conf_entry in enumerate(resolved):
            conf = json.load(
                open(os.path.join(conf_entry["dir"], "node.conf"))
            )
            block = conf["bft_cluster"]
            assert block["index"] == i
            seed = bytes.fromhex(block["signing_seed"])
            member = block["members"][i]
            # the private seed matches the member's shared public key
            assert ed25519_math.public_from_seed(seed).hex() == (
                member["signing_pub"]
            )
            seeds.append(seed)
            pubs.append(member["signing_pub"])
            shared_pub_lists.append(
                [m["signing_pub"] for m in block["members"]]
            )
        # every member's conf carries the SAME public-key list
        assert all(pl == shared_pub_lists[0] for pl in shared_pub_lists)
        assert len(set(seeds)) == 4, "signing seeds must be random per member"
        assert len(set(pubs)) == 4

    def test_undersized_bft_cluster_rejected(self, tmp_path):
        import pytest as _pytest

        from corda_tpu.tools.cordform import deploy_nodes

        with _pytest.raises(ValueError, match="cluster_size >= 4"):
            deploy_nodes(
                {"nodes": [{"name": "O=SmallBFT,L=X,C=GB", "notary": "bft",
                            "cluster_size": 3}]},
                str(tmp_path),
            )


class TestDemobenchFleetWeb:
    """The fleet panel (reference tools/demobench's JavaFX shell as a
    browser page): spawn/stop nodes and tail logs through the JSON API
    the page uses."""

    def test_fleet_api_drives_network(self, tmp_path):
        import json
        import urllib.request

        from corda_tpu.tools.demobench import DemoBench, FleetWebServer

        out = io.StringIO()
        bench = DemoBench(base_dir=str(tmp_path), out=out)
        server = FleetWebServer(bench)
        base = f"http://127.0.0.1:{server.port}"

        def post(path, payload):
            req = urllib.request.Request(
                base + path, data=json.dumps(payload).encode(),
                headers={"content-type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=180) as resp:
                return json.loads(resp.read())

        def get(path):
            with urllib.request.urlopen(base + path, timeout=30) as resp:
                return json.loads(resp.read())

        try:
            # the page itself serves
            with urllib.request.urlopen(base + "/", timeout=10) as resp:
                assert b"demobench fleet" in resp.read()
            # drive a notary + bank network through the API
            r = post("/fleet/add", {"name": "Notary", "notary": True})
            assert r["broker_port"] > 0
            post("/fleet/add", {"name": "BankA"})
            fleet = get("/fleet")
            names = {n["name"]: n for n in fleet["nodes"]}
            assert names["Notary"]["alive"] and names["Notary"]["notary"]
            assert names["BankA"]["alive"] and not names["BankA"]["notary"]
            assert names["Notary"]["network_map"]  # first node hosts the map
            log = get("/fleet/logs?name=BankA&tail=50")["log"]
            assert log  # the node wrote something on boot
            # stop one node from the panel
            post("/fleet/kill", {"name": "BankA"})
            fleet = get("/fleet")
            assert all(n["name"] != "BankA" for n in fleet["nodes"])
            # error surfaces as JSON, not a crash
            try:
                post("/fleet/kill", {"name": "Nope"})
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()
            bench.shutdown()

"""The DEFAULT-tier real-process slice: a budgeted 2-process issue+pay
over real TCP brokers with a mid-run shard-worker SIGKILL (ISSUE 14
acceptance), plus the fleet-observatory stitch check — one trace joined
across >= 2 OS processes from their /traces/export feeds (ISSUE 17
acceptance).

Everything else that boots OS processes lives in the nightly heavy tier
(conftest._HEAVY_FILES) — the driver's default run used to see zero real
processes (61 skips). This file is deliberately NOT in the heavy set:
one small, tightly budgeted scenario keeps process-separation fidelity
(fork/exec, TCP broker wire, durable journals, supervisor respawn,
cross-process RPC rerouting) in every tier-1 run.

Budget: the whole scenario must finish inside ``_BUDGET_S`` (60 s) on a
1-core CI box — measured ~8 s warm. Skips are NAMED and narrow: no free
TCP port, or no fork support. Anything else that goes wrong is a
FAILURE, never a silent skip.
"""
import os
import socket
import subprocess
import sys
import tempfile
import time

import pytest

#: hard wall for the whole scenario (the ISSUE's <60 s acceptance)
_BUDGET_S = 60.0


def _skip_reason():
    """Only the two legitimate environmental skips, by name."""
    if not hasattr(os, "fork"):
        return "os.fork unavailable on this platform"
    try:
        probe = socket.socket()
        try:
            probe.bind(("127.0.0.1", 0))
        finally:
            probe.close()
    except OSError as exc:
        return f"no free TCP port on 127.0.0.1: {exc}"
    return None


def _find_worker_pids(node_dir: str):
    """PIDs of `--shard-worker` processes spawned for node_dir, via the
    same /proc scan the remote soak driver uses."""
    from corda_tpu.loadtest.remote import LocalSession, parse_hosts

    session = LocalSession(parse_hosts("local")[0])
    return session.find_pids(f"{node_dir} --shard-worker")


def test_two_node_tcp_issue_pay_with_worker_kill(monkeypatch):
    """Boot a 2-process network (validating notary + network map, and a
    bank running its flow path in ONE shard-worker OS process), drive
    issue+pay pairs over real TCP, SIGKILL the bank's worker mid-run,
    and require: pairs RESUME after the supervisor respawns it (unacked
    redelivery + checkpoint restore + the flow_result reroute), and the
    end state is no-loss/no-dup on the counterparty ledger."""
    reason = _skip_reason()
    if reason:
        pytest.skip(reason)
    # the kill can land before the in-flight flow's FIRST checkpoint —
    # that flow is legitimately lost and its flow_result wait only ends
    # at the driver's deadline. Scale every procdriver wait down so the
    # worst-case single stall fits the tier-1 budget with room.
    monkeypatch.setenv("CORDA_TPU_LOADTEST_DEADLINE_S", "15")

    from corda_tpu.loadtest.procdriver import (
        PairDriver,
        assert_no_loss_no_dup,
        resolve_identities,
    )
    from corda_tpu.testing.smoketesting import Factory
    from corda_tpu.tools.cordform import deploy_nodes

    t0 = time.monotonic()

    def budget_left(phase: str) -> float:
        left = _BUDGET_S - (time.monotonic() - t0)
        assert left > 0, (
            f"tier-1 real-process budget ({_BUDGET_S}s) exhausted "
            f"during {phase}"
        )
        return left

    base = tempfile.mkdtemp(prefix="t1-real-")
    spec = {"nodes": [
        {"name": "O=T1Notary,L=Zurich,C=CH", "notary": "validating",
         "network_map_service": True},
        {"name": "O=T1Bank,L=London,C=GB", "node_workers": 1},
    ]}
    resolved = deploy_nodes(spec, base)
    factory = Factory(base)
    nodes = []
    driver = None
    try:
        for conf in resolved:
            nodes.append(
                factory.launch(conf["dir"], timeout=budget_left("boot"))
            )
        # the bank node pays the notary-host node's own identity: two
        # processes give the full wire (bank worker -> supervisor broker
        # -> bridge -> notary broker) without a third boot on the budget
        me, notary, peer = resolve_identities(nodes[1], nodes[0])
        driver = PairDriver(nodes[1], notary, me, peer).start()
        while len(driver.completed) < 3:
            budget_left("warm-up")
            assert driver._thread.is_alive(), (
                f"driver died during warm-up: {driver.errors[-3:]}"
            )
            time.sleep(0.2)

        # mid-run disruption: SIGKILL the bank's ONLY shard worker
        pids = _find_worker_pids(resolved[1]["dir"])
        assert pids, "no shard-worker process visible in /proc"
        os.kill(pids[0], 9)
        before = len(driver.completed)

        # recovery, not survival: pairs must RESUME through the respawn
        while len(driver.completed) < before + 3:
            budget_left("post-kill recovery")
            time.sleep(0.2)

        # the supervisor respawned the worker (new pid, same duty)
        deadline = time.monotonic() + min(20.0, budget_left("respawn"))
        while time.monotonic() < deadline:
            fresh = _find_worker_pids(resolved[1]["dir"])
            if fresh and fresh != pids:
                break
            time.sleep(0.3)
        fresh = _find_worker_pids(resolved[1]["dir"])
        assert fresh and fresh != pids, (
            f"worker never respawned: before={pids} after={fresh}"
        )

        driver.stop(timeout=budget_left("driver stop"))
        assert_no_loss_no_dup(driver, nodes[0])
        assert len(driver.completed) >= before + 3
    finally:
        if driver is not None and not driver._stop.is_set():
            try:
                driver.stop(timeout=5)
            except BaseException:
                pass  # lint: allow(swallow) — teardown must close the nodes
        for n in nodes:
            n.close()


def test_fleet_observatory_stitches_one_trace_across_processes():
    """Boot the 2-process network with an ops endpoint on BOTH nodes,
    drive issue+pay pairs over real TCP, then run the fleet collector
    over them and require ONE stitched trace whose spans came from >= 2
    OS processes — including the verifier batch and the notary commit —
    i.e. the W3C traceparent really rode the broker wire between
    processes and the observatory really joined the stores
    (docs/observability.md, fleet observatory)."""
    reason = _skip_reason()
    if reason:
        pytest.skip(reason)

    from corda_tpu.loadtest.observatory import FleetCollector, NodeProbe
    from corda_tpu.loadtest.procdriver import PairDriver, resolve_identities
    from corda_tpu.loadtest.remote import LocalSession, parse_hosts
    from corda_tpu.testing.smoketesting import Factory
    from corda_tpu.tools.cordform import deploy_nodes

    t0 = time.monotonic()

    def budget_left(phase: str) -> float:
        left = _BUDGET_S - (time.monotonic() - t0)
        assert left > 0, (
            f"tier-1 fleet-stitch budget ({_BUDGET_S}s) exhausted "
            f"during {phase}"
        )
        return left

    base = tempfile.mkdtemp(prefix="t1-fleet-")
    spec = {"nodes": [
        {"name": "O=T1FleetNotary,L=Zurich,C=CH", "notary": "validating",
         "network_map_service": True, "ops_port": 0},
        {"name": "O=T1FleetBank,L=London,C=GB", "ops_port": 0},
    ]}
    resolved = deploy_nodes(spec, base)
    factory = Factory(base)
    nodes = []
    driver = None
    try:
        for conf in resolved:
            nodes.append(
                factory.launch(conf["dir"], timeout=budget_left("boot"))
            )
        for node in nodes:
            assert node.ops_port, (
                "ready.json carried no ops_port despite ops_port:0 in "
                "the node spec"
            )
        me, notary, peer = resolve_identities(nodes[1], nodes[0])
        driver = PairDriver(nodes[1], notary, me, peer).start()
        while len(driver.completed) < 2:
            budget_left("pairs")
            assert driver._thread.is_alive(), (
                f"driver died: {driver.errors[-3:]}"
            )
            time.sleep(0.2)
        driver.stop(timeout=budget_left("driver stop"))

        session = LocalSession(parse_hosts("local")[0])
        collector = FleetCollector([
            NodeProbe("notary", session, nodes[0].ops_port,
                      timeout_s=budget_left("collect")),
            NodeProbe("bank", session, nodes[1].ops_port,
                      timeout_s=budget_left("collect")),
        ])
        ok = collector.poll_once()
        assert ok == {"notary": True, "bank": True}, ok

        traces = collector.stitched()
        cross = [
            t for t in traces.values() if len(t.get("nodes", ())) >= 2
        ]
        assert cross, (
            "no stitched trace spans >= 2 OS processes; "
            f"stitched={len(traces)}"
        )
        # the notarised pair's tree: bank-side flow + notary-side
        # verifier batch and commit, joined under ONE trace id
        def names(t):
            return {s["name"] for s in t["spans"]}

        full = [
            t for t in cross
            if any(n.startswith("notary.") for n in names(t))
            and "verifier.batch" in names(t)
        ]
        assert full, (
            "no cross-process trace reached verifier batch + notary "
            f"commit; cross-node names={[sorted(names(t)) for t in cross]}"
        )
        span_nodes = {s["fleet_node"] for s in full[0]["spans"]}
        assert {"notary", "bank"} <= span_nodes
        assert collector.capture()["cross_node_traces"] >= 1
    finally:
        if driver is not None and not driver._stop.is_set():
            try:
                driver.stop(timeout=5)
            except BaseException:
                pass  # lint: allow(swallow) — teardown must close the nodes
        for n in nodes:
            n.close()


def test_two_domain_notary_change_survives_old_notary_kill():
    """ISSUE 19 acceptance: a cross-domain payment via notary-change on
    a REAL 3-process, 2-domain TCP network survives the SIGKILL of the
    OLD domain's notary mid-protocol. The change is parked at CONSUME
    (old notary SIGSTOPped) with the durable journal at phase "prepare"
    — verified by reading the instigator's sqlite from outside the
    process — then the notary is SIGKILLed and relaunched; unacked
    redelivery + the idempotent notary commits land the re-pin on
    EXACTLY one owning notary: the coin is invisible to domain A's coin
    selection, pays out once under domain B, and the stale ref draws a
    conflict at notary B."""
    reason = _skip_reason()
    if reason:
        pytest.skip(reason)

    import sqlite3

    from corda_tpu.core.contracts import Amount, StateAndRef, StateRef
    from corda_tpu.core.contracts.amount import Issued
    from corda_tpu.core.serialization.codec import deserialize
    from corda_tpu.testing.smoketesting import Factory
    from corda_tpu.tools.cordform import deploy_nodes

    t0 = time.monotonic()

    def budget_left(phase: str) -> float:
        left = _BUDGET_S - (time.monotonic() - t0)
        assert left > 0, (
            f"tier-1 two-domain budget ({_BUDGET_S}s) exhausted "
            f"during {phase}"
        )
        return left

    base = tempfile.mkdtemp(prefix="t1-domains-")
    # the BANK hosts the map directory so killing domain alpha's notary
    # never takes the network map down with it
    spec = {"nodes": [
        {"name": "O=T1DomBank,L=London,C=GB", "domain": "alpha",
         "network_map_service": True},
        {"name": "O=T1DomNotaryA,L=Zurich,C=CH", "notary": "validating",
         "domain": "alpha", "gateway": True},
        {"name": "O=T1DomNotaryB,L=Geneva,C=CH", "notary": "validating",
         "domain": "beta", "gateway": True},
    ]}
    resolved = deploy_nodes(spec, base)
    db_path = os.path.join(resolved[0]["dir"], "node.db")

    def journal_rows():
        """The instigator's notary-change journal, read OUTSIDE the node
        process — proof the intent is durable, not an in-memory map."""
        try:
            con = sqlite3.connect(f"file:{db_path}?mode=ro", uri=True)
            try:
                rows = con.execute(
                    "SELECT v FROM kv_notary_change_journal"
                ).fetchall()
            finally:
                con.close()
        except sqlite3.OperationalError:
            return []  # table not created yet
        return [deserialize(v) for (v,) in rows]

    factory = Factory(base)
    nodes = []
    try:
        for conf in resolved:
            nodes.append(
                factory.launch(conf["dir"], timeout=budget_left("boot"))
            )
        bank = nodes[0]
        conn = bank.connect()
        me = conn.proxy.node_info()
        notaries = conn.proxy.notary_identities()

        def notary_named(tag):
            hit = [n for n in notaries
                   if tag in n.name.replace(" ", "").lower()]
            assert hit, f"no notary matching {tag!r}: {notaries}"
            return hit[0]

        notary_a = notary_named("notarya")
        notary_b = notary_named("notaryb")

        stx = conn.proxy.start_flow_and_wait(
            "CashIssueFlow", Amount(9, "USD"), b"\x03", me, notary_a,
            timeout=budget_left("issue"),
        )
        original = StateAndRef(stx.tx.outputs[0], StateRef(stx.id, 0))

        # park the change at CONSUME: the old notary keeps its sockets
        # but stops responding, so the journal's "prepare" record is
        # written and the protocol can go no further
        nodes[1].suspend()
        fid = conn.proxy.start_flow_dynamic(
            "NotaryChangeFlow", original, notary_b,
        )
        rows = journal_rows()
        while not rows:
            budget_left("journal write")
            time.sleep(0.1)
            rows = journal_rows()
        assert [r["phase"] for r in rows] == ["prepare"], rows
        assert rows[0]["old"] == notary_a.name
        assert rows[0]["new"] == notary_b.name

        # the acceptance's disruption: SIGKILL the OLD domain's notary
        # after prepare, then bring a fresh process up on the same port
        nodes[1].kill()
        nodes[1] = factory.launch(
            resolved[1]["dir"], timeout=budget_left("notary relaunch")
        )
        moved = conn.proxy.flow_result(
            fid, budget_left("change completion")
        )
        assert moved.state.notary.name == notary_b.name, (
            f"re-pin landed on {moved.state.notary.name}"
        )
        assert journal_rows() == [], "journal must not outlive the change"

        # exactly-one-owner probes. Domain A: the migrated coin must be
        # ineligible to a builder pinned to the OLD notary
        token = Issued(me.ref(3), "USD")
        with pytest.raises(Exception, match="[Ii]nsufficient"):
            conn.proxy.start_flow_and_wait(
                "CashPaymentFlow", Amount(9, token), me, notary_a,
                timeout=budget_left("domain A probe"),
            )
        # Domain B: the SAME coin pays out exactly once under the new
        # notary (the cross-domain payment the change was for)
        conn.proxy.start_flow_and_wait(
            "CashPaymentFlow", Amount(9, token), me, notary_b,
            timeout=budget_left("domain B payment"),
        )
        # ...and the stale pre-payment ref draws a conflict at notary B
        # (a DIFFERENT consuming tx id, so idempotent replay can't mask
        # a fork)
        fid2 = conn.proxy.start_flow_dynamic(
            "NotaryChangeFlow", moved, notary_a,
        )
        with pytest.raises(Exception, match="[Cc]onflict|consumed"):
            conn.proxy.flow_result(fid2, budget_left("stale-ref probe"))
    finally:
        for n in nodes:
            n.close()


def test_budget_guard_never_skips_silently():
    """The skip guard names exactly two environmental reasons; on a
    healthy box it returns None (the scenario RUNS — the whole point of
    promoting it out of the 61-skip dead zone)."""
    reason = _skip_reason()
    assert reason is None or (
        "fork" in reason or "TCP port" in reason
    ), f"unnamed skip reason: {reason!r}"


def test_worker_pid_scan_excludes_the_scanner():
    """find_pids must not match its own sh/grep pipeline (killing the
    scanner instead of the worker silently voided the disruption)."""
    from corda_tpu.loadtest.remote import LocalSession, parse_hosts

    session = LocalSession(parse_hosts("local")[0])
    marker = "tier1-scan-marker-%d" % os.getpid()
    proc = subprocess.Popen(
        [sys.executable, "-c",
         f"import time  # {marker}\ntime.sleep(30)"],
    )
    try:
        deadline = time.monotonic() + 10
        pids = []
        while time.monotonic() < deadline:
            pids = session.find_pids(marker)
            if pids:
                break
            time.sleep(0.1)
        assert pids == [proc.pid], pids
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_sigkill_at_group_commit_drain_barrier_recovers(monkeypatch):
    """The crash-consistency plane's real-process slice (ISSUE 20,
    docs/robustness.md §7): boot a 2-process network, arm
    ``CORDA_TPU_CRASH_AT=checkpoint.group_commit.drain:3`` on the BANK
    only — the node SIGKILLs ITSELF (no teardown, no flush) the third
    time its checkpoint group-commit leader drains a batch, which lands
    inside the first issue+pay pair — relaunch the same node directory,
    and require the in-flight payment completed EXACTLY ONCE or is
    cleanly retryable: every payment the client saw complete is at the
    counterparty, the retried pair lands through the relaunched node,
    and no tx id ever pays more than one state (no replay dup)."""
    reason = _skip_reason()
    if reason:
        pytest.skip(reason)

    from corda_tpu.core.contracts import Amount
    from corda_tpu.core.contracts.amount import Issued
    from corda_tpu.loadtest.procdriver import (
        payment_txids,
        resolve_identities,
    )
    from corda_tpu.testing.smoketesting import Factory
    from corda_tpu.tools.cordform import deploy_nodes

    budget_s = 20.0
    t0 = time.monotonic()

    def budget_left(phase: str) -> float:
        left = budget_s - (time.monotonic() - t0)
        assert left > 0, (
            f"crash-barrier budget ({budget_s}s) exhausted during {phase}"
        )
        return left

    base = tempfile.mkdtemp(prefix="t1-crash-")
    spec = {"nodes": [
        {"name": "O=T1CrashNotary,L=Zurich,C=CH", "notary": "validating",
         "network_map_service": True},
        {"name": "O=T1CrashBank,L=London,C=GB"},
    ]}
    resolved = deploy_nodes(spec, base)
    factory = Factory(base)
    nodes = []
    try:
        nodes.append(
            factory.launch(resolved[0]["dir"], timeout=budget_left("boot"))
        )
        # armed for the bank's boot ONLY (Factory copies os.environ);
        # cleared before the relaunch so recovery runs unarmed. Boot
        # itself never drains (no flows yet) — the fuse burns during
        # the first pair's checkpoint writes.
        monkeypatch.setenv(
            "CORDA_TPU_CRASH_AT", "checkpoint.group_commit.drain:3"
        )
        bank = factory.launch(
            resolved[1]["dir"], timeout=budget_left("bank boot")
        )
        nodes.append(bank)
        monkeypatch.delenv("CORDA_TPU_CRASH_AT")

        me, notary, peer = resolve_identities(bank, nodes[0])
        token = Issued(me.ref(1), "USD")
        conn = bank.connect()
        completed = []
        try:
            for _ in range(10):
                budget_left("pre-crash pairs")
                fid = conn.proxy.start_flow_dynamic(
                    "CashIssueFlow", Amount(100, "USD"), b"\x01",
                    me, notary,
                )
                conn.proxy.flow_result(fid, 6)
                fid = conn.proxy.start_flow_dynamic(
                    "CashPaymentFlow", Amount(100, token), peer, notary,
                )
                stx = conn.proxy.flow_result(fid, 6)
                completed.append(stx.id)
        # lint: allow(swallow) — the dying node kills the RPC wire mid-
        except Exception:  # call; the barrier assert below is the check
            pass
        finally:
            try:
                conn.close()
            # lint: allow(swallow) — wire already dead with the node
            except Exception:
                pass

        # the process must be DEAD BY ITS OWN HAND at the barrier:
        # SIGKILL (rc -9), no graceful exit path involved
        deadline = time.monotonic() + budget_left("barrier kill")
        while bank.alive() and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not bank.alive(), (
            "CORDA_TPU_CRASH_AT=checkpoint.group_commit.drain:3 never "
            "killed the bank — the barrier did not fire"
        )
        assert bank._proc.poll() == -9, (
            f"bank exited rc={bank._proc.poll()}, not the barrier's "
            f"SIGKILL"
        )

        # cold relaunch of the SAME directory: journal replay +
        # checkpoint restore + quarantine-not-wedge, unarmed
        bank2 = factory.launch(
            resolved[1]["dir"], timeout=budget_left("relaunch")
        )
        nodes.append(bank2)

        # exactly-once-or-retryable: every payment the client SAW
        # complete must be at the counterparty (no loss)...
        txids, n_states = payment_txids(
            nodes[0], deadline_s=min(8.0, budget_left("vault check")),
            want=set(completed),
        )
        missing = set(completed) - txids
        assert not missing, f"acked payments LOST in the crash: {missing}"

        # ...and the pair interrupted mid-flight either landed (visible
        # as an extra txid) or is cleanly RETRYABLE through the
        # relaunched node — drive one full pair to prove the recovered
        # node serves
        conn2 = bank2.connect()
        try:
            fid = conn2.proxy.start_flow_dynamic(
                "CashIssueFlow", Amount(100, "USD"), b"\x01", me, notary,
            )
            conn2.proxy.flow_result(fid, budget_left("retry issue"))
            fid = conn2.proxy.start_flow_dynamic(
                "CashPaymentFlow", Amount(100, token), peer, notary,
            )
            stx = conn2.proxy.flow_result(fid, budget_left("retry pay"))
            completed.append(stx.id)
        finally:
            conn2.close()

        txids, n_states = payment_txids(
            nodes[0], deadline_s=budget_left("final check"),
            want=set(completed),
        )
        assert set(completed) <= txids, (
            f"retried payment lost: {set(completed) - txids}"
        )
        # EXACTLY once: each payment tx pays exactly one state to the
        # counterparty — a checkpoint-replayed dup would add a second
        # state under a replayed (or fresh) tx id
        assert n_states == len(txids), (
            f"replay duplicated payment states: {n_states} states over "
            f"{len(txids)} tx ids"
        )
    finally:
        for n in nodes:
            n.close()

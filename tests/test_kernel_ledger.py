"""Device-plane kernel flight ledger (utils/profiling.py, ISSUE 18).

Covers: the bounded per-dispatch ring and its strictly-after cursor
contract (including the restart-reset signal), the env kill switch and
ring-size knob, padding-occupancy and pipeline-stage labelling, the
jax-free XLA cost cache and roofline attainment math against the
op-budget pins, compile-event linkage, the tpu_capture provenance
stamp, the /kernels endpoint + node_kernels() RPC, Prometheus validity
of the Kernel.Ledger.* / Kernel.Attainment{...} families, the
fresh-subprocess proof that a scrape never imports jax, the gate
direction pins, the kernel_report CLI, and the acceptance proof: one
notarised MockNetwork transaction leaves ledger records with
scheme/bucket labels and populated cost-analysis flops on the CPU
backend.
"""
import json
import re
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from corda_tpu.utils import profiling


@pytest.fixture(autouse=True)
def _fresh_ledger():
    profiling.ledger_reset()
    yield
    profiling.ledger_reset()
    profiling.set_stage(None)


def _dispatch(kernel="ed25519.verify_batch", seconds=0.01, **kw):
    kw.setdefault("scheme", "EDDSA_ED25519_SHA512")
    kw.setdefault("bucket", "64")
    kw.setdefault("rows", 64)
    kw.setdefault("real_rows", 50)
    profiling.record_dispatch(kernel, seconds, **kw)


# ---------------------------------------------------------------------------
# the ring + cursor contract
# ---------------------------------------------------------------------------

class TestLedgerRing:
    def test_records_carry_the_dispatch_facts(self):
        _dispatch(donated=True, mesh_n=4, stage="mesh")
        page = profiling.ledger_since(0)
        assert page["enabled"] is True
        (rec,) = page["records"]
        assert rec["kernel"] == "ed25519.verify_batch"
        assert rec["scheme"] == "EDDSA_ED25519_SHA512"
        assert rec["bucket"] == "64"
        assert rec["rows"] == 64 and rec["real_rows"] == 50
        assert rec["occupancy_pct"] == pytest.approx(78.12)
        assert rec["donated"] is True
        assert rec["mesh_n"] == 4
        assert rec["stage"] == "mesh"
        assert rec["wall_s"] == pytest.approx(0.01)

    def test_cursor_is_strictly_after(self):
        for _ in range(3):
            _dispatch()
        page = profiling.ledger_since(0)
        assert [r["seq"] for r in page["records"]] == [1, 2, 3]
        assert page["next"] == 3 and page["newest"] == 3
        again = profiling.ledger_since(page["next"])
        assert again["records"] == []
        assert again["next"] == 3  # cursor holds position when drained
        _dispatch()
        fresh = profiling.ledger_since(3)
        assert [r["seq"] for r in fresh["records"]] == [4]

    def test_limit_pages_oldest_first(self):
        for _ in range(5):
            _dispatch()
        page = profiling.ledger_since(0, limit=2)
        assert [r["seq"] for r in page["records"]] == [1, 2]
        page = profiling.ledger_since(page["next"], limit=2)
        assert [r["seq"] for r in page["records"]] == [3, 4]

    def test_restart_reset_signal(self):
        for _ in range(4):
            _dispatch()
        cursor = profiling.ledger_since(0)["next"]
        profiling.ledger_reset()
        page = profiling.ledger_since(cursor)
        assert page["newest"] < cursor  # the collector's reset signal
        assert page["records"] == []

    def test_ring_bounded_by_env_knob(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_KERNEL_LEDGER_MAX", "16")
        profiling.ledger_reset()  # ring is built lazily at current max
        for _ in range(40):
            _dispatch()
        page = profiling.ledger_since(0, limit=1000)
        assert len(page["records"]) == 16
        assert page["records"][0]["seq"] == 25  # oldest were evicted
        assert page["newest"] == 40
        # totals keep counting past the ring: the ring bounds MEMORY,
        # not the attainment math
        att = profiling.attainment()["ed25519.verify_batch"]
        assert att["dispatches"] == 40

    def test_kill_switch_disables_ledger_not_aggregates(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_KERNEL_LEDGER", "0")
        before = profiling.dispatch_snapshot()["dispatch"].get(
            "ed25519.verify_batch", {}
        ).get("count", 0)
        _dispatch()
        page = profiling.ledger_since(0)
        assert page["enabled"] is False
        assert page["records"] == [] and page["attainment"] == {}
        # the pre-existing aggregate recorder is NOT gated
        after = profiling.dispatch_snapshot()["dispatch"][
            "ed25519.verify_batch"]
        assert after["count"] == before + 1
        assert not profiling.cost_analysis_enabled()

    def test_stage_comes_from_thread_local_unless_explicit(self):
        profiling.set_stage("dispatch")
        _dispatch()
        profiling.set_stage(None)
        _dispatch(stage="mesh")
        _dispatch()
        stages = [r["stage"] for r in profiling.ledger_since(0)["records"]]
        assert stages == ["dispatch", "mesh", None]


# ---------------------------------------------------------------------------
# cost analysis + attainment + compile events + provenance
# ---------------------------------------------------------------------------

class TestAttainment:
    def test_attainment_math_against_the_budget_pin(self):
        profiling.record_cost_analysis(
            "ed25519.verify_batch", "64", 64,
            {"flops": 64_000.0, "bytes accessed": 2_048.0},
            backend="cpu",
        )
        _dispatch(seconds=0.005)
        _dispatch(seconds=0.005)
        att = profiling.attainment()["ed25519.verify_batch"]
        assert att["dispatches"] == 2
        assert att["rows"] == 128 and att["real_rows"] == 100
        assert att["occupancy_pct"] == pytest.approx(78.12)
        assert att["achieved_sigs_s"] == pytest.approx(100 / 0.01)
        assert att["backend"] == "cpu"
        assert att["peak_sigs_s"] == profiling.PEAK_SIGS_S["cpu"]
        assert att["attainment_pct"] == pytest.approx(
            100.0 * (100 / 0.01) / profiling.PEAK_SIGS_S["cpu"], rel=1e-6
        )
        # flops: padded rows do the work (1000 flops/row x 128 rows)
        assert att["flops_per_row"] == pytest.approx(1000.0)
        assert att["achieved_flops_s"] == pytest.approx(1000.0 * 128 / 0.01)
        # the roofline's op-budget pin rides along (ops/opbudget_manifest)
        assert att["budget_field_mul_equiv_per_sig"] == pytest.approx(
            5665.3, abs=500
        )

    def test_attainment_gauge_is_minus_one_until_measured(self):
        assert profiling.attainment_value("ed25519.verify_batch") == -1.0
        _dispatch(seconds=0.01)
        assert profiling.attainment_value("ed25519.verify_batch") > 0.0
        assert profiling.attainment_value(
            "ecdsa.secp256r1.verify_batch"
        ) == -1.0

    def test_cost_analysis_list_shape_normalised(self):
        # some jax versions return [dict]; both shapes must cache
        profiling.record_cost_analysis(
            "ecdsa.secp256r1.verify_batch", "8", 8,
            [{"flops": 80.0, "bytes accessed": 16.0}],
        )
        entry = profiling.cost_analysis()[
            "ecdsa.secp256r1.verify_batch"]["8"]
        assert entry["flops"] == 80.0
        assert entry["bytes_accessed"] == 16.0
        assert entry["flops_per_row"] == pytest.approx(10.0)

    def test_compile_events_link_into_records(self):
        _dispatch()
        profiling.record_compile(
            "ed25519.batch_shape", bucket="64", seconds=0.25
        )
        _dispatch()
        page = profiling.ledger_since(0)
        (event,) = [e for e in page["compile_events"]
                    if e["seconds"] is not None]
        assert event["name"] == "ed25519.batch_shape"
        assert event["bucket"] == "64"
        before, after = page["records"]
        assert before["compile_seq"] < event["seq"]
        assert after["compile_seq"] == event["seq"]

    def test_provenance_stamps_ring_and_future(self):
        _dispatch()
        profiling.annotate_provenance({"live": True, "step": "bench-inline"})
        _dispatch()
        recs = profiling.ledger_since(0)["records"]
        assert all(
            r["provenance"] == {"live": True, "step": "bench-inline"}
            for r in recs
        )

    def test_ledger_gauges_shape(self):
        g = profiling.ledger_gauges()
        assert g["records"] == 0.0 and g["occupancy_pct"] == -1.0
        _dispatch()
        g = profiling.ledger_gauges()
        assert g["records"] == 1.0
        assert g["rows"] == 64.0 and g["real_rows"] == 50.0
        assert g["occupancy_pct"] == pytest.approx(78.12)


# ---------------------------------------------------------------------------
# /kernels endpoint + RPC + Prometheus families
# ---------------------------------------------------------------------------

class TestKernelsEndpoint:
    @pytest.fixture()
    def node_port(self):
        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        try:
            node = net.create_node(
                "O=KernelObs,L=London,C=GB", ops_port=0
            )
            yield node, node.ops_server.port
        finally:
            net.stop_nodes()

    def _get(self, port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as resp:
            return json.loads(resp.read())

    def test_kernels_page_and_cursor_drain(self, node_port):
        _node, port = node_port
        for _ in range(3):
            _dispatch()
        page = self._get(port, "/kernels")
        assert page["enabled"] is True
        assert [r["seq"] for r in page["records"]] == [1, 2, 3]
        assert "ed25519.verify_batch" in page["attainment"]
        assert page["backend"] == "cpu"
        drained = self._get(port, f"/kernels?since={page['next']}")
        assert drained["records"] == []
        _dispatch()
        assert [
            r["seq"] for r in
            self._get(port, f"/kernels?since={page['next']}")["records"]
        ] == [4]

    def test_malformed_cursor_is_client_fault(self, node_port):
        _node, port = node_port
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/kernels?since=bogus", timeout=5
            )
        assert err.value.code == 400

    def test_restart_reset_over_the_endpoint(self, node_port):
        _node, port = node_port
        for _ in range(2):
            _dispatch()
        cursor = self._get(port, "/kernels")["next"]
        profiling.ledger_reset()
        page = self._get(port, f"/kernels?since={cursor}")
        assert page["newest"] < cursor

    def test_rpc_node_kernels(self, node_port):
        from corda_tpu.rpc.ops import CordaRPCOps

        node, _port = node_port
        _dispatch()
        ops = CordaRPCOps(node.services, node.smm)
        page = ops.node_kernels()
        assert len(page["records"]) == 1
        assert page["records"][0]["kernel"] == "ed25519.verify_batch"

    def test_ledger_families_render_valid_prometheus(self, node_port):
        _node, port = node_port
        _dispatch()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        for family in (
            "corda_tpu_kernel_ledger_records",
            "corda_tpu_kernel_ledger_rows",
            "corda_tpu_kernel_ledger_real_rows",
            "corda_tpu_kernel_ledger_occupancy_pct",
            "corda_tpu_kernel_attainment",
        ):
            assert f"\n{family}" in body or body.startswith(family), family
        # the labelled attainment family carries the kernel label
        assert 'kernel="ed25519.verify_batch"' in body
        # strict exposition validity over the whole scrape (same
        # contract test_profiler pins for the profiler families)
        sample_re = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
            r'(\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+="[^"]*")*\})?'
            r" [^ ]+( [0-9.e+-]+)?$"
        )
        families = []
        for line in body.splitlines():
            if line.startswith("# TYPE "):
                families.append(line.split()[2])
                continue
            if not line or line.startswith("#"):
                continue
            assert sample_re.match(line), f"bad sample line: {line}"
        assert len(families) == len(set(families)), "duplicate TYPE family"


def test_kernels_scrape_never_imports_jax(tmp_path):
    """The jax-free read discipline, pinned end-to-end: a fresh process
    that records, serves and scrapes /kernels (attainment, cost cache,
    budget pins and all) must never import jax — a metrics scrape can
    never trigger a backend init or a compile."""
    script = """
import json, sys, urllib.request
from corda_tpu.node.opsserver import OpsServer
from corda_tpu.utils import profiling
from corda_tpu.utils.metrics import MetricRegistry

profiling.record_dispatch(
    "ed25519.verify_batch", 0.01, scheme="EDDSA_ED25519_SHA512",
    bucket="64", rows=64, real_rows=50,
)
ops = OpsServer(MetricRegistry())
try:
    with urllib.request.urlopen(
        "http://127.0.0.1:%d/kernels" % ops.port, timeout=5
    ) as resp:
        page = json.loads(resp.read())
finally:
    ops.stop()
assert page["records"], page
assert page["attainment"]["ed25519.verify_batch"]["attainment_pct"] > 0
assert page["backend"] == "cpu"
assert "jax" not in sys.modules, "scrape imported jax"
print("OK")
"""
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout


# ---------------------------------------------------------------------------
# gate direction pins + the report CLI
# ---------------------------------------------------------------------------

class TestGateAndReport:
    @pytest.mark.parametrize("key,expected", [
        ("kernel_observe_overhead_pct", "lower"),
        ("stage_timings.kernel_observe_overhead_pct", "lower"),
        ("kernel_observe_on_per_sec", "higher"),
        ("kernel_attainment.attainment_pct", "higher"),
        ("kernel_attainment_pct{kernel=ed25519.verify_batch}", "higher"),
    ])
    def test_direction_pins(self, key, expected):
        from corda_tpu.loadtest.gate import direction

        assert direction(key) == expected

    def test_kernel_report_renders_a_kernels_page(self, tmp_path):
        _dispatch()
        profiling.record_cost_analysis(
            "ed25519.verify_batch", "64", 64,
            {"flops": 64_000.0, "bytes accessed": 2_048.0},
        )
        path = tmp_path / "kernels.json"
        path.write_text(json.dumps(profiling.ledger_since(0)))
        proc = subprocess.run(
            [sys.executable, "tools/kernel_report.py",
             "--current", str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "ed25519.verify_batch" in proc.stdout
        assert "kernel attainment" in proc.stdout
        assert "xla cost model" in proc.stdout

    def test_kernel_report_renders_a_bench_record(self, tmp_path):
        _dispatch()
        record = {"stage_timings": {
            "kernel_attainment": profiling.attainment(),
        }}
        path = tmp_path / "bench.json"
        path.write_text(json.dumps(record))
        proc = subprocess.run(
            [sys.executable, "tools/kernel_report.py",
             "--current", str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "ed25519.verify_batch" in proc.stdout

    def test_kernel_report_unreadable_is_exit_2(self):
        proc = subprocess.run(
            [sys.executable, "tools/kernel_report.py",
             "--current", "/nonexistent/kernels.json"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 2


# ---------------------------------------------------------------------------
# the acceptance proof: a notarised MockNetwork tx lands in the ledger
# ---------------------------------------------------------------------------

def test_notarised_tx_leaves_ledger_records_with_cost(monkeypatch):
    """One notarised MockNetwork payment, forced onto the device verify
    path (the suite's CPU backend would normally take the host pool),
    must leave >=1 ledger record per engaged verify kernel with the
    scheme/bucket labels, REAL-row occupancy, populated cost-analysis
    flops, and a computed attainment entry."""
    from corda_tpu.core.crypto import EDDSA_ED25519_SHA512
    from corda_tpu.core.crypto import batch as crypto_batch
    from corda_tpu.ops import ed25519_batch
    from corda_tpu.testing.mocknetwork import MockNetwork

    monkeypatch.setattr(crypto_batch, "DISPATCH", "device")
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 1)
    # force the one-per-shape cost capture even when an earlier test in
    # this process already compiled the padded shape
    monkeypatch.setattr(ed25519_batch, "_SEEN_SHAPES", set())

    net = MockNetwork()
    try:
        notary = net.create_notary_node(validating=True)
        alice = net.create_node("O=LedgerAlice,L=London,C=GB")
        bob = net.create_node("O=LedgerBob,L=Paris,C=FR")

        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.rpc import CordaRPCOps

        ops = CordaRPCOps(alice.services, alice.smm)
        fid = ops.start_flow_dynamic(
            "corda_tpu.finance.flows.CashIssueFlow",
            Amount(1000, "USD"), (1,), alice.info, notary.info,
        )
        net.run_network()
        assert ops.flow_result(fid, timeout=10) is not None
        token = Issued(alice.info.ref(1), "USD")
        fid = ops.start_flow_dynamic(
            "corda_tpu.finance.flows.CashPaymentFlow",
            Amount(400, token), bob.info, notary.info,
        )
        net.run_network()
        assert ops.flow_result(fid, timeout=10) is not None
    finally:
        net.stop_nodes()

    page = profiling.ledger_since(0, limit=1000)
    recs = [r for r in page["records"]
            if r["kernel"] == "ed25519.verify_batch"]
    assert recs, "no device dispatch reached the ledger"
    scheme = EDDSA_ED25519_SHA512.scheme_code_name
    for rec in recs:
        assert rec["scheme"] == scheme
        assert rec["bucket"] in profiling.ED25519_BUCKET_LABELS
        assert rec["rows"] >= rec["real_rows"] >= 1
        assert 0.0 < rec["occupancy_pct"] <= 100.0

    # the XLA cost model was captured at compile time, on this process's
    # CPU backend, and is readable jax-free
    cost = page["cost"]["ed25519.verify_batch"]
    assert any(
        isinstance(e.get("flops"), float) and e["flops"] > 0
        for e in cost.values()
    ), cost
    assert page["backend"] == "cpu"

    att = page["attainment"]["ed25519.verify_batch"]
    assert att["dispatches"] >= 1
    assert att["achieved_sigs_s"] > 0
    assert isinstance(att["attainment_pct"], float)
    assert att["flops_per_row"] > 0

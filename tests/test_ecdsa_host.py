"""Native batched ECDSA engine (native/src/ecdsa_host.cpp +
core/crypto/ecdsa_host.py): differential against the OpenSSL loop
(`crypto.is_valid`) and the pure-Python oracle (secp_math), comb-cache
equivalence, strict-DER agreement, and dispatch routing.

Reference surface: core/.../crypto/Crypto.kt:91-151 (BouncyCastle
per-signature ECDSA verify for the same two curves)."""
import numpy as np
import pytest

from corda_tpu import native
from corda_tpu.core.crypto import crypto, ecdsa_host, secp_math
from corda_tpu.core.crypto import batch as crypto_batch
from corda_tpu.core.crypto.schemes import (
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
)

pytestmark = pytest.mark.skipif(
    not ecdsa_host.available(), reason="native library unavailable"
)

SCHEMES = {
    "secp256k1": ECDSA_SECP256K1_SHA256,
    "secp256r1": ECDSA_SECP256R1_SHA256,
}


def _items(curve_name, n, n_keys=None, seed=0):
    rng = np.random.default_rng(seed)
    n_keys = n_keys or n
    kps = [crypto.generate_keypair(SCHEMES[curve_name]) for _ in range(n_keys)]
    items = []
    for i in range(n):
        kp = kps[i % n_keys]
        msg = rng.bytes(40)
        items.append((kp.public, crypto.do_sign(kp.private, msg), msg))
    return items


@pytest.mark.parametrize("curve_name", ["secp256k1", "secp256r1"])
def test_reject_classes_match_openssl_loop(curve_name):
    """Every reject class must agree bit-for-bit with crypto.is_valid
    (the OpenSSL loop): ONE ECDSA acceptance rule per deployment."""
    items = _items(curve_name, 12, seed=1)
    n_order = ecdsa_host.CURVE_IDS[curve_name][1]
    pub, sig, msg = items[0]
    r, s = secp_math.der_decode_sig(sig)
    mutations = [
        (pub, sig, b"wrong message"),
        (pub, secp_math.der_encode_sig(s, r), msg),        # swapped
        (pub, secp_math.der_encode_sig(0, s), msg),        # r = 0
        (pub, secp_math.der_encode_sig(r, 0), msg),        # s = 0
        (pub, secp_math.der_encode_sig(n_order, s), msg),  # r = n
        (pub, secp_math.der_encode_sig(r, n_order + 1), msg),
        (pub, b"\x30\x00", msg),                           # malformed DER
        (pub, sig + b"\x00", msg),                         # trailing byte
        (pub, b"", msg),
        (items[1][0], sig, msg),                           # wrong key
    ]
    rows = items + mutations
    got = ecdsa_host.verify_batch_host(
        curve_name,
        [p.encoded for p, _, _ in rows],
        [sg for _, sg, _ in rows],
        [m for _, _, m in rows],
    )
    want = [crypto.is_valid(p, sg, m) for p, sg, m in rows]
    assert got == want
    assert got == [True] * 12 + [False] * len(mutations)


@pytest.mark.parametrize("curve_name", ["secp256k1", "secp256r1"])
def test_nonminimal_der_rejected_everywhere(curve_name):
    """A non-minimal DER integer (extra leading zero) must be rejected
    by the native path exactly as OpenSSL rejects it — the strict
    parsing rule is shared, not path-specific."""
    pub, sig, msg = _items(curve_name, 1, seed=2)[0]
    r, s = secp_math.der_decode_sig(sig)

    def pad(v):
        b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if b[0] & 0x80:
            b = b"\x00" + b
        b = b"\x00" + b  # non-minimal: extra zero
        return b"\x02" + bytes([len(b)]) + b

    body = pad(r) + pad(s)
    bad = b"\x30" + bytes([len(body)]) + body
    assert crypto.is_valid(pub, bad, msg) is False  # OpenSSL: reject
    got = ecdsa_host.verify_batch_host(
        curve_name, [pub.encoded], [bad], [msg]
    )
    assert got == [False]
    with pytest.raises(ValueError):
        secp_math.der_decode_sig(bad)


def test_comb_cache_changes_speed_not_verdicts():
    """Verdicts (incl. exact tamper positions) must be identical before
    and after a key's comb table is built."""
    items = _items("secp256r1", 64, n_keys=4, seed=3)  # hot keys
    bad = list(items)
    bad[5] = (bad[5][0], bad[5][1], b"tampered")
    bad[41] = (bad[41][0], bad[41][1][:-1] + b"\x01", bad[41][2])

    def run(rows):
        return ecdsa_host.verify_batch_host(
            "secp256r1",
            [p.encoded for p, _, _ in rows],
            [sg for _, sg, _ in rows],
            [m for _, _, m in rows],
        )

    cold = run(bad)
    warm = run(bad)  # combs built during the first call
    expect = [crypto.is_valid(p, sg, m) for p, sg, m in bad]
    assert cold == warm == expect
    assert not cold[5] and not cold[41]


def test_all_distinct_keys_cold_path():
    items = _items("secp256k1", 48, seed=4)  # every key distinct: wNAF
    got = ecdsa_host.verify_batch_host(
        "secp256k1",
        [p.encoded for p, _, _ in items],
        [sg for _, sg, _ in items],
        [m for _, _, m in items],
    )
    assert got == [True] * 48


def test_decompress_matches_python_oracle():
    curve = secp_math.SECP256K1
    rng = np.random.default_rng(6)
    comp = []
    for _ in range(16):
        priv = int(rng.integers(2, 2**31))
        comp.append(curve.encode_point(curve.mul(priv, curve.g)))
    out = native.ecdsa_decompress_many(0, comp)
    for enc, aff in zip(comp, out):
        x, y = curve.decode_point(enc)
        assert aff == x.to_bytes(32, "big") + y.to_bytes(32, "big")
    # x with no square root / not on curve
    bad = bytes([2]) + (5).to_bytes(32, "big")
    if curve.sqrt((5**3 + 7) % curve.p) is None:
        assert native.ecdsa_decompress_many(0, [bad]) == [None]


def test_dispatch_routes_ecdsa_to_native(monkeypatch):
    """CPU deployments route ECDSA buckets (any size) to the native
    engine; verdicts stay positionally exact in mixed batches."""
    calls = []
    real = ecdsa_host.verify_batch_host

    def spy(curve_name, *a):
        calls.append(curve_name)
        return real(curve_name, *a)

    monkeypatch.setattr(crypto_batch, "DISPATCH", "host")
    monkeypatch.setattr(ecdsa_host, "verify_batch_host", spy)
    items = _items("secp256r1", 5, seed=7) + _items("secp256k1", 3, seed=8)
    bad = list(items)
    bad[2] = (bad[2][0], bad[2][1], b"x")
    out = crypto_batch.verify_batch(bad)
    assert out == [True, True, False, True, True, True, True, True]
    assert sorted(set(calls)) == ["secp256k1", "secp256r1"]


def test_fuzz_differential_vs_openssl():
    """Random byte mutations over signatures/messages/keys: the native
    engine must agree with crypto.is_valid on every row."""
    rng = np.random.default_rng(9)
    items = _items("secp256r1", 24, n_keys=6, seed=10)
    rows = []
    for i, (pub, sig, msg) in enumerate(items):
        if i % 3 == 1:
            sig = bytearray(sig)
            sig[int(rng.integers(0, len(sig)))] ^= 1 << int(rng.integers(0, 8))
            sig = bytes(sig)
        elif i % 3 == 2:
            msg = bytearray(msg)
            msg[int(rng.integers(0, len(msg)))] ^= 1
            msg = bytes(msg)
        rows.append((pub, sig, msg))
    got = ecdsa_host.verify_batch_host(
        "secp256r1",
        [p.encoded for p, _, _ in rows],
        [sg for _, sg, _ in rows],
        [m for _, _, m in rows],
    )
    want = [crypto.is_valid(p, sg, m) for p, sg, m in rows]
    assert got == want

"""Mesh-sharded verification tests (8 virtual CPU devices via conftest)."""
import pytest
import numpy as np

from corda_tpu.core.crypto import ed25519_math
from corda_tpu.parallel import DistributedVerifier, data_mesh, shard_verify_ed25519


def _batch(n, seed=11):
    rng = np.random.default_rng(seed)
    pubs, sigs, msgs = [], [], []
    for _ in range(n):
        sk = rng.bytes(32)
        msg = rng.bytes(40)
        pubs.append(ed25519_math.public_from_seed(sk))
        sigs.append(ed25519_math.sign(sk, msg))
        msgs.append(msg)
    return pubs, sigs, msgs


def test_shard_verify_all_valid():
    mesh = data_mesh(8)
    pubs, sigs, msgs = _batch(64)
    mask = shard_verify_ed25519(mesh, pubs, sigs, msgs)
    assert mask.shape == (64,)
    assert mask.all()


def test_shard_verify_detects_forgeries_positionally():
    mesh = data_mesh(8)
    pubs, sigs, msgs = _batch(40, seed=12)
    bad = {3, 17, 39}
    for i in bad:
        msgs[i] = b"forged" + msgs[i]
    mask = shard_verify_ed25519(mesh, pubs, sigs, msgs)
    for i in range(40):
        assert bool(mask[i]) == (i not in bad)


def test_ragged_batch_padding():
    mesh = data_mesh(8)
    # 13 does not divide by 8: exercises pad + truncate-back
    pubs, sigs, msgs = _batch(13, seed=13)
    mask = shard_verify_ed25519(mesh, pubs, sigs, msgs)
    assert mask.shape == (13,)
    assert mask.all()


def test_distributed_verifier_wrapper():
    dv = DistributedVerifier(n_devices=4)
    assert dv.n_devices == 4
    pubs, sigs, msgs = _batch(16, seed=14)
    sigs[5] = bytes(64)  # zero signature: invalid but well-formed length
    out = dv.verify_ed25519(pubs, sigs, msgs)
    assert out[5] is False
    assert all(out[:5] + out[6:])


def test_ecdsa_bucket_routes_through_mesh(monkeypatch):
    """ECDSA buckets >= MESH_MIN_BATCH must take the mesh path (round-2
    VERDICT #3: scale-out must cover all schemes uniformly). Routing-only
    — the real sharded ECDSA kernel is exercised by the heavy_compile
    test below and by __graft_entry__.dryrun_multichip."""
    from corda_tpu.core.crypto import batch as crypto_batch
    from corda_tpu.core.crypto import crypto
    from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
    from corda_tpu.parallel import mesh as mesh_mod

    calls = []

    def fake_shard_verify(mesh, scheme, pubs, sigs, msgs,
                          return_total=False):
        calls.append((scheme, len(pubs)))
        mask = np.ones(len(pubs), bool)
        if return_total:
            return mask, int(mask.sum())
        return mask

    monkeypatch.setattr(mesh_mod, "shard_verify", fake_shard_verify)
    kp = crypto.generate_keypair(ECDSA_SECP256K1_SHA256)
    content = b"mesh-routing probe"
    sig = crypto.do_sign(kp.private, content)
    items = [(kp.public, sig, content)] * 64
    crypto_batch.configure_mesh(data_mesh(8), min_batch=64)
    try:
        out = crypto_batch.verify_batch(items)
        assert all(out)
        assert calls == [("secp256k1", 64)]
    finally:
        crypto_batch.configure_mesh(None)


@pytest.mark.heavy_compile
def test_shard_verify_ecdsa_differential():
    """Real sharded ECDSA kernel over the 8-device CPU mesh vs the host
    oracle (compile-dominated: the full 256-bit ladder)."""
    from corda_tpu.core.crypto import crypto
    from corda_tpu.core.crypto.keys import SchemePublicKey
    from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
    from corda_tpu.parallel.mesh import shard_verify

    mesh = data_mesh(8)
    rng = np.random.default_rng(21)
    pubs, sigs, msgs = [], [], []
    for i in range(16):
        kp = crypto.generate_keypair(ECDSA_SECP256K1_SHA256)
        m = rng.bytes(32)
        pubs.append(kp.public.encoded)
        sigs.append(crypto.do_sign(kp.private, m))
        msgs.append(m)
    msgs[5] = b"forged"
    mask = shard_verify(mesh, "secp256k1", pubs, sigs, msgs)
    host = [
        crypto.is_valid(
            SchemePublicKey("ECDSA_SECP256K1_SHA256", pubs[i]), sigs[i], msgs[i]
        )
        for i in range(16)
    ]
    assert [bool(b) for b in mask] == host
    assert not mask[5] and mask[4]


@pytest.mark.slow
class TestMeshProductionPath:
    """The mesh wired into the PRODUCTION batching path (VERDICT round-1
    #4): configure_mesh routes large ed25519 buckets in
    core.crypto.batch.verify_batch through parallel.mesh, which is what
    the SignatureBatcher -> verifier service -> notary stack uses.

    Firehose size: 8x256 by default (CPU virtual devices verify ~100
    sigs/s total — the full >=100k firehose is for real chips; set
    CORDA_TPU_FIREHOSE to run it here)."""

    def test_batcher_routes_through_mesh_with_tampering(self):
        import os

        from corda_tpu.core.crypto import batch as crypto_batch
        from corda_tpu.core.crypto import crypto
        from corda_tpu.core.crypto.keys import SchemePublicKey
        from corda_tpu.parallel import data_mesh
        from corda_tpu.verifier import (
            InMemoryTransactionVerifierService,
            SignatureBatcher,
        )

        n = int(os.environ.get("CORDA_TPU_FIREHOSE", 8 * 256))
        mesh = data_mesh(8)
        crypto_batch.configure_mesh(mesh, min_batch=512)
        try:
            kp = crypto.entropy_to_keypair(31337)
            content = b"notary uniqueness batch row"
            sig = crypto.do_sign(kp.private, content)
            items = [(kp.public, sig, content)] * n
            # tamper known positions (first, middle, last)
            bad_positions = {0, n // 2, n - 1}
            items = [
                (kp.public, sig, b"forged") if i in bad_positions else it
                for i, it in enumerate(items)
            ]
            svc = InMemoryTransactionVerifierService(
                batcher=SignatureBatcher(max_batch=n)
            )
            futures = svc.verify_signatures(items)
            svc._batcher.flush()
            results = [f.result(timeout=600) for f in futures]
            for i, ok in enumerate(results):
                assert ok == (i not in bad_positions), i
            svc.stop()
        finally:
            crypto_batch.configure_mesh(None)

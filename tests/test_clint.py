"""Native-plane C-source lint (corda_tpu/analysis/clint.py; ISSUE 13).

Pins the three tokenizer passes (gil_region / buffer_release /
refcount_escape): clean on the real native sources, each detects its
synthetic violation (in-process AND through the tools/lint.py CLI with
a --root minirepo, failing with a named NEW FINDING), suppressions
work, the fixed journal.cpp true positives stay fixed, and the native
passes ride the same pinned analysis_manifest.json as the PR-9 suite.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from corda_tpu.analysis import clint, manifest
from corda_tpu.analysis.manifest import ALL_PASS_IDS, load_manifest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_CLI = os.path.join(REPO, "tools", "lint.py")


def _lint_src(tmp_path, name, src, passes=None):
    """Run clint over one synthetic source file."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return clint.run_passes(paths=[str(p)], root=str(tmp_path),
                            passes=passes)


# -- tokenizer / structure ----------------------------------------------------

class TestTokenizer:
    def test_functions_found_in_real_codec(self):
        src = os.path.join(REPO, "corda_tpu", "native", "src", "codec_ext.c")
        with open(src) as fh:
            cf = clint._CFile(src, "codec_ext.c", fh.read())
        names = {f[0] for f in cf.functions}
        for expected in ("py_encode", "py_decode", "encode_value",
                         "decode_value", "py_decode_many", "parse_batch",
                         "py_parse_headers_many", "py_route_hints_many"):
            assert expected in names, sorted(names)

    def test_comments_and_strings_are_not_code(self, tmp_path):
        findings = _lint_src(tmp_path, "c.c", """
            /* Py_BEGIN_ALLOW_THREADS then PyList_New in a comment */
            // Py_BEGIN_ALLOW_THREADS PyDict_New
            static const char *s = "Py_BEGIN_ALLOW_THREADS PyList_New";
            int f(int x) { return x; }
        """)
        assert findings == []


# -- pass: gil_region ---------------------------------------------------------

GIL_BAD = """
    #include <Python.h>
    static PyObject *bad_region(PyObject *self, PyObject *args) {
        PyObject *out = NULL;
        Py_ssize_t n = 0;
        Py_BEGIN_ALLOW_THREADS
        out = PyList_New(n);
        Py_END_ALLOW_THREADS
        return out;
    }
"""


class TestGilRegion:
    def test_api_call_in_region_flagged(self, tmp_path):
        findings = _lint_src(tmp_path, "g.c", GIL_BAD, ["gil_region"])
        assert [f.key for f in findings] == [
            "gil_region:g.c:bad_region:PyList_New"
        ]
        assert "Py_BEGIN_ALLOW_THREADS" in findings[0].message

    def test_allowlisted_names_pass(self, tmp_path):
        findings = _lint_src(tmp_path, "g.c", """
            #include <Python.h>
            static void ok_region(char *d, Py_ssize_t len) {
                Py_BEGIN_ALLOW_THREADS
                Py_ssize_t i;
                for (i = 0; i < len && i < PY_SSIZE_T_MAX; i++) d[i] = 0;
                Py_END_ALLOW_THREADS
            }
        """, ["gil_region"])
        assert findings == []

    def test_block_threads_reacquires(self, tmp_path):
        findings = _lint_src(tmp_path, "g.c", """
            #include <Python.h>
            static void mixed(char *d) {
                Py_BEGIN_ALLOW_THREADS
                d[0] = 0;
                Py_BLOCK_THREADS
                PyErr_SetString(PyExc_ValueError, "x");
                Py_UNBLOCK_THREADS
                d[1] = 0;
                Py_END_ALLOW_THREADS
            }
        """, ["gil_region"])
        assert findings == []

    def test_suppression(self, tmp_path):
        src = GIL_BAD.replace(
            "out = PyList_New(n);",
            "out = PyList_New(n);  /* lint: allow(gil_region) — test */",
        )
        assert _lint_src(tmp_path, "g.c", src, ["gil_region"]) == []


# -- pass: buffer_release -----------------------------------------------------

BUF_BAD = """
    #include <Python.h>
    static PyObject *bad_buffer(PyObject *self, PyObject *obj) {
        Py_buffer view;
        if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0) return NULL;
        if (((char *)view.buf)[0] == 'x') return NULL;
        PyBuffer_Release(&view);
        Py_RETURN_NONE;
    }
"""


class TestBufferRelease:
    def test_early_return_without_release_flagged(self, tmp_path):
        findings = _lint_src(tmp_path, "b.c", BUF_BAD, ["buffer_release"])
        assert [f.key for f in findings] == [
            "buffer_release:b.c:bad_buffer:view"
        ]

    def test_acquisition_failure_guard_exempt_and_pairing_clean(
        self, tmp_path
    ):
        findings = _lint_src(tmp_path, "b.c", """
            #include <Python.h>
            static PyObject *ok_buffer(PyObject *self, PyObject *obj) {
                Py_buffer view;
                if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0)
                    return NULL;
                if (view.len == 0) {
                    PyBuffer_Release(&view);
                    return NULL;
                }
                PyBuffer_Release(&view);
                Py_RETURN_NONE;
            }
        """, ["buffer_release"])
        assert findings == []

    def test_parse_tuple_y_star_acquisition(self, tmp_path):
        findings = _lint_src(tmp_path, "b.c", """
            #include <Python.h>
            static PyObject *bad_ystar(PyObject *self, PyObject *args) {
                Py_buffer view;
                PyObject *other;
                if (!PyArg_ParseTuple(args, "y*O", &view, &other))
                    return NULL;
                if (other == Py_None) return NULL;
                PyBuffer_Release(&view);
                Py_RETURN_NONE;
            }
        """, ["buffer_release"])
        assert [f.key for f in findings] == [
            "buffer_release:b.c:bad_ystar:view"
        ]

    def test_goto_fail_epilogue_with_release_clean(self, tmp_path):
        findings = _lint_src(tmp_path, "b.c", """
            #include <Python.h>
            static PyObject *ok_goto(PyObject *self, PyObject *obj) {
                Py_buffer view;
                PyObject *out = NULL;
                if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0)
                    return NULL;
                if (view.len == 0) goto done;
                out = PyBytes_FromStringAndSize(view.buf, view.len);
            done:
                PyBuffer_Release(&view);
                return out;
            }
        """, ["buffer_release"])
        assert findings == []

    def test_goto_fail_epilogue_without_release_flagged(self, tmp_path):
        findings = _lint_src(tmp_path, "b.c", """
            #include <Python.h>
            static PyObject *bad_goto(PyObject *self, PyObject *obj) {
                Py_buffer view;
                PyObject *out = NULL;
                if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0)
                    return NULL;
                if (view.len == 0) goto done;
                out = PyBytes_FromStringAndSize(view.buf, view.len);
            done:
                return out;
            }
        """, ["buffer_release"])
        assert [f.key for f in findings] == [
            "buffer_release:b.c:bad_goto:view"
        ]
        assert "goto" in findings[0].message


# -- pass: refcount_escape ----------------------------------------------------

REF_BAD = """
    #include <Python.h>
    static int bad_leak(int x) {
        PyObject *tmp = PyList_New(0);
        if (!tmp) return -1;
        if (x) return -1;
        Py_DECREF(tmp);
        return 0;
    }
"""


class TestRefcountEscape:
    def test_early_error_leak_flagged(self, tmp_path):
        findings = _lint_src(tmp_path, "r.c", REF_BAD, ["refcount_escape"])
        assert [f.key for f in findings] == [
            "refcount_escape:r.c:bad_leak:tmp"
        ]

    def test_release_and_transfer_paths_clean(self, tmp_path):
        findings = _lint_src(tmp_path, "r.c", """
            #include <Python.h>
            static PyObject *ok_paths(int x) {
                PyObject *a = PyList_New(0);
                if (!a) return NULL;
                if (x == 1) { Py_DECREF(a); return NULL; }
                if (x == 2) return a;
                PyObject *t = PyTuple_New(1);
                if (!t) { Py_DECREF(a); return NULL; }
                PyTuple_SET_ITEM(t, 0, a);
                return t;
            }
        """, ["refcount_escape"])
        assert findings == []

    def test_unguarded_new_flagged_cpp_only(self, tmp_path):
        src = """
            extern "C" {
            void *bad_new(int n) {
                int *p = new int[4];
                return p;
            }
            }
        """
        findings = _lint_src(tmp_path, "n.cpp", src, ["refcount_escape"])
        assert any(f.symbol == "bad_new:new" for f in findings), findings
        assert "nothrow" in findings[0].message

    def test_nothrow_new_clean(self, tmp_path):
        findings = _lint_src(tmp_path, "n.cpp", """
            #include <new>
            extern "C" {
            void *ok_new(void) {
                int *p = new (std::nothrow) int;
                if (!p) return 0;
                return p;
            }
            }
        """, ["refcount_escape"])
        assert findings == []

    def test_suppression(self, tmp_path):
        src = REF_BAD.replace(
            "if (x) return -1;",
            "if (x) return -1;  /* lint: allow(refcount_escape) — test */",
        )
        assert _lint_src(tmp_path, "r.c", src,
                         ["refcount_escape"]) == []


# -- the real sources + the pinned baseline -----------------------------------

class TestRealSources:
    def test_native_sources_clean(self):
        """All five native extension sources pass all three passes —
        the accepted baseline for the native plane is ZERO."""
        findings = clint.run_passes()
        assert findings == [], [f.key for f in findings]

    def test_native_paths_cover_all_five(self):
        names = {os.path.basename(p) for p in clint.native_paths()}
        assert names == {"codec_ext.c", "ecdsa_host.cpp",
                         "ed25519_msm.cpp", "journal.cpp",
                         "sha2_batch.cpp"}

    def test_fixed_true_positives_stay_fixed(self):
        """The journal.cpp findings this suite surfaced (unguarded
        `new` across the C ABI; the fopen handle leaking when the
        alloc-failure path was added) are FIXED — the keys must stay
        absent from findings AND from the accepted baseline."""
        current = {f.key for f in clint.run_passes()}
        pinned = {
            k for keys in load_manifest()["passes"].values() for k in keys
        }
        for key in (
            "refcount_escape:corda_tpu/native/src/journal.cpp:"
            "journal_open:new",
            "refcount_escape:corda_tpu/native/src/journal.cpp:"
            "journal_open:fh",
        ):
            assert key not in current, f"regressed: {key}"
            assert key not in pinned, f"crept back into baseline: {key}"

    def test_native_passes_pinned_at_zero(self):
        baseline = load_manifest()["passes"]
        for pid in clint.PASS_IDS:
            assert baseline[pid] == [], baseline[pid]

    def test_manifest_covers_both_planes(self):
        baseline = load_manifest()["passes"]
        assert set(ALL_PASS_IDS) <= set(baseline)
        result = manifest.check_findings()
        assert result["new"] == [], result["new"]


# -- tools/lint.py CLI over a --root minirepo ---------------------------------

C_VIOLATIONS = {
    "gil_region": GIL_BAD,
    "buffer_release": BUF_BAD,
    "refcount_escape": REF_BAD,
}


class TestCLintCLI:
    @pytest.mark.parametrize("pass_id", sorted(C_VIOLATIONS))
    def test_synthetic_violation_fails_cli_with_named_finding(
        self, tmp_path, pass_id
    ):
        root = tmp_path / "minirepo"
        src_dir = root / "corda_tpu" / "native" / "src"
        src_dir.mkdir(parents=True)
        bad = src_dir / f"bad_{pass_id}.c"
        bad.write_text(textwrap.dedent(C_VIOLATIONS[pass_id]))
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--baseline", "--no-kernel",
             "--root", str(root), "--pass", pass_id],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1, proc.stderr
        expected = (f"NEW FINDING {pass_id}:"
                    f"corda_tpu/native/src/bad_{pass_id}.c:")
        assert expected in proc.stderr, proc.stderr

    def test_explicit_c_path_lints_without_gate(self, tmp_path):
        bad = tmp_path / "x.c"
        bad.write_text(textwrap.dedent(REF_BAD))
        proc = subprocess.run(
            [sys.executable, LINT_CLI, str(bad)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0
        assert "[refcount_escape]" in proc.stdout

    def test_clean_repo_includes_native_passes(self):
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--baseline", "--no-kernel",
             "--json"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout.splitlines()[-1])
        assert out["ok"]

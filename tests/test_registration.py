"""Network registration tests (reference NetworkRegistrationHelper +
doorman protocol)."""
import os

import pytest

from corda_tpu.core.crypto import pki
from corda_tpu.node.registration import (
    DoormanServer,
    NetworkRegistrationHelper,
    RegistrationError,
)

pytestmark = pytest.mark.skipif(
    not pki.OPENSSL_AVAILABLE,
    reason="X.509 PKI requires the 'cryptography' package",
)


class TestRegistration:
    def test_auto_approved_registration(self, tmp_path):
        doorman = DoormanServer()
        try:
            helper = NetworkRegistrationHelper(
                doorman.url, "O=NewNode,L=London,C=GB", str(tmp_path)
            )
            chain = helper.register(timeout=20)
            assert len(chain) == 3
            # installed identity verifies to the doorman's root
            leaf = pki.read_cert(str(tmp_path), "identity")
            assert pki.verify_chain(
                leaf.cert, [doorman.intermediate.cert], doorman.root.cert
            )
            # the node CA cert can issue identity certs (is_ca)
            assert os.path.exists(tmp_path / "identity.key.pem")
            assert os.path.exists(tmp_path / "root.cert.pem")
        finally:
            doorman.stop()

    def test_manual_approval_flow(self, tmp_path):
        import threading

        doorman = DoormanServer(auto_approve=False)
        try:
            helper = NetworkRegistrationHelper(
                doorman.url, "O=WaitingNode,L=Paris,C=FR", str(tmp_path)
            )
            result = {}

            def run():
                result["chain"] = helper.register(timeout=30)

            t = threading.Thread(target=run)
            t.start()
            deadline = 50
            import time

            t0 = time.monotonic()
            while not doorman.pending() and time.monotonic() - t0 < deadline:
                time.sleep(0.05)
            pending = doorman.pending()
            assert len(pending) == 1
            doorman.approve(pending[0])
            t.join(timeout=30)
            assert len(result["chain"]) == 3
        finally:
            doorman.stop()

    def test_rejection_raises(self, tmp_path):
        import threading
        import time

        doorman = DoormanServer(auto_approve=False)
        try:
            helper = NetworkRegistrationHelper(
                doorman.url, "O=BadNode,L=X,C=GB", str(tmp_path)
            )
            err = {}

            def run():
                try:
                    helper.register(timeout=30)
                except RegistrationError as exc:
                    err["exc"] = exc

            t = threading.Thread(target=run)
            t.start()
            t0 = time.monotonic()
            while not doorman.pending() and time.monotonic() - t0 < 50:
                time.sleep(0.05)
            doorman.reject(doorman.pending()[0], "compliance")
            t.join(timeout=30)
            assert "compliance" in str(err["exc"])
        finally:
            doorman.stop()


class TestChainValidation:
    """A MITM/rogue doorman must not be able to install an arbitrary
    identity (ADVICE round 2: pin + verify the returned chain)."""

    def test_pinned_root_accepts_genuine_doorman(self, tmp_path):
        doorman = DoormanServer()
        try:
            helper = NetworkRegistrationHelper(
                doorman.url, "O=Pinned,L=London,C=GB", str(tmp_path),
                expected_root=doorman.root.cert,
            )
            assert len(helper.register(timeout=20)) == 3
        finally:
            doorman.stop()

    def test_pinned_fingerprint_accepts_genuine_doorman(self, tmp_path):
        import hashlib

        from cryptography.hazmat.primitives import serialization

        doorman = DoormanServer()
        try:
            fp = hashlib.sha256(
                doorman.root.cert.public_bytes(serialization.Encoding.DER)
            ).hexdigest()
            helper = NetworkRegistrationHelper(
                doorman.url, "O=PinnedFp,L=London,C=GB", str(tmp_path),
                expected_root=fp,
            )
            assert len(helper.register(timeout=20)) == 3
        finally:
            doorman.stop()

    def test_pinned_root_rejects_rogue_doorman(self, tmp_path):
        rogue = DoormanServer()  # its own self-signed root
        expected = pki.create_self_signed_ca("Real Network Root")
        try:
            helper = NetworkRegistrationHelper(
                rogue.url, "O=Victim,L=London,C=GB", str(tmp_path),
                expected_root=expected.cert,
            )
            with pytest.raises(RegistrationError, match="trust root"):
                helper.register(timeout=20)
            assert not os.path.exists(tmp_path / "identity.cert.pem")
        finally:
            rogue.stop()

    def test_wrong_leaf_key_rejected(self, tmp_path, monkeypatch):
        """A doorman that re-keys the identity (returns a leaf for a key
        the node never generated) must be rejected."""
        doorman = DoormanServer()

        real_approve = doorman.approve

        def approve_with_other_key(request_id):
            other_csr, _ = pki.create_csr("O=Victim,L=London,C=GB")
            with doorman._lock:
                doorman._requests[request_id]["csr"] = other_csr
            real_approve(request_id)

        doorman.approve = approve_with_other_key
        try:
            helper = NetworkRegistrationHelper(
                doorman.url, "O=Victim,L=London,C=GB", str(tmp_path),
                expected_root=doorman.root.cert,
            )
            with pytest.raises(RegistrationError, match="CSR"):
                helper.register(timeout=20)
        finally:
            doorman.stop()

    def test_overlong_chain_rejected(self, tmp_path):
        """4+ certificates must error, not silently truncate (the old
        zip() dropped extras)."""
        doorman = DoormanServer()

        real_approve = doorman.approve

        def approve_padded(request_id):
            real_approve(request_id)
            with doorman._lock:
                entry = doorman._requests[request_id]
                entry["certs"] = entry["certs"] + [doorman.root.cert]

        doorman.approve = approve_padded
        try:
            helper = NetworkRegistrationHelper(
                doorman.url, "O=Victim,L=London,C=GB", str(tmp_path),
                expected_root=doorman.root.cert,
            )
            with pytest.raises(RegistrationError, match="expected exactly"):
                helper.register(timeout=20)
        finally:
            doorman.stop()


class TestNodeCLIRegistration:
    def test_initial_registration_flag(self, tmp_path):
        """`python -m corda_tpu.node DIR --initial-registration` registers
        against the doorman named in node.conf and exits (reference
        NodeStartup --initial-registration)."""
        import json
        import subprocess
        import sys

        doorman = DoormanServer()
        try:
            node_dir = tmp_path / "regnode"
            node_dir.mkdir()
            (node_dir / "node.conf").write_text(json.dumps({
                "my_legal_name": "O=CliReg,L=London,C=GB",
                "doorman_url": doorman.url,
            }))
            env = dict(os.environ)
            import corda_tpu

            repo = os.path.dirname(os.path.dirname(corda_tpu.__file__))
            env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
            out = subprocess.run(
                [sys.executable, "-m", "corda_tpu.node", str(node_dir),
                 "--initial-registration"],
                capture_output=True, text=True, timeout=120, env=env,
            )
            assert out.returncode == 0, out.stdout + out.stderr
            assert "chain of 3 certificates" in out.stdout
            leaf = pki.read_cert(str(node_dir / "certificates"), "identity")
            assert pki.verify_chain(
                leaf.cert, [doorman.intermediate.cert], doorman.root.cert
            )
        finally:
            doorman.stop()

"""Remote-soak machinery (loadtest/remote.py, tools/soak_gate.py, the
process-granular disruption catalog, the explorer action surface):
deterministic units — the composed end-to-end soak itself is the
`python -m corda_tpu.loadtest.remote --hosts hosts.conf` heavy-tier run
(docs/robustness.md "Remote soak")."""
import json
import os
import random
import subprocess
import sys
import urllib.error
import urllib.parse
import urllib.request

import pytest

from corda_tpu.loadtest import remote
from corda_tpu.loadtest.disruption import (
    assert_recovers,
    process_hang,
    process_restart,
    shard_worker_process_kill,
    transport_partition,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# hosts.conf
# ---------------------------------------------------------------------------

class TestHostsConf:
    def test_parse_local_and_ssh_entries(self):
        specs = remote.parse_hosts(
            "# comment\n"
            "local\n"
            "loadtest@10.1.2.3 workdir=/tmp/soak python=python3.9\n"
            "db-host addr=192.0.2.7 name=db\n"
        )
        assert len(specs) == 3
        assert specs[0].is_local and specs[0].addr == "127.0.0.1"
        assert not specs[1].is_local
        assert specs[1].addr == "10.1.2.3"
        assert specs[1].workdir == "/tmp/soak"
        assert specs[1].python == "python3.9"
        assert specs[2].addr == "192.0.2.7" and specs[2].name == "db"

    def test_empty_and_malformed_rejected(self):
        with pytest.raises(ValueError, match="no hosts"):
            remote.parse_hosts("# only comments\n\n")
        with pytest.raises(ValueError, match="key=value"):
            remote.parse_hosts("host1 not-an-option\n")

    def test_repo_example_parses_as_local_rig(self):
        specs = remote.load_hosts(os.path.join(_REPO, "hosts.conf"))
        assert specs and specs[0].is_local


# ---------------------------------------------------------------------------
# sessions (local transport shares every code path with ssh but the argv)
# ---------------------------------------------------------------------------

class TestLocalSession:
    @pytest.fixture()
    def session(self):
        return remote.LocalSession(remote.parse_hosts("local")[0])

    def test_run_and_check(self, session):
        rc, out = session.run("echo hi")
        assert rc == 0 and "hi" in out
        rc, _ = session.run("exit 3")
        assert rc == 3
        with pytest.raises(remote.SessionError, match="rc=4"):
            session.run("exit 4", check=True)

    def test_run_timeout_is_bounded(self, session):
        rc, out = session.run("sleep 30", timeout=1.0)
        assert rc == 124 and "timeout" in out

    def test_spawn_signal_alive(self, session, tmp_path):
        log = str(tmp_path / "spawn.log")
        pid = session.spawn("sleep 30", log)
        try:
            assert session.alive(pid)
            assert session.signal(pid, "STOP")
            assert session.signal(pid, "CONT")
        finally:
            session.signal(pid, "KILL")
        import time

        deadline = time.monotonic() + 10
        while session.alive(pid) and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not session.alive(pid)

    def test_read_write_file(self, session, tmp_path):
        path = str(tmp_path / "x.txt")
        session.write_file(path, "line1\nline2'with quote\n")
        assert session.read_file(path) == "line1\nline2'with quote\n"
        assert session.read_file(str(tmp_path / "missing")) is None

    def test_free_port_binds(self, session):
        import socket

        port = session.free_port()
        s = socket.socket()
        s.bind(("127.0.0.1", port))  # free means bindable right now
        s.close()

    def test_put_dir(self, session, tmp_path):
        src = tmp_path / "src" / "nodeX"
        src.mkdir(parents=True)
        (src / "node.conf").write_text("{}")
        dest_parent = tmp_path / "dst"
        session.put_dir(str(src), str(dest_parent))
        assert (dest_parent / "nodeX" / "node.conf").read_text() == "{}"

    def test_open_session_probe_failure_names_host(self):
        spec = remote.HostSpec("local")
        spec.python = "/nonexistent"  # probe is a shell echo; still ok
        session = remote.open_session(spec)
        assert isinstance(session, remote.LocalSession)

    def test_ssh_spec_builds_ssh_session(self):
        spec = remote.parse_hosts("user@host1")[0]
        session = remote.SshSession(spec)
        argv = session._argv("echo ok")
        assert argv[0] == "ssh" and "BatchMode=yes" in argv
        assert session._is_transport_failure(255)
        assert not session._is_transport_failure(1)


# ---------------------------------------------------------------------------
# disruption catalog: deterministic fire/heal with recovery assertions
# ---------------------------------------------------------------------------

class _FakeVictim:
    def __init__(self):
        self.calls = []

    def kill(self):
        self.calls.append("kill")

    def relaunch(self):
        self.calls.append("relaunch")

    def suspend(self):
        self.calls.append("suspend")

    def resume(self):
        self.calls.append("resume")


class _FakeProxy:
    def __init__(self):
        self.calls = []

    def set_mode(self, mode, direction="both", delay_s=0.0):
        self.calls.append(("set_mode", mode, direction))

    def heal(self):
        self.calls.append(("heal",))


class _Counter:
    """A probe that advances by `step` each read after fire."""

    def __init__(self, step=1):
        self.value = 0
        self.step = step

    def __call__(self):
        self.value += self.step
        return self.value


class TestDisruptionCatalog:
    def test_process_restart_fire_heal_asserts_recovery(self):
        victim, probe = _FakeVictim(), _Counter()
        d = process_restart(victim, probe, recovery_deadline_s=5.0)
        rng = random.Random(1)
        d.fire(rng)
        assert victim.calls == ["kill"]
        d.heal(rng)  # probe advances: recovery proven
        assert victim.calls == ["kill", "relaunch"]

    def test_process_restart_heal_raises_without_progress(self):
        victim = _FakeVictim()
        d = process_restart(
            victim, lambda: 7, recovery_deadline_s=0.6,
        )
        rng = random.Random(1)
        d.fire(rng)
        with pytest.raises(AssertionError, match="no recovery"):
            d.heal(rng)
        assert victim.calls == ["kill", "relaunch"]

    def test_process_hang_fire_heal(self):
        victim, probe = _FakeVictim(), _Counter()
        d = process_hang(victim, probe, recovery_deadline_s=5.0)
        rng = random.Random(2)
        d.fire(rng)
        assert victim.calls == ["suspend"]
        d.heal(rng)
        assert victim.calls == ["suspend", "resume"]

    def test_process_hang_heal_raises_without_progress(self):
        victim = _FakeVictim()
        d = process_hang(victim, lambda: 0, recovery_deadline_s=0.6)
        rng = random.Random(2)
        d.fire(rng)
        with pytest.raises(AssertionError, match="SIGSTOP"):
            d.heal(rng)

    def test_transport_partition_fire_heal(self):
        proxy, probe = _FakeProxy(), _Counter()
        d = transport_partition(
            proxy, probe, mode="blackhole", direction="c2s",
            recovery_deadline_s=5.0,
        )
        rng = random.Random(3)
        d.fire(rng)
        assert proxy.calls == [("set_mode", "blackhole", "c2s")]
        d.heal(rng)
        assert proxy.calls[-1] == ("heal",)

    def test_transport_partition_heal_raises_without_progress(self):
        proxy = _FakeProxy()
        d = transport_partition(
            proxy, lambda: 3, recovery_deadline_s=0.6,
        )
        rng = random.Random(3)
        d.fire(rng)
        with pytest.raises(AssertionError, match="transport partition"):
            d.heal(rng)
        assert proxy.calls[-1] == ("heal",)  # wire restored BEFORE verdict

    def test_shard_worker_kill_fire_heal_and_no_worker_noop(self):
        killed = []
        probe = _Counter()
        d = shard_worker_process_kill(
            lambda rng: 4242, killed.append, probe,
            recovery_deadline_s=5.0,
        )
        rng = random.Random(4)
        d.fire(rng)
        assert killed == [4242]
        d.heal(rng)
        # no worker visible: fire is a no-op and the heal must not
        # demand recovery for a disruption that never happened
        d2 = shard_worker_process_kill(
            lambda rng: None, killed.append, lambda: 0,
            recovery_deadline_s=0.5,
        )
        d2.fire(rng)
        d2.heal(rng)  # no raise
        assert killed == [4242]

    def test_assert_recovers_reports_counts(self):
        with pytest.raises(AssertionError, match="0 completions"):
            assert_recovers(lambda: 5, 5, "unit", deadline_s=0.4)
        assert assert_recovers(
            _Counter(step=3), 0, "unit", deadline_s=5.0
        ) >= 2

    def test_probabilistic_interface_still_works(self):
        # the deterministic fire()/heal() surface must not break the
        # existing maybe_fire/maybe_heal probabilistic contract
        victim, probe = _FakeVictim(), _Counter()
        d = process_restart(victim, probe, probability=1.0,
                            heal_after=0, recovery_deadline_s=5.0)
        rng = random.Random(5)
        d.maybe_fire(rng, None, 0)
        assert victim.calls == ["kill"]
        d.maybe_heal(rng, None, 1)
        assert victim.calls == ["kill", "relaunch"]


# ---------------------------------------------------------------------------
# soak gate CLI
# ---------------------------------------------------------------------------

def _run_gate(record, *args):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "soak_gate.py"),
         "--current", "-", *args],
        input=json.dumps(record), capture_output=True, text=True,
        timeout=60,
    )
    return proc.returncode, proc.stdout, proc.stderr


_GREEN = {
    "metric": "remote-soak-pairs",
    "pairs": 120,
    "hard_error_rate": 0.01,
    "disruptions_fired": 4,
    "disruptions_recovered": 4,
    "consistent": True,
    "slo_violations": [],
    "overload": {"recovered": 1.0, "shed": 12.0},
}


class TestSoakGate:
    def test_green_record_passes(self):
        rc, out, err = _run_gate(_GREEN)
        assert rc == 0, err
        assert json.loads(out.splitlines()[-1])["ok"] is True

    def test_recorded_slo_violation_fails(self):
        record = {**_GREEN, "slo_violations": [
            {"key": "pairs", "value": 0, "bound": 1, "kind": "min"},
        ]}
        rc, _, err = _run_gate(record)
        assert rc == 1 and "SOAK VIOLATION pairs" in err

    def test_loss_dup_inconsistency_fails(self):
        rc, _, err = _run_gate({**_GREEN, "consistent": False})
        assert rc == 1 and "loss-or-dup" in err

    def test_hard_error_rate_bound_is_baseline(self):
        rc, _, err = _run_gate({**_GREEN, "hard_error_rate": 0.9})
        assert rc == 1 and "hard_error_rate" in err

    def test_extra_slo_bound_asserted_and_missing_is_violation(self):
        rc, _, err = _run_gate(_GREEN, "--slo", "pairs>=1000")
        assert rc == 1 and "pairs" in err
        # a bound on a metric the record lacks is a violation, not a skip
        rc, _, err = _run_gate(_GREEN, "--slo", "no_such_metric>=1")
        assert rc == 1 and "missing" in err
        # dotted keys reach nested blocks
        rc, _, _ = _run_gate(_GREEN, "--slo", "overload.shed>=1")
        assert rc == 0

    def test_usage_errors_exit_2(self):
        rc, _, err = _run_gate(_GREEN, "--slo", "pairs=10")
        assert rc == 2 and "<=" in err
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "soak_gate.py"),
             "--current", "/nonexistent.json"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 2


# ---------------------------------------------------------------------------
# explorer action surface (dashboard POST forms over the RPC bridge)
# ---------------------------------------------------------------------------

class TestExplorerActions:
    @pytest.fixture()
    def web(self):
        import threading

        from corda_tpu.rpc.ops import CordaRPCOps
        from corda_tpu.testing import MockNetwork
        from corda_tpu.webserver import WebServer

        net = MockNetwork()
        net.create_notary_node(validating=True)
        node = net.create_node("O=ActBank,L=London,C=GB")
        net.create_node("O=ActPeer,L=Paris,C=FR")
        ops = CordaRPCOps(node.services, node.smm)
        server = WebServer(ops)
        stop = threading.Event()

        def pump():
            while not stop.wait(0.05):
                net.run_network()

        t = threading.Thread(target=pump, daemon=True, name="act-pump")
        t.start()
        yield ops, f"http://127.0.0.1:{server.port}"
        stop.set()
        t.join(timeout=5)
        server.stop()
        net.stop_nodes()

    @staticmethod
    def _post(base, path, form, timeout=30):
        data = urllib.parse.urlencode(form).encode()
        with urllib.request.urlopen(
            base + path, data=data, timeout=timeout
        ) as resp:
            return resp.status, json.loads(resp.read().decode())

    def test_issue_and_pay_forms(self, web):
        _, base = web
        status, body = self._post(
            base, "/action/issue", {"amount": "500", "currency": "USD"}
        )
        assert status == 200 and body["flow"] == "CashIssueFlow"
        assert body["tx_id"]
        status, body = self._post(
            base, "/action/pay",
            {"amount": "500", "currency": "USD", "peer": "ActPeer"},
        )
        assert status == 200 and body["flow"] == "CashPaymentFlow"
        assert body["tx_id"]

    def test_json_body_accepted_too(self, web):
        _, base = web
        req = urllib.request.Request(
            base + "/action/issue",
            data=json.dumps({"amount": 100, "currency": "USD"}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 200

    def test_unknown_and_ambiguous_peer_are_400(self, web):
        _, base = web
        self._post(base, "/action/issue", {"amount": "100"})
        with pytest.raises(urllib.error.HTTPError) as err:
            self._post(base, "/action/pay",
                       {"amount": "100", "peer": "NoSuchBank"})
        assert err.value.code == 400
        body = json.loads(err.value.read())
        assert body["error"] == "ValueError"
        assert "unknown" in body["message"]

    def test_overload_renders_typed_429_with_retry_hint(self, web):
        ops, base = web
        from corda_tpu.node.admission import NodeOverloadedError

        def shed(*a, **k):
            raise NodeOverloadedError(
                "node overloaded: unit", retry_after_ms=321
            )

        original = ops.start_flow_and_wait
        ops.start_flow_and_wait = shed
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                self._post(base, "/action/issue", {"amount": "100"})
            assert err.value.code == 429
            body = json.loads(err.value.read())
            assert body["error"] == "overloaded"
            assert body["retry_after_ms"] == 321
        finally:
            ops.start_flow_and_wait = original

    def test_bad_amount_is_400_not_500(self, web):
        _, base = web
        with pytest.raises(urllib.error.HTTPError) as err:
            self._post(base, "/action/issue", {"amount": "not-a-number"})
        assert err.value.code == 400

    def test_dashboard_ships_the_forms(self, web):
        _, base = web
        with urllib.request.urlopen(base + "/", timeout=30) as resp:
            page = resp.read().decode()
        assert '/action/issue' in page and '/action/pay' in page
        assert "retry_after_ms" in page  # typed overload rendering


# ---------------------------------------------------------------------------
# procdriver deadline knob + real.py fingerprint satellites
# ---------------------------------------------------------------------------

class TestSatellites:
    def test_loadtest_deadline_knob(self, monkeypatch):
        from corda_tpu.loadtest.procdriver import _deadline_s

        monkeypatch.delenv("CORDA_TPU_LOADTEST_DEADLINE_S", raising=False)
        assert _deadline_s(60.0) == 60.0
        monkeypatch.setenv("CORDA_TPU_LOADTEST_DEADLINE_S", "240")
        assert _deadline_s(60.0) == 240.0
        monkeypatch.setenv("CORDA_TPU_LOADTEST_DEADLINE_S", "garbage")
        assert _deadline_s(60.0) == 60.0

    def test_conflict_reconciliation_marks_vault_states(self):
        """The notary-conflict wedge fix: a conflict naming OUR inputs
        consumed by a foreign tx flips them consumed in the vault, so
        coin selection stops picking provably-dead states."""
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.finance.cash import CashState
        from corda_tpu.node.notary import (
            NotaryException,
            conflict_consumed_refs,
        )
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        net.create_notary_node(validating=True)
        bank = net.create_node("O=WedgeBank,L=London,C=GB")
        from corda_tpu.core.transactions.builder import TransactionBuilder
        from corda_tpu.finance.cash import CashCommand

        token = Issued(bank.info.ref(1), "USD")
        b = TransactionBuilder(notary=bank.info)
        b.add_output_state(
            CashState(amount=Amount(100, token), owner=bank.info)
        )
        b.add_command(CashCommand.Issue(), bank.info.owning_key)
        issue = bank.services.sign_initial_transaction(b)
        bank.services.record_transactions([issue])
        ref = issue.tx.out_ref(0).ref
        vault = bank.services.vault_service
        assert any(
            sr.ref == ref for sr in vault.unconsumed_states()
        )
        consuming = "AB" * 32
        exc = NotaryException(
            f"notary error: Conflict(tx_id=SecureHash(CD), "
            f"consumed={{'{ref!r}': SecureHash({consuming})}})"
        )
        pairs = conflict_consumed_refs(exc)
        assert pairs and pairs[0][0] == ref
        flipped = vault.mark_notary_consumed([p[0] for p in pairs])
        assert flipped == [ref]
        assert not any(
            sr.ref == ref for sr in vault.unconsumed_states()
        )
        # idempotent: a second reconciliation flips nothing
        assert vault.mark_notary_consumed([ref]) == []
        net.stop_nodes()

    def test_real_result_carries_fingerprint_and_topology(self):
        """loadtest/real.py records must be gate-comparable across
        boxes: env_fingerprint + host topology ride the result line
        (the same provenance block bench records carry)."""
        import inspect

        from corda_tpu.loadtest import real

        src = inspect.getsource(real.run)
        assert "env_fingerprint" in src and "host_topology" in src

    def test_rpc_reroute_inert_for_unsharded_unknown_ids(self):
        """A plain node owns every flow it started: unknown ids answer
        immediately (no reroute), tagged ids reroute only when a shard
        role is set."""
        from corda_tpu.messaging import Broker
        from corda_tpu.rpc.server import RPCServer

        class _Smm:
            flows = {}

        class _Ops:
            _smm = _Smm()

            def flow_result_future(self, fid):
                raise ValueError(f"unknown flow id {fid}")

        server = RPCServer.__new__(RPCServer)
        server.ops = _Ops()
        server.broker = Broker()
        server.shard_role = None
        assert not server._reroute_foreign({}, "plain-uuid", None)
        # a worker-tagged id reroutes even on role-less servers (the
        # tag itself proves a sharded sibling exists)
        assert server._reroute_foreign({}, "w2-abcd", None)
        server.shard_role = "worker"
        assert server._reroute_foreign({}, "plain-uuid", None)
        # spent budget: answered instead of bounced forever
        assert not server._reroute_foreign(
            {"_reroute_deadline": 1.0}, "w2-abcd", None
        )

"""Pipelined system-path tests: batched notary commits (coalescing
layer + putall_multi Raft protocol), the double-buffered signature
batcher, the scheme-aware verify cache, and the codec encode fast-path.

These pin the four tentpole stages of the batch-oriented verify→notarise
pipeline (see docs/perf-system.md, "The four-stage pipeline").
"""
import threading
import time
from collections import deque

import pytest

from corda_tpu.core.contracts import StateRef
from corda_tpu.core.crypto import SecureHash, crypto
from corda_tpu.core.identity import Party
from corda_tpu.node.database import NodeDatabase
from corda_tpu.node.notary import (
    CoalescingUniquenessProvider,
    Conflict,
    PersistentUniquenessProvider,
    RaftUniquenessProvider,
    UniquenessException,
    maybe_coalesced,
)

PARTY = Party("O=Notary,L=Zurich,C=CH", crypto.entropy_to_keypair(9).public)


def _ref(tag: bytes, idx: int = 0) -> StateRef:
    return StateRef(SecureHash.sha256(tag), idx)


def _tx(tag: bytes) -> SecureHash:
    return SecureHash.sha256(b"tx-" + tag)


# ---------------------------------------------------------------------------
# Stage 1: batched uniqueness commits
# ---------------------------------------------------------------------------

class TestPersistentCommitMany:
    def test_merged_batch_conflict_rejects_only_conflicting_tx(self):
        p = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        shared = _ref(b"shared")
        results = p.commit_many([
            ([_ref(b"a"), shared], _tx(b"a"), PARTY),
            ([_ref(b"b")], _tx(b"b"), PARTY),
            ([shared], _tx(b"c"), PARTY),  # loses to tx-a within the batch
        ])
        assert results[0] is None
        assert results[1] is None
        assert isinstance(results[2], Conflict)
        assert results[2].consumed  # names the winning tx
        # the rejected tx consumed NOTHING; the accepted ones did
        assert p._map.get(p._key(shared)) is not None
        assert p._map.get(p._key(_ref(b"b"))) is not None

    def test_batch_matches_sequential_semantics(self):
        seq = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        bat = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        requests = [
            ([_ref(b"r1")], _tx(b"1"), PARTY),
            ([_ref(b"r1")], _tx(b"2"), PARTY),   # conflict with 1
            ([_ref(b"r2"), _ref(b"r3")], _tx(b"3"), PARTY),
            ([_ref(b"r3")], _tx(b"4"), PARTY),   # conflict with 3
            ([_ref(b"r1")], _tx(b"1"), PARTY),   # idempotent re-commit
        ]
        seq_results = []
        for states, tx_id, party in requests:
            try:
                seq.commit(states, tx_id, party)
                seq_results.append(None)
            except UniquenessException as e:
                seq_results.append(e.conflict)
        bat_results = bat.commit_many(requests)
        assert [r is None for r in seq_results] == [
            r is None for r in bat_results
        ]

    def test_commit_single_still_raises(self):
        p = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        p.commit([_ref(b"x")], _tx(b"x1"), PARTY)
        with pytest.raises(UniquenessException):
            p.commit([_ref(b"x")], _tx(b"x2"), PARTY)


class _SyncRaft:
    """Single-node raft stand-in: applies commands synchronously."""

    def __init__(self):
        self.apply_fn = None
        self.snapshot_fn = None
        self.restore_fn = None
        self.log = []

    def submit(self, command):
        from concurrent.futures import Future

        self.log.append(command)
        fut = Future()
        fut.set_result(self.apply_fn(command))
        return fut


def _raft_provider():
    node = _SyncRaft()
    provider = RaftUniquenessProvider(node, NodeDatabase(":memory:"))
    node.apply_fn = provider.apply
    return provider, node


class TestRaftCommitMany:
    def test_one_log_entry_per_batch(self):
        p, node = _raft_provider()
        results = p.commit_many([
            ([_ref(b"m1")], _tx(b"m1"), PARTY),
            ([_ref(b"m2")], _tx(b"m2"), PARTY),
            ([_ref(b"m1")], _tx(b"m3"), PARTY),  # intra-batch conflict
        ])
        assert len(node.log) == 1  # ONE consensus round for the batch
        assert node.log[0]["kind"] == "putall_multi"
        assert results[0] is None and results[1] is None
        assert isinstance(results[2], Conflict)

    def test_legacy_putall_still_applies(self):
        # logs persisted before the batched protocol replay verbatim
        p, _ = _raft_provider()
        from corda_tpu.core.serialization.codec import serialize

        blob = serialize({"tx_id": _tx(b"old"), "by": PARTY.name})
        key = PersistentUniquenessProvider._key(_ref(b"old")).hex()
        out = p.apply({"kind": "putall", "entries": {key: blob}})
        assert out == {"conflicts": {}}
        assert p.is_consumed(_ref(b"old"))

    def test_batched_state_survives_snapshot_restore(self):
        p1, _ = _raft_provider()
        p1.commit_many([
            ([_ref(b"s1")], _tx(b"s1"), PARTY),
            ([_ref(b"s2")], _tx(b"s2"), PARTY),
        ])
        snap = p1.snapshot()
        p2, _ = _raft_provider()
        p2.restore(snap)
        assert p2.is_consumed(_ref(b"s1"))
        assert p2.is_consumed(_ref(b"s2"))
        # a conflicting commit against restored state still rejects
        res = p2.commit_many([([_ref(b"s1")], _tx(b"other"), PARTY)])
        assert isinstance(res[0], Conflict)


class TestCoalescing:
    def test_concurrent_commits_coalesce(self):
        inner = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        calls = []
        orig = inner.commit_many

        def spy(requests):
            calls.append(len(requests))
            time.sleep(0.01)  # hold the round open so others queue
            return orig(requests)

        inner.commit_many = spy
        c = CoalescingUniquenessProvider(inner)
        n = 24
        errs = []

        def commit(i):
            try:
                c.commit([_ref(b"c%d" % i)], _tx(b"c%d" % i), PARTY)
            except Exception as exc:
                errs.append(exc)

        threads = [
            threading.Thread(target=commit, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert sum(calls) == n
        assert c.commits == n
        assert c.batches == len(calls) < n  # actually coalesced
        assert c.mean_batch > 1.0
        assert c.largest_batch == max(calls)

    def test_conflict_demuxes_to_the_right_caller(self):
        c = maybe_coalesced(
            PersistentUniquenessProvider(NodeDatabase(":memory:"))
        )
        assert isinstance(c, CoalescingUniquenessProvider)
        c.commit([_ref(b"d")], _tx(b"d1"), PARTY)
        with pytest.raises(UniquenessException) as ei:
            c.commit([_ref(b"d")], _tx(b"d2"), PARTY)
        assert ei.value.conflict.tx_id == _tx(b"d2")
        # unrelated commit unaffected
        c.commit([_ref(b"e")], _tx(b"e1"), PARTY)

    def test_observability_passthrough(self):
        inner = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        c = CoalescingUniquenessProvider(inner)
        c.commit([_ref(b"f")], _tx(b"f"), PARTY)
        # delegated attribute access (tests/dryruns poke these)
        assert c._map.get(c._key(_ref(b"f"))) is not None

    def test_env_gate_disables(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_NOTARY_COALESCE", "0")
        p = PersistentUniquenessProvider(NodeDatabase(":memory:"))
        assert maybe_coalesced(p) is p


# ---------------------------------------------------------------------------
# Stage 2: double-buffered signature batcher
# ---------------------------------------------------------------------------

class TestDoubleBufferedBatcher:
    def _items(self, n, entropy0=700):
        items = []
        for i in range(n):
            kp = crypto.entropy_to_keypair(entropy0 + i)
            content = b"dbl-%d" % i
            items.append(
                (kp.public, crypto.do_sign(kp.private, content), content)
            )
        return items

    def test_submit_keeps_filling_while_flush_runs(self, monkeypatch):
        from corda_tpu.verifier import batcher as batcher_mod

        started = threading.Event()
        release = threading.Event()
        real = batcher_mod.crypto_batch.verify_batch

        def slow_verify(items):
            started.set()
            release.wait(5)
            return real(items)

        monkeypatch.setattr(
            batcher_mod.crypto_batch, "verify_batch", slow_verify
        )
        # pipeline=False: this test pins the SYNCHRONOUS double-buffer
        # machinery (the CORDA_TPU_PIPELINE=0 path) by stubbing
        # verify_batch; the staged-pipeline equivalents live in
        # tests/test_pipeline.py
        b = batcher_mod.SignatureBatcher(
            max_batch=2, linger_ms=10_000, pipeline=False
        )
        items = self._items(4)
        f01 = b.submit_many(items[:2])  # hits max_batch -> flush thread
        assert started.wait(5)
        # the flush thread is parked inside verify; submit must NOT block
        t0 = time.perf_counter()
        f23 = b.submit_many(items[2:])
        assert time.perf_counter() - t0 < 1.0
        release.set()
        assert all(f.result(timeout=10) for f in f01 + f23)
        assert b.handoffs == 2
        assert b.flushes == 2

    def test_linger_hands_off_instead_of_flushing_on_wheel(self, monkeypatch):
        from corda_tpu.verifier import batcher as batcher_mod

        flushed_on = []
        real = batcher_mod.crypto_batch.verify_batch

        def spy(items):
            flushed_on.append(threading.current_thread().name)
            return real(items)

        monkeypatch.setattr(batcher_mod.crypto_batch, "verify_batch", spy)
        # pipeline=False: pins the sync-path wheel-callback contract
        b = batcher_mod.SignatureBatcher(
            max_batch=1000, linger_ms=20, pipeline=False
        )
        fut = b.submit(self._items(1)[0])
        assert fut.result(timeout=10) is True
        # the verify body ran on the batcher's own flush thread, never on
        # the shared wheel's callback pool (ADVICE r5 finding)
        assert flushed_on == ["sig-batcher-flush"]

    def test_flush_waits_for_in_flight_background_batches(self, monkeypatch):
        from corda_tpu.verifier import batcher as batcher_mod

        release = threading.Event()
        real = batcher_mod.crypto_batch.verify_batch

        def slow_verify(items):
            release.wait(5)
            return real(items)

        monkeypatch.setattr(
            batcher_mod.crypto_batch, "verify_batch", slow_verify
        )
        # pipeline=False: pins the sync-path flush-waits contract
        b = batcher_mod.SignatureBatcher(
            max_batch=1, linger_ms=10_000, pipeline=False
        )
        futs = b.submit_many(self._items(1))
        timer = threading.Timer(0.2, release.set)
        timer.start()
        b.flush()  # must block until the background batch resolved
        assert futs[0].done()
        timer.cancel()

    def test_close_under_concurrent_submit_strands_no_future(self):
        b = None
        from corda_tpu.verifier.batcher import SignatureBatcher

        b = SignatureBatcher(max_batch=4, linger_ms=5)
        items = self._items(12)
        futures = []
        rejected = []
        stop = threading.Event()

        def submitter(chunk):
            for it in chunk:
                try:
                    futures.append(b.submit(it))
                except RuntimeError:
                    rejected.append(it)
                if stop.is_set():
                    return

        threads = [
            threading.Thread(target=submitter, args=(items[i::3],))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.02)
        b.close()
        stop.set()
        for t in threads:
            t.join()
        # every accepted future resolves; rejected submits raised cleanly
        for f in futures:
            assert f.result(timeout=10) is True
        assert len(futures) + len(rejected) == len(items)

    def test_ordering_telemetry_consistent(self):
        from corda_tpu.verifier.batcher import SignatureBatcher

        b = SignatureBatcher(max_batch=4, linger_ms=10_000)
        # one oversized submit ships as ONE buffer (old flush semantics);
        # two sequential submits each hit max_batch and hand off
        futs = b.submit_many(self._items(4))
        futs += b.submit_many(self._items(4, entropy0=800))
        assert all(f.result(timeout=10) for f in futs)
        b.close()
        assert b.items_verified == 8
        assert b.flushes == 2
        assert b.largest_batch == 4
        assert b.flush_wall_s > 0.0


# ---------------------------------------------------------------------------
# Scheme-aware verify cache (satellite regression)
# ---------------------------------------------------------------------------

def test_verify_cache_key_is_scheme_aware():
    """A signature cache-accepted under ed25519 must NOT be accepted for
    a key claiming a different scheme with identical encoded bytes
    (ADVICE r5 medium: warm- vs cold-cache replicas would split)."""
    from corda_tpu.core.crypto.keys import SchemePublicKey
    from corda_tpu.core.crypto.signing import DigitalSignatureWithKey
    from corda_tpu.core.transactions import signed as signed_mod

    kp = crypto.entropy_to_keypair(4242)
    content = SecureHash.sha256(b"cache-split").bytes

    class FakeTx(signed_mod.TransactionWithSignatures):
        def __init__(self, sigs):
            self.sigs = tuple(sigs)

        @property
        def id(self):
            return SecureHash.sha256(b"cache-split")

        @property
        def required_signing_keys(self):
            return frozenset()

    good = DigitalSignatureWithKey(
        bytes=crypto.do_sign(kp.private, content), by=kp.public
    )
    FakeTx([good]).check_signatures_are_valid()  # warms the cache
    # same encoded bytes, different claimed scheme -> must NOT cache-hit
    imposter_key = SchemePublicKey(
        "ECDSA_SECP256R1_SHA256", kp.public.encoded
    )
    imposter = DigitalSignatureWithKey(bytes=good.bytes, by=imposter_key)
    with pytest.raises(Exception):
        FakeTx([imposter]).check_signatures_are_valid()
    # and the warm entry still serves the REAL key
    FakeTx([good]).check_signatures_are_valid()


# ---------------------------------------------------------------------------
# Codec encode fast-path parity (stage 3)
# ---------------------------------------------------------------------------

def test_codec_fast_path_bytes_identical():
    """The pre-bound encoder must emit byte-for-byte what the generic
    path emits (tx ids are Merkle roots over these bytes)."""
    from corda_tpu.core.serialization import codec
    from corda_tpu.node.session import SessionData, SessionInit

    values = [
        SessionData("sess-1", 3, b"payload" * 20),
        SessionInit("init-1", "SomeFlow", 1, None),
        {"k": [1, 2, {"n": SessionData("s", 0, b"")}]},
    ]
    for v in values:
        out_fast = bytearray(b"")
        codec._encode(out_fast, v)  # warm cache then re-encode
        out_fast = bytearray(b"")
        codec._encode(out_fast, v)
        codec._ENC_CACHE.clear()
        codec._MRO_CACHE.clear()
        out_cold = bytearray(b"")
        codec._encode(out_cold, v)
        assert bytes(out_fast) == bytes(out_cold)
        # and a decode round-trip survives
        blob = codec.serialize(v)
        assert codec.serialize(codec.deserialize(blob)) == blob


def test_codec_encode_stats_seam():
    from corda_tpu.core.serialization import codec
    from corda_tpu.node.session import SessionEnd

    before = codec.encode_stats()["obj_fast"]
    for _ in range(3):
        codec.serialize(SessionEnd("x", None))
    after = codec.encode_stats()["obj_fast"]
    if codec._native_codec is None:
        assert after >= before + 2  # fast path engaged after first encode
    else:  # native codec encodes objects C-side; stats only track Python
        assert after >= before


# ---------------------------------------------------------------------------
# Broker batched pump (stage 4)
# ---------------------------------------------------------------------------

class TestBrokerReceiveMany:
    def test_receive_many_drains_in_one_call(self):
        from corda_tpu.messaging import Broker

        broker = Broker()
        broker.create_queue("q")
        c = broker.create_consumer("q")
        for i in range(10):
            broker.send("q", b"m%d" % i)
        batch = c.receive_many(8, timeout=1)
        assert [m.payload for m in batch] == [b"m%d" % i for i in range(8)]
        c.ack_many(batch)
        rest = c.receive_many(8, timeout=1)
        assert len(rest) == 2
        c.ack_many(rest)
        assert broker.message_count("q") == 0

    def test_receive_many_blocks_then_times_out(self):
        from corda_tpu.messaging import Broker

        broker = Broker()
        broker.create_queue("q2")
        c = broker.create_consumer("q2")
        t0 = time.perf_counter()
        assert c.receive_many(4, timeout=0.1) == []
        assert time.perf_counter() - t0 >= 0.09

    def test_unacked_batch_redelivers_on_close(self):
        from corda_tpu.messaging import Broker

        broker = Broker()
        broker.create_queue("q3")
        c1 = broker.create_consumer("q3")
        broker.send("q3", b"a")
        broker.send("q3", b"b")
        batch = c1.receive_many(8, timeout=1)
        assert len(batch) == 2
        c1.close()  # died mid-batch: both must redeliver, in order
        c2 = broker.create_consumer("q3")
        redelivered = c2.receive_many(8, timeout=1)
        assert [m.payload for m in redelivered] == [b"a", b"b"]
        assert all(m.delivery_count == 2 for m in redelivered)

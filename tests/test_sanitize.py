"""ASan/UBSan build-and-run gate (corda_tpu/analysis/sanitize.py;
ISSUE 13).

Pins the CI contract: the runner exits nonzero exactly when a
sanitizer REPORTS (or the suites fail under it), 0-with-notice when
the toolchain is absent (classified skip), and its report parser turns
raw sanitizer logs into named findings.  On a box with the toolchain,
the real UBSan leg runs tier-1 (builds are srchash-cached); the ASan
leg and the detection canaries for both modes prove the harness
catches a planted bug end-to-end.
"""
import os
import shutil
import subprocess
import sys

import pytest

from corda_tpu.analysis import sanitize

HAVE_UBSAN = sanitize.classify_skip("ubsan") is None
HAVE_ASAN = sanitize.classify_skip("asan") is None


class TestClassification:
    def test_no_compiler_is_classified(self, monkeypatch):
        monkeypatch.setattr(shutil, "which", lambda name: None)
        assert sanitize.classify_skip("asan") == "no_compiler"
        assert sanitize.classify_skip("ubsan") == "no_compiler"

    def test_missing_runtime_is_classified(self, monkeypatch):
        monkeypatch.setattr(sanitize, "_runtime_lib", lambda mode: None)
        assert sanitize.classify_skip("asan") == "no_asan_runtime"
        assert sanitize.classify_skip("ubsan") == "no_ubsan_runtime"

    def test_skip_short_circuits_run_one(self, monkeypatch):
        monkeypatch.setattr(sanitize, "classify_skip",
                            lambda mode: "no_compiler")
        r = sanitize.run_one("asan")
        assert r["status"] == "skip" and r["skip_reason"] == "no_compiler"

    @pytest.mark.skipif(not HAVE_ASAN, reason="no asan runtime here")
    def test_runtime_lib_resolves_to_elf(self):
        path = sanitize._runtime_lib("asan")
        with open(path, "rb") as fh:
            assert fh.read(4) == b"\x7fELF"


class TestReportParsing:
    def _write_log(self, tmp_path, mode, text):
        (tmp_path / f"{mode}.12345").write_text(text)
        return str(tmp_path)

    def test_asan_error_classified(self, tmp_path):
        d = self._write_log(tmp_path, "asan", (
            "==1==ERROR: AddressSanitizer: heap-buffer-overflow on "
            "address 0x60200000001 at pc 0x7f\n"
            "    #0 0x7f in corda_tpu_canary\n"
            "SUMMARY: AddressSanitizer: heap-buffer-overflow in x\n"
        ))
        findings = sanitize._parse_logs(d, "asan")
        assert [f["kind"] for f in findings] == ["heap-buffer-overflow"]
        assert "SUMMARY" not in findings[0]["line"]

    def test_leak_report_classified(self, tmp_path):
        d = self._write_log(tmp_path, "asan", (
            "==1==ERROR: LeakSanitizer: detected memory leaks\n"
            "Direct leak of 8 byte(s) in 1 object(s)\n"
            "SUMMARY: AddressSanitizer: 8 byte(s) leaked\n"
        ))
        findings = sanitize._parse_logs(d, "asan")
        assert [f["kind"] for f in findings] == ["leak"]

    def test_ubsan_runtime_error_classified(self, tmp_path):
        d = self._write_log(tmp_path, "ubsan", (
            "canary.c:4:22: runtime error: signed integer overflow: "
            "2147483647 + 1 cannot be represented in type 'int'\n"
        ))
        findings = sanitize._parse_logs(d, "ubsan")
        assert len(findings) == 1
        assert findings[0]["kind"].startswith("ub: signed integer")

    def test_other_modes_logs_ignored(self, tmp_path):
        d = self._write_log(tmp_path, "asan", "ERROR: AddressSanitizer: x\n")
        assert sanitize._parse_logs(d, "ubsan") == []


class TestChildBuildClassification:
    """run_child must distinguish an ABSENT toolchain (exit 3, the
    0-with-notice skip) from an instrumented build that FAILED with the
    toolchain present (exit 2 — the gate must go red, not silently
    skip)."""

    def _run(self, monkeypatch, tmp_path, reason):
        import corda_tpu.native as native

        monkeypatch.setattr(native, "build_all", lambda sanitize=None: {
            "codec_ext": {"available": False, "reason": reason},
        })
        report = tmp_path / "r.json"
        rc = sanitize.run_child("ubsan", str(report))
        import json as _json

        return rc, _json.loads(report.read_text())

    def test_no_compiler_is_a_skip(self, monkeypatch, tmp_path):
        rc, report = self._run(monkeypatch, tmp_path, "no_compiler")
        assert rc == 3 and report["skip"] == "no_compiler"

    def test_compile_error_is_a_failure(self, monkeypatch, tmp_path):
        rc, report = self._run(monkeypatch, tmp_path,
                               "compile_error: boom")
        assert rc == 2
        assert "instrumented build failed" in report["error"]


class TestExitCodes:
    """The CI contract, with the children stubbed out."""

    def _main(self, monkeypatch, result):
        monkeypatch.setattr(sanitize, "run_one",
                            lambda mode, timeout=0: {**result,
                                                     "mode": mode})
        return sanitize.main(["--sanitizer", "asan"])

    def test_clean_exits_zero(self, monkeypatch, capsys):
        rc = self._main(monkeypatch, {"status": "clean", "findings": [],
                                      "report": {"suites": {}}})
        assert rc == 0
        assert "PASS" in capsys.readouterr().err

    def test_findings_exit_nonzero_and_named(self, monkeypatch, capsys):
        rc = self._main(monkeypatch, {
            "status": "findings",
            "findings": [{"kind": "heap-use-after-free", "log": "asan.1",
                          "line": "ERROR: ..."}],
        })
        assert rc == 1
        err = capsys.readouterr().err
        assert "SANITIZER FINDING asan:heap-use-after-free" in err

    def test_skip_exits_zero_with_notice(self, monkeypatch, capsys):
        rc = self._main(monkeypatch, {"status": "skip", "findings": [],
                                      "skip_reason": "no_compiler"})
        assert rc == 0
        err = capsys.readouterr().err
        assert "SKIP (no_compiler)" in err and "not a failure" in err

    def test_infrastructure_error_exits_nonzero(self, monkeypatch,
                                                capsys):
        rc = self._main(monkeypatch, {"status": "error", "findings": [],
                                      "skip_reason": "child_timeout"})
        assert rc == 1

    def test_cli_no_toolchain_subprocess(self, tmp_path):
        """End-to-end 0-with-notice: a PATH without compilers."""
        env = dict(os.environ)
        env["PATH"] = str(tmp_path)  # empty dir: no gcc/g++
        proc = subprocess.run(
            [sys.executable, "-m", "corda_tpu.analysis.sanitize",
             "--sanitizer", "ubsan"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        assert "SKIP (no_compiler)" in proc.stderr


@pytest.mark.skipif(not HAVE_UBSAN, reason="no ubsan runtime here")
class TestRealUBSan:
    def test_parity_suites_clean_under_ubsan(self):
        """The acceptance run: build the five extensions instrumented,
        replay the codec/pump parity + fuzz suites and the malformed
        corpus under UBSan — clean.  (Builds are srchash-cached, so
        reruns cost ~1s.)"""
        r = sanitize.run_one("ubsan", timeout=sanitize._CHILD_TIMEOUT)
        assert r["status"] == "clean", r
        suites = r["report"]["suites"]
        assert suites["codec_roundtrips"] >= 100
        assert suites["malformed_frames"] >= 25  # builtin + corpus
        assert suites["pump_msgs"] >= 100

    def test_self_test_detects_planted_ub(self):
        """Detection proof: a signed-overflow canary must be reported
        (the sanitizer analogue of the lint suite's synthetic
        violations)."""
        r = sanitize.self_test("ubsan")
        assert r["status"] == "detected", r


@pytest.mark.slow
@pytest.mark.skipif(not HAVE_ASAN, reason="no asan runtime here")
class TestRealASan:
    def test_parity_suites_clean_under_asan_with_leak_check(self):
        r = sanitize.run_one("asan", timeout=sanitize._CHILD_TIMEOUT)
        assert r["status"] == "clean", r
        assert r["report"]["leak_check"] == "clean"

    def test_self_test_detects_planted_overflow(self):
        r = sanitize.self_test("asan")
        assert r["status"] == "detected", r

"""Clean-venv install smoke (capsule parity, r3 VERDICT #10).

The reference ships each process surface as a self-contained capsule jar
(`node/capsule/build.gradle:26-45`); the TPU build's equivalent is one
pip-installable artifact whose console scripts (corda-node,
corda-cordform, ...) carry the full process surface, with the native C
components shipped as package-data source that compiles on first use.

This suite proves the artifact works OUTSIDE the repo checkout: install
into a fresh venv, deploy a network with the INSTALLED cordform, boot the
INSTALLED corda-node binaries, and watch them come up. Nightly tier: it
builds a wheel and boots OS processes.
"""
import json
import os
import shutil
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def clean_venv(tmp_path_factory):
    venv = tmp_path_factory.mktemp("capsule") / "venv"
    subprocess.run([sys.executable, "-m", "venv", str(venv)], check=True)
    # the running interpreter is itself a venv (/opt/venv): chain its
    # site-packages via a .pth so numpy/jax/setuptools resolve, while the
    # new venv's own site-packages (holding corda-tpu) stays in front
    site = next((venv / "lib").glob("python*")) / "site-packages"
    for p in sys.path:
        if p.endswith("site-packages") and os.path.isdir(p):
            with open(site / "_deps.pth", "a") as fh:
                fh.write(p + "\n")
    subprocess.run(
        [str(venv / "bin" / "pip"), "install", "--no-build-isolation",
         "--no-index", "-q", REPO],
        check=True,
    )
    return venv


def _run_outside_repo(argv, **kw):
    """Run with cwd away from the checkout so `import corda_tpu` can only
    resolve to the installed package."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    return subprocess.run(
        argv, cwd="/tmp", env=env, capture_output=True, text=True,
        timeout=kw.pop("timeout", 120), **kw
    )


def test_installed_package_resolves_outside_checkout(clean_venv):
    out = _run_outside_repo([
        str(clean_venv / "bin" / "python"), "-c",
        "import corda_tpu; print(corda_tpu.__file__)",
    ])
    assert out.returncode == 0, out.stderr
    assert str(clean_venv) in out.stdout, out.stdout


def test_native_sources_ship_in_the_artifact(clean_venv):
    site = next((clean_venv / "lib").glob("python*")) / "site-packages"
    src = site / "corda_tpu" / "native" / "src"
    assert (src / "codec_ext.c").exists()
    assert (src / "sha2_batch.cpp").exists()
    assert (src / "journal.cpp").exists()
    assert (src / "ed25519_msm.cpp").exists()
    web = site / "corda_tpu" / "webserver" / "static"
    assert (web / "dashboard.html").exists()


def test_cordform_deploy_and_runnodes_from_installed_package(
    clean_venv, tmp_path
):
    spec = tmp_path / "network.json"
    spec.write_text(json.dumps({"nodes": [
        {"name": "O=CapNotary,L=Zurich,C=CH", "notary": "validating",
         "network_map_service": True},
        {"name": "O=CapBank,L=London,C=GB"},
    ]}))
    out_dir = tmp_path / "out"
    deployed = _run_outside_repo([
        str(clean_venv / "bin" / "corda-cordform"), str(spec), str(out_dir),
    ])
    assert deployed.returncode == 0, deployed.stderr

    procs = []
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["CORDA_TPU_EXIT_ON_ORPHAN"] = "1"
    try:
        for name in ("CapNotary", "CapBank"):
            d = out_dir / name
            procs.append(subprocess.Popen(
                [str(clean_venv / "bin" / "corda-node"), str(d),
                 "--jax-platform", "cpu"],
                cwd="/tmp", env=env,
                stdout=open(d / "node.log", "w"), stderr=subprocess.STDOUT,
            ))
        deadline = time.monotonic() + 120
        want = [out_dir / n / "broker.port" for n in ("CapNotary", "CapBank")]
        while time.monotonic() < deadline:
            if all(p.exists() for p in want):
                break
            for proc, name in zip(procs, ("CapNotary", "CapBank")):
                assert proc.poll() is None, (
                    f"{name} died:\n"
                    + (out_dir / name / "node.log").read_text()[-2000:]
                )
            time.sleep(1)
        assert all(p.exists() for p in want), "nodes never became ready"
        log = (out_dir / "CapBank" / "node.log").read_text()
        assert "node ready" in log
    finally:
        for proc in procs:
            proc.kill()
        for proc in procs:
            proc.wait(timeout=30)
        shutil.rmtree(out_dir, ignore_errors=True)

"""The controllable TCP partition proxy (loadtest/netproxy.py) and its
composition with the verifier failover path (docs/robustness.md):

  * per-direction drop / black-hole / delay / stall semantics plus the
    heal contract (tainted streams closed, intact streams resumed);
  * the command-file CLI the ssh soak driver controls remote proxies
    through;
  * a proxy-partitioned RemoteBroker worker link tripping the circuit
    breaker (fallback serves — zero hung futures) and RECOVERING after
    the heal;
  * a SIGSTOPped real verifier process surviving the deadline path.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from corda_tpu.core.crypto import crypto
from corda_tpu.loadtest.netproxy import DIRECTIONS, MODES, NetProxy


# ---------------------------------------------------------------------------
# plumbing: a tiny echo server to proxy
# ---------------------------------------------------------------------------

class _Echo:
    def __init__(self):
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self._threads = []
        t = threading.Thread(
            target=self._accept, daemon=True, name="echo-accept"
        )
        t.start()
        self._threads.append(t)

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._pump, args=(conn,), daemon=True,
                name="echo-pump",
            )
            t.start()
            self._threads.append(t)

    def _pump(self, conn):
        while True:
            try:
                data = conn.recv(4096)
            except OSError:
                return
            if not data:
                return
            try:
                conn.sendall(data.upper())
            except OSError:
                return

    def close(self):
        try:
            self.srv.close()
        except OSError:
            pass


@pytest.fixture()
def echo_proxy():
    echo = _Echo()
    proxy = NetProxy("127.0.0.1", echo.port).start()
    yield echo, proxy
    proxy.stop()
    echo.close()


def _client(port, timeout=5.0):
    c = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    c.settimeout(timeout)
    return c


def _recv_or_none(c, timeout=0.5):
    c.settimeout(timeout)
    try:
        return c.recv(4096)
    except socket.timeout:
        return None
    except OSError:
        return b""


class TestNetProxyModes:
    def test_pass_forwards_both_directions(self, echo_proxy):
        _, proxy = echo_proxy
        c = _client(proxy.port)
        c.sendall(b"hello")
        assert c.recv(100) == b"HELLO"
        # stats increment after the forward; poll briefly
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            stats = proxy.stats()
            if stats["bytes_c2s"] >= 5 and stats["bytes_s2c"] >= 5:
                break
            time.sleep(0.02)
        assert stats["bytes_c2s"] == 5 and stats["bytes_s2c"] == 5
        c.close()

    def test_stall_blocks_then_heal_resumes_stream_intact(self, echo_proxy):
        _, proxy = echo_proxy
        c = _client(proxy.port)
        c.sendall(b"a")
        assert c.recv(10) == b"A"
        proxy.set_mode("stall", "both")
        time.sleep(0.25)
        c.sendall(b"later")
        assert _recv_or_none(c) is None, "stalled wire delivered data"
        proxy.heal()
        c.settimeout(5)
        # the SAME connection resumes with framing intact: stall
        # buffers in kernel queues, it never discards
        assert c.recv(100) == b"LATER"
        c.close()

    def test_blackhole_discards_and_heal_closes_tainted(self, echo_proxy):
        _, proxy = echo_proxy
        c = _client(proxy.port)
        c.sendall(b"a")
        assert c.recv(10) == b"A"
        proxy.set_mode("blackhole", "c2s")
        time.sleep(0.25)
        c.sendall(b"lost")
        time.sleep(0.4)
        assert proxy.stats()["bytes_discarded"] >= 4
        proxy.heal()
        # bytes were discarded mid-stream: the heal CLOSES the tainted
        # connection (a resumed corrupt stream would be worse than a
        # reset); a fresh connection works
        time.sleep(0.3)
        data = _recv_or_none(c, timeout=2.0)
        assert data == b"", f"tainted conn survived heal: {data!r}"
        c2 = _client(proxy.port)
        c2.sendall(b"again")
        assert c2.recv(100) == b"AGAIN"
        c2.close()

    def test_blackhole_is_per_direction(self, echo_proxy):
        _, proxy = echo_proxy
        c = _client(proxy.port)
        c.sendall(b"a")
        assert c.recv(10) == b"A"
        # discard only server->client: the send still REACHES the echo
        proxy.set_mode("blackhole", "s2c")
        time.sleep(0.25)
        c.sendall(b"gone")
        time.sleep(0.4)
        stats = proxy.stats()
        assert stats["bytes_c2s"] >= 5  # request forwarded
        assert stats["bytes_discarded"] >= 4  # reply eaten
        assert _recv_or_none(c) is None
        c.close()

    def test_delay_adds_latency_but_delivers(self, echo_proxy):
        _, proxy = echo_proxy
        proxy.set_mode("delay", "c2s", delay_s=0.4)
        time.sleep(0.25)
        c = _client(proxy.port)
        t0 = time.monotonic()
        c.sendall(b"slow")
        assert c.recv(100) == b"SLOW"
        assert time.monotonic() - t0 >= 0.3
        c.close()

    def test_drop_refuses_new_and_resets_existing(self, echo_proxy):
        _, proxy = echo_proxy
        c = _client(proxy.port)
        c.sendall(b"a")
        assert c.recv(10) == b"A"
        proxy.set_mode("drop", "both")
        time.sleep(0.3)
        # existing connection reset
        assert _recv_or_none(c, timeout=2.0) == b""
        # new connections refused (accept+close or connect failure)
        try:
            c2 = _client(proxy.port, timeout=2.0)
            assert c2.recv(10) == b""
            c2.close()
        except OSError:
            pass  # connection reset at connect: equally refused
        proxy.heal()
        time.sleep(0.3)
        c3 = _client(proxy.port)
        c3.sendall(b"back")
        assert c3.recv(100) == b"BACK"
        c3.close()

    def test_bad_mode_and_direction_rejected(self, echo_proxy):
        _, proxy = echo_proxy
        with pytest.raises(ValueError, match="unknown mode"):
            proxy.set_mode("nonsense")
        with pytest.raises(ValueError, match="unknown direction"):
            proxy.set_mode("stall", "upwards")
        assert set(DIRECTIONS) == {"c2s", "s2c"}
        assert "stall" in MODES and "blackhole" in MODES


class TestNetProxyCli:
    def test_control_file_protocol(self, tmp_path):
        """The remote-rig form: command file polled, state file acked
        with seq + applied modes; bad commands surface in state.error
        instead of killing the proxy."""
        echo = _Echo()
        control = str(tmp_path / "proxy.ctl")
        state_path = control + ".state"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "PYTHONPATH": repo}
        proc = subprocess.Popen(
            [sys.executable, "-m", "corda_tpu.loadtest.netproxy",
             "--target", f"127.0.0.1:{echo.port}", "--control", control],
            env=env,
        )
        try:
            deadline = time.monotonic() + 30
            state = None
            while time.monotonic() < deadline:
                if os.path.exists(state_path):
                    with open(state_path) as fh:
                        state = json.load(fh)
                    break
                time.sleep(0.05)
            assert state and state["port"], "proxy never wrote its state"
            port = state["port"]

            c = _client(port, timeout=10)
            c.sendall(b"one")
            assert c.recv(100) == b"ONE"

            def command(seq, text):
                with open(control + ".tmp", "w") as fh:
                    fh.write(f"{seq} {text}\n")
                os.replace(control + ".tmp", control)
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    with open(state_path) as fh:
                        s = json.load(fh)
                    if s.get("seq", -1) >= seq:
                        return s
                    time.sleep(0.05)
                raise AssertionError(f"proxy never acked seq {seq}")

            s = command(1, "mode stall both")
            assert s["modes"] == {"c2s": "stall", "s2c": "stall"}
            c.sendall(b"two")
            assert _recv_or_none(c) is None
            s = command(2, "heal")
            assert s["modes"] == {"c2s": "pass", "s2c": "pass"}
            c.settimeout(5)
            assert c.recv(100) == b"TWO"
            s = command(3, "mode sideways both")
            assert "bad proxy command" in s.get("error", "") or \
                "unknown mode" in s.get("error", "")
            # proxy still alive and serving after the bad command
            c.sendall(b"three")
            assert c.recv(100) == b"THREE"
            c.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)
            echo.close()


# ---------------------------------------------------------------------------
# composition with the verifier failover path
# ---------------------------------------------------------------------------

def _sig_items(n, entropy0=41000):
    items = []
    for i in range(n):
        kp = crypto.entropy_to_keypair(entropy0 + i)
        content = b"netproxy-msg-%d" % i
        items.append(
            (kp.public, crypto.do_sign(kp.private, content), content)
        )
    return items


class TestProxyPartitionedVerifier:
    def test_stalled_worker_link_trips_breaker_then_recovers(self):
        """An in-process verifier service + a worker connected through
        the proxy over a REAL BrokerServer socket. Stalling the wire is
        the gray failure: the consumer stays registered but answers
        nothing — the deadline supervisor redispatches, failures stack,
        the breaker opens and the FALLBACK serves (zero hung futures).
        After the heal the half-open probe closes the breaker on the
        live worker again."""
        from corda_tpu.messaging import Broker
        from corda_tpu.messaging.net import BrokerServer, RemoteBroker
        from corda_tpu.verifier import (
            OutOfProcessTransactionVerifierService,
            VerifierWorker,
        )

        broker = Broker()
        server = BrokerServer(broker, port=0)
        server.start()
        proxy = NetProxy("127.0.0.1", server.port).start()
        remote = RemoteBroker("127.0.0.1", proxy.port)
        worker = None
        svc = None
        try:
            worker = VerifierWorker(remote, name="proxied").start()
            svc = OutOfProcessTransactionVerifierService(
                broker, "proxy-test", deadline_s=0.4, max_retries=1,
            )
            svc.breaker.cooldown_s = 0.4
            items = _sig_items(4)
            futures = svc.verify_signatures(items)
            assert all(f.result(timeout=30) for f in futures)
            assert svc.breaker.state == "closed"

            # partition: stall BOTH directions of the worker's link.
            # Each stalled call exhausts its deadline budget and records
            # a breaker failure; at the threshold (3) the breaker OPENS.
            # Every future still completes — the fallback serves.
            proxy.set_mode("stall", "both")
            time.sleep(0.2)
            for _ in range(3):
                futures = svc.verify_signatures(items)
                assert all(f.result(timeout=30) for f in futures), (
                    "futures hung behind the stalled wire"
                )
            assert svc.breaker.trips >= 1
            assert svc.breaker.state in ("open", "half-open")

            # heal: the worker drains its backlog; after the cooldown a
            # probe lands on it and the breaker closes again
            proxy.heal()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                futures = svc.verify_signatures(_sig_items(2, 42000))
                assert all(f.result(timeout=30) for f in futures)
                if svc.breaker.state == "closed":
                    break
                time.sleep(0.3)
            assert svc.breaker.state == "closed", (
                f"breaker never recovered: {svc.breaker.state}"
            )
        finally:
            # heal FIRST: worker/remote teardown over a still-stalled
            # wire blocks on the dead socket
            proxy.heal()
            if svc is not None:
                svc.stop()
            if worker is not None:
                worker.stop(graceful=False)
            remote.close()
            proxy.stop()
            server.stop()
            broker.close()


class TestSigstopRealProcess:
    def test_sigstopped_worker_process_survives_deadline_path(self, tmp_path):
        """SIGSTOP a REAL out-of-process verifier worker mid-service:
        the process keeps its socket (consumer registered, queue
        stalls) — the requester-side deadline/redispatch/fallback path
        must complete every future; SIGCONT restores it and the breaker
        recovers."""
        from corda_tpu.loadtest.chaos import _Worker
        from corda_tpu.messaging import Broker
        from corda_tpu.messaging.net import BrokerServer
        from corda_tpu.verifier import OutOfProcessTransactionVerifierService

        broker = Broker()
        server = BrokerServer(broker, port=0)
        server.start()
        worker = _Worker(
            str(tmp_path), f"127.0.0.1:{server.port}", "sigstop-w0"
        )
        svc = None
        try:
            worker.launch(timeout=120)
            svc = OutOfProcessTransactionVerifierService(
                broker, "sigstop-test", deadline_s=0.5, max_retries=1,
            )
            svc.breaker.cooldown_s = 0.5
            items = _sig_items(3, 43000)
            futures = svc.verify_signatures(items)
            assert all(f.result(timeout=60) for f in futures)

            worker.suspend()  # the hang: socket alive, nothing answers
            futures = svc.verify_signatures(items)
            assert all(f.result(timeout=60) for f in futures), (
                "futures hung behind a SIGSTOPped worker"
            )

            worker.resume()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                futures = svc.verify_signatures(_sig_items(2, 44000))
                assert all(f.result(timeout=60) for f in futures)
                if svc.breaker.state == "closed":
                    break
                time.sleep(0.3)
            assert svc.breaker.state == "closed"
        finally:
            if svc is not None:
                svc.stop()
            worker.close()
            server.stop()
            broker.close()

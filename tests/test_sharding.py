"""Sharded node + partitioned uniqueness provider (docs/sharding.md).

Tier-1 coverage for PR 8, all in-process (MockNetwork / in-process
Broker — no real OS workers; the real-process path is exercised by
loadtest/real.py --node-workers and the shard_ab bench harness):

  * stable shard routing (txhash-prefix locality) + session routing
  * single-shard grouping, cross-shard two-phase commit, per-tx
    conflict attribution across shards (rejected exactly once)
  * prepare-expiry after coordinator death; journal recovery re-drives
    a decided commit and releases an undecided prepare
  * concurrent cross-shard commits over overlapping refs linearise
  * CoalescingUniquenessProvider shard-awareness (one round per shard)
  * MockNetwork `shards=` end-to-end + sharded-raft notary with a
    shard-leader kill
  * ShardRouter / EgressPump over an in-process Broker, with the eager
    queue registration the PR-3 gauges / PR-5 caps rely on
  * portable RPC session tokens (competing worker RPC servers)
"""
import hashlib
import threading
import time

import pytest

from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.node.database import NodeDatabase
from corda_tpu.node.notary import (
    CoalescingUniquenessProvider,
    Conflict,
    PersistentUniquenessProvider,
    UniquenessException,
    default_uniqueness_provider,
)
from corda_tpu.node.sharded_notary import (
    CoordinatorCrashError,
    ShardedUniquenessProvider,
    shard_of_key,
)
from corda_tpu.testing import faults


class _Party:
    name = "O=Test,L=London,C=GB"


PARTY = _Party()


def tx_id_of(tag: str) -> SecureHash:
    return SecureHash(hashlib.sha256(tag.encode()).digest())


def ref_on_shard(shard: int, n_shards: int, tag: str = "r",
                 index: int = 0) -> StateRef:
    """A StateRef routing to `shard` (brute-forced nonce)."""
    for nonce in range(100_000):
        h = hashlib.sha256(f"{tag}-{nonce}".encode()).digest()
        ref = StateRef(SecureHash(h), index)
        key = h + index.to_bytes(4, "big")
        if shard_of_key(key, n_shards) == shard:
            return ref
    raise AssertionError("no nonce found")


def make_provider(n_shards: int = 4, db=None, **kw):
    if db is not None:
        return ShardedUniquenessProvider.over_database(db, n_shards, **kw)
    return ShardedUniquenessProvider(
        [PersistentUniquenessProvider(NodeDatabase(":memory:"))
         for _ in range(n_shards)],
        **kw,
    )


class TestRouting:
    def test_stable_and_in_range(self):
        key = hashlib.sha256(b"k").digest() + (0).to_bytes(4, "big")
        assert shard_of_key(key, 4) == shard_of_key(key, 4)
        for n in (1, 2, 4, 7):
            assert 0 <= shard_of_key(key, n) < n

    def test_txhash_prefix_locality(self):
        """All outputs of one source tx co-locate (the common spend
        commits single-shard); conflict detection still holds because
        both spenders of a ref hash the same 32 bytes."""
        h = hashlib.sha256(b"src").digest()
        shards = {
            shard_of_key(h + i.to_bytes(4, "big"), 4) for i in range(16)
        }
        assert len(shards) == 1

    def test_shards_of_empty_is_shard0(self):
        p = make_provider(4)
        assert p.shards_of([]) == [0]

    def test_session_routing(self):
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.session import (
            SessionConfirm,
            SessionData,
            SessionInit,
        )
        from corda_tpu.node.shardhost import (
            route_session_payload,
            worker_tag_of,
        )

        assert worker_tag_of("w3-abc:2") == 3
        assert worker_tag_of("abc") is None
        # data routes by the recipient id's worker tag
        data = serialize(SessionData("w1-f:0", 0, b"x"))
        assert route_session_payload(data, 4) == 1
        # confirm routes by the initiator id's tag
        conf = serialize(SessionConfirm("w2-f:0", "peer:1"))
        assert route_session_payload(conf, 4) == 2
        # untagged ids (supervisor-started flows) fall to the supervisor
        assert route_session_payload(
            serialize(SessionData("plain:0", 0, b"x")), 4
        ) is None
        # init has no owner: stable hash, same worker on retransmit
        init = serialize(SessionInit("sess-1", "Flow", 1, b""))
        k = route_session_payload(init, 4)
        assert k is not None and route_session_payload(init, 4) == k
        # junk falls to the supervisor instead of raising
        assert route_session_payload(b"\xff\xfe junk", 4) is None


class TestShardedProvider:
    def test_single_shard_groups_one_round_per_shard(self):
        p = make_provider(4)
        reqs = []
        for shard in (0, 0, 1, 1, 1, 3):
            ref = ref_on_shard(shard, 4, tag=f"g{len(reqs)}")
            reqs.append(([ref], tx_id_of(f"tx{len(reqs)}"), PARTY))
        results = p.commit_many(reqs)
        assert results == [None] * 6
        assert p.single_commits == 6
        assert p.cross_commits == 0
        # one delegate round per touched shard, never one per request
        assert p.shard_rounds[0] == 1
        assert p.shard_rounds[1] == 1
        assert p.shard_rounds[3] == 1
        assert p.shard_rounds[2] == 0

    def test_cross_shard_commit_and_consumed(self):
        p = make_provider(4)
        a = ref_on_shard(0, 4, tag="xa")
        b = ref_on_shard(2, 4, tag="xb")
        p.commit([a, b], tx_id_of("cross"), PARTY)
        assert p.cross_commits == 1
        assert p.is_consumed(a) and p.is_consumed(b)
        # the journal drained: nothing left to recover
        assert p.journal.items() == []

    def test_double_spend_across_shards_rejected_once(self):
        """A double-spend whose two spends land on DIFFERENT shards is
        rejected exactly once, attributed to the committed tx."""
        p = make_provider(4)
        a = ref_on_shard(0, 4, tag="da")
        b = ref_on_shard(1, 4, tag="db")
        c = ref_on_shard(1, 4, tag="dc")
        p.commit([a, b], tx_id_of("winner"), PARTY)
        with pytest.raises(UniquenessException) as exc:
            p.commit([a, c], tx_id_of("loser"), PARTY)
        conflict = exc.value.conflict
        assert isinstance(conflict, Conflict)
        assert conflict.tx_id == tx_id_of("loser")
        # attribution names the spent ref and the consuming tx
        assert repr(a) in conflict.consumed
        assert conflict.consumed[repr(a)] == tx_id_of("winner")
        # the loser's OTHER input was never committed anywhere
        assert not p.is_consumed(c)
        # and retrying the loser reports the SAME verdict (no wedge)
        with pytest.raises(UniquenessException):
            p.commit([a, c], tx_id_of("loser"), PARTY)

    def test_batchmate_contention_one_winner(self):
        """Two cross-shard txs in ONE drained round contending for one
        ref: exactly one commits, the other gets a Conflict."""
        p = make_provider(4)
        shared = ref_on_shard(0, 4, tag="shared")
        b = ref_on_shard(1, 4, tag="mb")
        c = ref_on_shard(2, 4, tag="mc")
        results = p.commit_many([
            ([shared, b], tx_id_of("m1"), PARTY),
            ([shared, c], tx_id_of("m2"), PARTY),
        ])
        winners = [r for r in results if r is None]
        losers = [r for r in results if r is not None]
        assert len(winners) == 1 and len(losers) == 1
        assert repr(shared) in losers[0].consumed
        assert p.cross_commits == 1 and p.cross_aborts == 1

    def test_reservation_blocks_single_shard_spend(self):
        """A live cross-shard prepare holds its refs against competing
        single-shard spends (attributed to the reserving tx)."""
        clock = [1000.0]
        p = make_provider(4, clock=lambda: clock[0])
        a = ref_on_shard(0, 4, tag="ra")
        b = ref_on_shard(1, 4, tag="rb")
        with faults.inject(seed=1) as fi:
            fi.rule("sharded.finalise", "crash", match="s0", times=1)
            with pytest.raises(CoordinatorCrashError):
                p.commit([a, b], tx_id_of("crosser"), PARTY)
        # reservations survive the coordinator death; a single-shard
        # spend of a reserved ref loses, attributed to the reserver
        res = p.commit_many([([a], tx_id_of("single"), PARTY)])[0]
        assert res is not None
        assert res.consumed[repr(a)] == tx_id_of("crosser")
        assert p.reservation_conflicts >= 1

    def test_prepare_expiry_releases_after_coordinator_death(self):
        """Coordinator dies mid-prepare; its reservations release by
        EXPIRY — the competing spend succeeds once the TTL passes even
        with no recovery pass."""
        clock = [1000.0]
        p = make_provider(4, clock=lambda: clock[0], prepare_ttl_s=5.0)
        a = ref_on_shard(0, 4, tag="ea")
        b = ref_on_shard(3, 4, tag="eb")
        with faults.inject(seed=2) as fi:
            # crash AFTER shard 0 reserved, before shard 3
            fi.rule("sharded.prepare", "crash", match="s3", times=1)
            with pytest.raises(CoordinatorCrashError):
                p.commit([a, b], tx_id_of("dead"), PARTY)
        # inside the TTL the ref is held
        res = p.commit_many([([a], tx_id_of("early"), PARTY)])[0]
        assert res is not None
        clock[0] += 6.0  # past the TTL: the lock has died
        p.commit([a], tx_id_of("late"), PARTY)
        assert p.is_consumed(a)

    def test_recovery_redrives_decided_commit(self):
        """Crash AFTER the journal flipped to "committing": a restarted
        provider re-drives the finalise on every shard — the commit is
        decided, never rolled back."""
        db = NodeDatabase(":memory:")
        p = make_provider(4, db=db)
        a = ref_on_shard(0, 4, tag="ca")
        b = ref_on_shard(1, 4, tag="cb")
        with faults.inject(seed=3) as fi:
            fi.rule("sharded.finalise", "crash", match="s1", times=1)
            with pytest.raises(CoordinatorCrashError):
                p.commit([a, b], tx_id_of("decided"), PARTY)
        # shard 0 finalised, shard 1 did not: the ref set is torn until
        # recovery; a successor provider over the same db heals it
        p2 = ShardedUniquenessProvider.over_database(db, 4)
        assert p2.recovered_commits == 1
        assert p2.is_consumed(a) and p2.is_consumed(b)
        assert p2.journal.items() == []
        # the re-driven commit is idempotent: same tx commits clean
        p2.commit([a, b], tx_id_of("decided"), PARTY)
        # and a double-spend still loses with the right attribution
        with pytest.raises(UniquenessException) as exc:
            p2.commit([a], tx_id_of("thief"), PARTY)
        assert repr(a) in exc.value.conflict.consumed

    def test_recovery_releases_undecided_prepare(self):
        """Crash BEFORE every shard prepared: recovery aborts the round
        ONCE EXPIRED — the reservations release and the journal drains,
        so the refs are spendable again. Before the TTL passes the round
        is presumed to belong to a LIVE sibling coordinator (shared-db
        mode runs many workers): a takeover provider must leave it
        alone, or it would release reservations the owner is about to
        finalise against."""
        clock = [1000.0]
        db = NodeDatabase(":memory:")
        p = make_provider(4, db=db, clock=lambda: clock[0],
                          prepare_ttl_s=5.0)
        a = ref_on_shard(0, 4, tag="ua")
        b = ref_on_shard(2, 4, tag="ub")
        with faults.inject(seed=4) as fi:
            fi.rule("sharded.prepare", "crash", match="s2", times=1)
            with pytest.raises(CoordinatorCrashError):
                p.commit([a, b], tx_id_of("undecided"), PARTY)
        # inside the TTL: presumed live, untouched (reservations held)
        p_live = ShardedUniquenessProvider.over_database(
            db, 4, clock=lambda: clock[0]
        )
        assert p_live.recovered_aborts == 0
        assert len(p_live.journal.items()) == 1
        # past the TTL: genuinely dead — abort, release, drain
        clock[0] += 6.0
        p2 = ShardedUniquenessProvider.over_database(
            db, 4, clock=lambda: clock[0]
        )
        assert p2.recovered_aborts >= 1
        assert p2.journal.items() == []
        assert not p2.is_consumed(a) and not p2.is_consumed(b)
        p2.commit([a, b], tx_id_of("successor"), PARTY)  # no wedge

    def test_concurrent_overlapping_cross_commits_linearise(self):
        """N threads race cross-shard commits over overlapping refs:
        exactly one winner per contended ref, every loser gets a
        Conflict, nobody deadlocks."""
        p = make_provider(4)
        shared = ref_on_shard(1, 4, tag="hot")
        outcomes = {}
        lock = threading.Lock()

        def spend(i):
            other = ref_on_shard((i % 3) + 1 if (i % 3) + 1 != 1 else 3, 4,
                                 tag=f"t{i}")
            try:
                p.commit([shared, other], tx_id_of(f"racer{i}"), PARTY)
                with lock:
                    outcomes[i] = "won"
            except UniquenessException as exc:
                assert repr(shared) in exc.conflict.consumed
                with lock:
                    outcomes[i] = "lost"

        threads = [
            threading.Thread(target=spend, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "cross-shard commit deadlocked"
        assert sum(1 for v in outcomes.values() if v == "won") == 1
        assert sum(1 for v in outcomes.values() if v == "lost") == 7
        # no reservations left dangling after the storm
        assert p.reservations.holders(
            [PersistentUniquenessProvider._key(shared)], p.clock()
        ) == {}

    def test_issuance_empty_inputs_commits(self):
        p = make_provider(4)
        p.commit([], tx_id_of("issue"), PARTY)
        assert p.single_commits == 1


class TestDefaults:
    def test_unsharded_default_unchanged(self, monkeypatch):
        monkeypatch.delenv("CORDA_TPU_SHARDS", raising=False)
        p = default_uniqueness_provider(NodeDatabase(":memory:"))
        assert isinstance(p, PersistentUniquenessProvider)

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_SHARDS", "3")
        p = default_uniqueness_provider(NodeDatabase(":memory:"))
        assert isinstance(p, ShardedUniquenessProvider)
        assert p.n_shards == 3

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_SHARDS", "3")
        p = default_uniqueness_provider(NodeDatabase(":memory:"), shards=1)
        assert isinstance(p, PersistentUniquenessProvider)

    def test_file_backed_uses_per_shard_files(self, tmp_path):
        import os

        db = NodeDatabase(str(tmp_path / "node.db"))
        p = default_uniqueness_provider(db, shards=2)
        assert isinstance(p, ShardedUniquenessProvider)
        assert os.path.exists(str(tmp_path / "shards" / "shard0.db"))
        assert os.path.exists(str(tmp_path / "shards" / "shard1.db"))
        # cross-process-safe coordination state lives in the node db
        a = ref_on_shard(0, 2, tag="fa")
        b = ref_on_shard(1, 2, tag="fb")
        p.commit([a, b], tx_id_of("filecross"), PARTY)
        p2 = default_uniqueness_provider(db, shards=2)
        assert p2.is_consumed(a) and p2.is_consumed(b)


class TestCoalescingShardAwareness:
    class _SpyShardedDelegate:
        """A shard-routing delegate recording every commit_many round."""

        def __init__(self, n_shards=4):
            self.n_shards = n_shards
            self.rounds = []  # (thread name, n requests)

        def shard_of(self, ref):
            return shard_of_key(
                PersistentUniquenessProvider._key(ref), self.n_shards
            )

        def shards_of(self, states):
            return sorted({self.shard_of(r) for r in states}) or [0]

        def commit_many(self, requests):
            self.rounds.append(
                (threading.current_thread().name, len(requests))
            )
            return [None] * len(requests)

    def test_mixed_batch_groups_by_shard(self):
        """A mixed coalesced batch dispatches ONE commit_many PER SHARD
        GROUP (cross-shard requests form their own group), concurrently —
        never one round per request."""
        spy = self._SpyShardedDelegate(4)
        c = CoalescingUniquenessProvider(spy)
        reqs = []
        for shard in (0, 0, 1):
            ref = ref_on_shard(shard, 4, tag=f"cg{len(reqs)}")
            reqs.append(([ref], tx_id_of(f"ct{len(reqs)}"), PARTY))
        # one cross-shard request rides the same batch
        reqs.append((
            [ref_on_shard(2, 4, tag="cgx"), ref_on_shard(3, 4, tag="cgy")],
            tx_id_of("ctx"), PARTY,
        ))
        results = c._commit_many_by_shard(reqs)
        assert results == [None] * 4
        # 3 groups: shard 0 (2 reqs), shard 1 (1 req), cross (1 req)
        assert sorted(n for _, n in spy.rounds) == [1, 1, 2]
        # groups ran on dedicated threads (concurrent dispatch)
        assert all(
            name.startswith("uniq-shard-") for name, _ in spy.rounds
        )

    def test_single_group_skips_threads(self):
        spy = self._SpyShardedDelegate(4)
        c = CoalescingUniquenessProvider(spy)
        ref = ref_on_shard(1, 4, tag="sg")
        results = c._commit_many_by_shard(
            [([ref], tx_id_of("sg1"), PARTY)]
        )
        assert results == [None]
        # no thread fan-out for a single group
        assert spy.rounds[0][0] == threading.current_thread().name

    def test_coalesced_end_to_end_over_sharded(self):
        """The production stack: Coalescing over Sharded — concurrent
        commits from many threads all land, conflicts attributed."""
        p = make_provider(4)
        c = CoalescingUniquenessProvider(p)
        refs = [ref_on_shard(i % 4, 4, tag=f"e{i}") for i in range(12)]
        errs = []

        def commit(i):
            try:
                c.commit([refs[i]], tx_id_of(f"e{i}"), PARTY)
            except BaseException as exc:  # pragma: no cover
                errs.append(exc)

        threads = [
            threading.Thread(target=commit, args=(i,)) for i in range(12)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs
        assert all(p.is_consumed(r) for r in refs)


class TestMockNetworkSharded:
    def _pay_pairs(self, net, notary, bank, n):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.finance.flows import CashIssueFlow, CashPaymentFlow

        for i in range(n):
            h = bank.start_flow(CashIssueFlow(
                Amount(100, "USD"), bytes([i + 1]), bank.info, notary.info
            ))
            net.run_network()
            h.result.result(timeout=5)
            token = Issued(bank.info.ref(i + 1), "USD")
            h2 = bank.start_flow(CashPaymentFlow(
                Amount(100, token), bank.info, notary.info
            ))
            net.run_network()
            h2.result.result(timeout=5)

    def test_create_node_shards_end_to_end(self):
        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        notary = net.create_notary_node(shards=4)
        bank = net.create_node("O=SA,L=London,C=GB")
        provider = notary.notary_service.uniqueness_provider
        sharded = provider.delegate  # coalescing wraps the sharded one
        assert isinstance(sharded, ShardedUniquenessProvider)
        self._pay_pairs(net, notary, bank, 3)
        stats = sharded.stats()
        assert stats["single_commits"] + stats["cross_commits"] >= 3
        net.stop_nodes()

    def test_sharded_raft_notary_leader_kill(self):
        """One notary, 2 shards, one Raft consensus group each: kill a
        shard's LEADER mid-run — the quorum re-elects and commits
        resume; no double-spend is admitted through the window."""
        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        notary, provider, buses = net.create_sharded_notary_node(
            n_shards=2
        )
        bank = net.create_node("O=SR,L=London,C=GB")
        self._pay_pairs(net, notary, bank, 2)
        # kill shard 0's current leader
        victim = buses[0].elect()
        buses[0].kill(victim.node_id)
        # commits resume through the re-elected quorum
        self._pay_pairs(net, notary, bank, 2)
        new_leader = buses[0].elect()
        assert new_leader.node_id != victim.node_id
        # double-spend probe through the provider during the window:
        # spend an already-spent ref, expect exactly a Conflict
        a = ref_on_shard(0, 2, tag="lk")
        provider.commit([a], tx_id_of("first"), PARTY)
        with pytest.raises(UniquenessException):
            provider.commit([a], tx_id_of("second"), PARTY)
        net.stop_nodes()

    def test_disruption_catalog_entries(self):
        from corda_tpu.loadtest.disruption import (
            shard_leader_kill,
            worker_process_kill,
        )
        from corda_tpu.testing.mocknetwork import make_raft_commit_group

        provider, bus = make_raft_commit_group(3)
        d = shard_leader_kill([bus], probability=1.0)
        import random

        leader_before = bus.elect().node_id
        d.maybe_fire(random.Random(1), None, 0)
        assert leader_before in bus.dead
        # the group still serves (re-election inside elect())
        ref = ref_on_shard(0, 1, tag="dk")
        provider.commit([ref], tx_id_of("dk"), PARTY)
        d.maybe_heal(random.Random(1), None, 5)
        assert leader_before not in bus.dead
        # worker_process_kill is constructible against a supervisor-like
        # object (real-process wiring is exercised in the chaos runner)
        sup = type("S", (), {"workers": []})()
        worker_process_kill(sup, probability=1.0)


class TestShardHostRouting:
    def _broker(self):
        from corda_tpu.messaging import Broker

        return Broker()

    def test_eager_queue_registration(self):
        """Every shard-addressed queue exists — created, bounded — at
        supervisor construction, BEFORE any worker attaches: no
        unbounded window before the first consumer (PR-5 caps, PR-3
        depth gauges)."""
        from corda_tpu.node.shardhost import ShardSupervisor

        broker = self._broker()

        class _Health:
            def register(self, *a, **k):
                pass

        class _Metrics:
            def gauge(self, *a, **k):
                pass

        node = type("N", (), {
            "info": type("P", (), {"name": "O=Shard,L=L,C=GB"})(),
            "metrics": _Metrics(), "health": _Health(),
        })()
        sup = ShardSupervisor(broker, node, ".", 2, broker_port=0)
        for q in (
            "p2p.inbound.O=Shard,L=L,C=GB",
            "p2p.inbound.O=Shard,L=L,C=GB.w0",
            "p2p.inbound.O=Shard,L=L,C=GB.w1",
            "shardhost.control.w0",
            "shardhost.control.w1",
            "p2p.egress",
        ):
            assert broker.queue_exists(q), q
        # worker queues are bounded from birth (reject policy)
        max_depth, policy = broker.queue_bound(
            "p2p.inbound.O=Shard,L=L,C=GB.w0"
        )
        assert max_depth == 10_000 and policy == "reject"

    def test_router_routes_session_messages(self):
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.session import SESSION_TOPIC, SessionData
        from corda_tpu.node.shardhost import (
            ShardRouter,
            supervisor_queue,
            worker_queue,
        )

        broker = self._broker()
        name = "O=R,L=L,C=GB"
        broker.create_queue(f"p2p.inbound.{name}")
        broker.create_queue(worker_queue(name, 0))
        broker.create_queue(worker_queue(name, 1))
        broker.create_queue(supervisor_queue(name))
        router = ShardRouter(broker, name, 2).start()
        try:
            # worker-tagged session data -> that worker's leg
            broker.send(
                f"p2p.inbound.{name}",
                serialize(SessionData("w1-flow:0", 0, b"p")),
                {"topic": SESSION_TOPIC},
            )
            # non-session -> supervisor leg
            broker.send(
                f"p2p.inbound.{name}", b"raft-bytes", {"topic": "raft"}
            )
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and router.routed < 2:
                time.sleep(0.01)
            assert broker.message_count(worker_queue(name, 1)) == 1
            assert broker.message_count(supervisor_queue(name)) == 1
            assert broker.message_count(worker_queue(name, 0)) == 0
            assert router.to_supervisor == 1
        finally:
            router.stop()

    def test_egress_pump_delivers_by_dest(self):
        from corda_tpu.node.shardhost import EGRESS_QUEUE, EgressPump

        broker = self._broker()
        broker.create_queue("p2p.inbound.O=Peer,L=P,C=FR")
        pump = EgressPump(broker).start()
        try:
            broker.send(
                EGRESS_QUEUE, b"hello",
                {"topic": "t", "x-dest": "O=Peer,L=P,C=FR"},
            )
            broker.send(EGRESS_QUEUE, b"lost", {"topic": "t"})  # no dest
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and (
                pump.forwarded + pump.dropped
            ) < 2:
                time.sleep(0.01)
            assert pump.forwarded == 1
            assert pump.dropped == 1
            assert broker.message_count("p2p.inbound.O=Peer,L=P,C=FR") == 1
        finally:
            pump.stop()


class TestPortableRpcSessions:
    def test_token_verifies_on_sibling_server(self):
        """A login token minted by one worker's RPC server authenticates
        on a sibling sharing the session secret (competing consumers on
        one request queue) — and not on a server with a different
        secret."""
        from corda_tpu.messaging import Broker
        from corda_tpu.rpc.server import RPCServer, RPCUser

        users = [RPCUser("admin", "admin")]
        s1 = RPCServer(Broker(), object(), users=users,
                       session_secret=b"s" * 32)
        s2 = RPCServer(Broker(), object(), users=users,
                       session_secret=b"s" * 32)
        s3 = RPCServer(Broker(), object(), users=users,
                       session_secret=b"x" * 32)
        s4 = RPCServer(Broker(), object(), users=users)  # classic mode
        try:
            token = s1._make_token("admin")
            assert s2._session_user(token) is not None
            assert s2._session_user(token).username == "admin"
            assert s3._session_user(token) is None
            assert s4._session_user(token) is None
            # tampered token fails
            assert s2._session_user(token[:-2] + "ff") is None
            # unknown user fails even with a valid-shape token
            bad = s1._make_token("ghost")
            assert s2._session_user(bad) is None
        finally:
            for s in (s1, s2, s3, s4):
                s.stop()

    def test_secret_derivation_stable(self):
        from corda_tpu.node.shardhost import rpc_session_secret

        assert rpc_session_secret(42) == rpc_session_secret(42)
        assert rpc_session_secret(42) != rpc_session_secret(43)


class TestWorkerTagging:
    def test_flow_id_tag_prefixes_and_checkpoint_filter(self):
        from corda_tpu.core.flows.api import FlowLogic
        from corda_tpu.testing.mocknetwork import MockNetwork

        class _Noop(FlowLogic):
            def call(self):
                return 7

        net = MockNetwork()
        node = net.create_node("O=W,L=L,C=GB")
        node.smm.flow_id_tag = "w2"
        h = node.start_flow(_Noop())
        net.run_network()
        assert h.result.result(timeout=5) == 7
        assert h.flow_id.startswith("w2-")
        # checkpoint_filter partitions restore: a filter that excludes
        # everything restores nothing (no raise)
        node.smm.checkpoint_filter = lambda fid: False
        node.smm.start()
        net.stop_nodes()


class TestShardAbFixture:
    def test_work_slice_deterministic_and_shaped(self):
        from corda_tpu.loadtest.shard_ab import _work_slice

        a = _work_slice(0, 100, 2, cross_pct=10)
        b = _work_slice(0, 100, 2, cross_pct=10)
        assert [(tuple(map(repr, s)), t) for s, t in a] == \
               [(tuple(map(repr, s)), t) for s, t in b]
        # cross share: txs drawing from two source txhashes
        crossers = sum(
            1 for states, _ in a
            if len({r.txhash for r in states}) > 1
        )
        assert crossers == 10  # 10% of 100

class TestReviewHardening:
    """Regression pins for the PR-8 review findings (each test names the
    hole it closes)."""

    def test_prepare_probes_after_reserve(self):
        """The committed-log probe runs AFTER our reservation landed.
        Probe-first left a cross-process window: probe clean, a sibling
        worker reserves+commits+releases the same ref, our reserve then
        succeeds — and the conflict would surface only at finalise,
        after earlier shards finalised."""
        p = make_provider(4)
        a = ref_on_shard(0, 4, tag="pra")
        b = ref_on_shard(1, 4, tag="prb")
        seen = {}
        orig = p._probes[0]

        def probe(keys):
            seen["held"] = p.reservations.holders(list(keys), p.clock())
            return orig(keys)

        p._probes[0] = probe
        p.commit([a, b], tx_id_of("orderer"), PARTY)
        key_a = PersistentUniquenessProvider._key(a)
        assert seen["held"].get(key_a) == tx_id_of("orderer").bytes.hex()

    def test_token_with_dotted_username(self):
        """Session tokens rsplit from the right: a username containing
        dots ('ops.admin') still verifies on a sibling worker (nonce
        and mac are hex and never contain a dot; the username may)."""
        from corda_tpu.messaging import Broker
        from corda_tpu.rpc.server import RPCServer, RPCUser

        users = [RPCUser("ops.admin", "pw")]
        s1 = RPCServer(Broker(), object(), users=users,
                       session_secret=b"s" * 32)
        s2 = RPCServer(Broker(), object(), users=users,
                       session_secret=b"s" * 32)
        try:
            token = s1._make_token("ops.admin")
            user = s2._session_user(token)
            assert user is not None and user.username == "ops.admin"
        finally:
            s1.stop()
            s2.stop()

    def test_env_fingerprint_topology_override(self, monkeypatch):
        """bench.py enables sharding by PARAMETER, never the env var:
        the fingerprint must stamp what actually ran or every record
        reads as unsharded and the gate's different-topology guard
        never fires."""
        from corda_tpu.utils.quiesce import env_fingerprint

        monkeypatch.delenv("CORDA_TPU_SHARDS", raising=False)
        monkeypatch.delenv("CORDA_TPU_NODE_WORKERS", raising=False)
        fp = env_fingerprint()
        assert fp["shards"] == 0 and fp["node_workers"] == 0
        fp = env_fingerprint(shards=4, node_workers=2)
        assert fp["shards"] == 4 and fp["node_workers"] == 2
        monkeypatch.setenv("CORDA_TPU_SHARDS", "8")
        assert env_fingerprint()["shards"] == 8
        assert env_fingerprint(shards=4)["shards"] == 4

    def test_soft_lock_reserve_reentrant_widening(self):
        """Re-reserving a ref already held under the SAME lock_id is a
        success; a FAILED widening rolls back only what that call
        acquired — the original holdings stay locked (two worker
        processes share the vault table; the coin-selection retry loop
        re-reserves under one lock_id)."""
        from corda_tpu.node.services import (
            StatesNotAvailableError,
            VaultService,
        )

        db = NodeDatabase(":memory:")
        vault = VaultService(db, is_relevant=lambda *a: True)
        refs = []
        for i in range(2):
            txid = tx_id_of(f"vault{i}")
            db.execute(
                "INSERT INTO vault_states(tx_id, output_index, state_blob,"
                " contract_name, consumed) VALUES (?, 0, ?, 'C', 0)",
                (txid.bytes, b"s"),
            )
            refs.append(StateRef(txid, 0))
        a, b = refs
        vault.soft_lock_reserve("L1", [a])
        vault.soft_lock_reserve("L1", [a])  # re-entrant: no raise
        vault.soft_lock_reserve("L2", [b])
        with pytest.raises(StatesNotAvailableError):
            vault.soft_lock_reserve("L1", [a, b])  # b is L2's
        rows = db.query(
            "SELECT lock_id FROM vault_states WHERE tx_id=?",
            (a.txhash.bytes,),
        )
        assert rows[0][0] == "L1"  # failed widening kept the original

    def test_egress_pump_blocks_until_dest_drains(self):
        """A bounded destination queue that is FULL blocks the pump (a
        session message dropped here has no retransmit — the flow would
        hang to timeout); the blocked send lands once the queue drains,
        and nothing is counted dropped."""
        from corda_tpu.messaging import Broker
        from corda_tpu.node.shardhost import EGRESS_QUEUE, EgressPump

        broker = Broker()
        dest = "O=Full,L=P,C=FR"
        broker.create_queue(f"p2p.inbound.{dest}", max_depth=1)
        broker.send(f"p2p.inbound.{dest}", b"occupier", {})
        pump = EgressPump(broker).start()
        try:
            broker.send(
                EGRESS_QUEUE, b"payload", {"topic": "t", "x-dest": dest}
            )
            time.sleep(0.3)
            assert pump.dropped == 0 and pump.forwarded == 0
            consumer = broker.create_consumer(f"p2p.inbound.{dest}")
            msg = consumer.receive(timeout=1)
            consumer.ack(msg)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and pump.forwarded < 1:
                time.sleep(0.01)
            assert pump.forwarded == 1 and pump.dropped == 0
            assert broker.message_count(f"p2p.inbound.{dest}") == 1
        finally:
            pump.stop()


class TestPerShardReservations:
    """The r13 perf fix: reservation lock tables live in each shard's
    OWN database, the hot path never writes the coordination db, and
    blocked writers poll instead of sleeping through sqlite's backoff
    (docs/sharding.md §storage-modes)."""

    def _dir_provider(self, tmp_path, n_shards=2):
        from corda_tpu.node.sharded_notary import ShardedUniquenessProvider

        coord = NodeDatabase(str(tmp_path / "coord.db"))
        p = ShardedUniquenessProvider.over_directory(
            coord, str(tmp_path / "shards"), n_shards
        )
        return p, coord

    def test_reservations_live_in_shard_db(self, tmp_path):
        p, coord = self._dir_provider(tmp_path)
        try:
            a = ref_on_shard(0, 2, tag="psa")
            b = ref_on_shard(1, 2, tag="psb")
            # a cross-shard prepare reserves on both shards
            lost = p._stores[0].reserve_many(
                {"aa" * 32: [PersistentUniquenessProvider._key(a)]},
                p.clock() + 30, p.clock(),
            )
            assert lost == {}
            rows = p.delegates[0]._db.query(
                "SELECT COUNT(*) FROM shard_reservations"
            )
            assert rows[0][0] == 1
            # ...and the coordination db holds NO reservation table rows
            coord_rows = coord.query(
                "SELECT name FROM sqlite_master WHERE name='shard_reservations'"
            )
            if coord_rows:
                assert coord.query(
                    "SELECT COUNT(*) FROM shard_reservations"
                )[0][0] == 0
            # shard 1's file is untouched by shard 0's reservation
            assert p.delegates[1]._db.query(
                "SELECT COUNT(*) FROM shard_reservations"
            )[0][0] == 0
            assert b is not None
        finally:
            p.close()

    def test_hot_path_never_writes_coordination_db(self, tmp_path):
        p, coord = self._dir_provider(tmp_path)
        try:
            refs = [ref_on_shard(0, 2, tag=f"hp{i}") for i in range(6)]
            for i, r in enumerate(refs):
                p.commit([r], tx_id_of(f"hp{i}"), PARTY)
            assert p.single_commits == 6
            # single-shard rounds leave zero rows anywhere in coord:
            # no journal record, no reservations
            names = {
                r[0] for r in coord.query(
                    "SELECT name FROM sqlite_master WHERE type='table'"
                )
            }
            for t in names:
                assert coord.query(f"SELECT COUNT(*) FROM {t}")[0][0] == 0, t
        finally:
            p.close()

    def test_sibling_instance_reservation_blocks_commit(self, tmp_path):
        """Two provider INSTANCES over the same directory (the OS-worker
        shape, minus fork): a reservation taken through instance A's
        shard file screens instance B's fused commit round — the
        arbitration lives in sqlite, not in-process state."""
        p1, _ = self._dir_provider(tmp_path)
        from corda_tpu.node.sharded_notary import ShardedUniquenessProvider

        p2 = ShardedUniquenessProvider.over_directory(
            NodeDatabase(str(tmp_path / "coord.db")),
            str(tmp_path / "shards"), 2,
        )
        try:
            shared = ref_on_shard(0, 2, tag="sib")
            holder = tx_id_of("holder")
            lost = p1._stores[0].reserve_many(
                {holder.bytes.hex():
                 [PersistentUniquenessProvider._key(shared)]},
                p1.clock() + 30, p1.clock(),
            )
            assert lost == {}
            with pytest.raises(UniquenessException) as exc:
                p2.commit([shared], tx_id_of("rival"), PARTY)
            assert repr(shared) in exc.value.conflict.consumed
            assert exc.value.conflict.consumed[repr(shared)] == holder
        finally:
            p1.close()
            p2.close()

    def test_shard_db_pragmas(self, tmp_path):
        p, _ = self._dir_provider(tmp_path)
        try:
            for d in p.delegates:
                assert d._db.query("PRAGMA busy_timeout")[0][0] == 5
                assert d._db.query("PRAGMA wal_autocheckpoint")[0][0] == 0
        finally:
            p.close()

    def test_retry_locked_polls_through_busy(self, tmp_path):
        import sqlite3 as sq

        p, _ = self._dir_provider(tmp_path)
        try:
            attempts = []

            def flaky():
                attempts.append(1)
                if len(attempts) < 3:
                    raise sq.OperationalError("database is locked")
                return "done"

            assert p._retry_locked(flaky) == "done"
            assert len(attempts) == 3
            # non-lock errors propagate untouched
            def broken():
                raise sq.OperationalError("no such table: nope")

            with pytest.raises(sq.OperationalError):
                p._retry_locked(broken)
        finally:
            p.close()

    def test_checkpoint_shards_and_close(self, tmp_path):
        p, _ = self._dir_provider(tmp_path)
        try:
            r = ref_on_shard(0, 2, tag="ck")
            p.commit([r], tx_id_of("ck"), PARTY)
            p.checkpoint_shards()  # PASSIVE sweep runs clean under load
            assert p.is_consumed(r)
        finally:
            p.close()
        assert p._sweep_stop.is_set()


class TestReviewHardening2:
    """Regression pins for the second review pass."""

    def test_logout_revokes_portable_token(self):
        """A logged-out HMAC token must stay dead on the worker that
        served the logout — stateless re-verification used to resurrect
        (and re-cache) it."""
        from corda_tpu.messaging import Broker
        from corda_tpu.rpc.server import RPCServer, RPCUser

        s = RPCServer(Broker(), object(), users=[RPCUser("ops", "pw")],
                      session_secret=b"s" * 32)
        try:
            token = s._make_token("ops")
            assert s._session_user(token) is not None
            s._handle({"kind": "logout", "session": token,
                       "id": "x", "reply_to": None})
            assert s._session_user(token) is None
        finally:
            s.stop()

    def test_fingerprint_topology_mismatch_vs_pre_shard_baseline(self):
        """A pre-r13 fingerprint (no 'shards' key) vs a shards=4 reading
        is a topology mismatch (gate warns instead of hard-comparing);
        identical topologies still compare clean."""
        from corda_tpu.utils.quiesce import fingerprint_mismatch

        old = {"backend": "cpu", "python": "3.10"}
        new = dict(old, shards=4, node_workers=0)
        keys = {m["key"] for m in fingerprint_mismatch(old, new)}
        assert keys == {"shards"}
        assert fingerprint_mismatch(new, dict(new)) == []

    def test_skewed_drain_respects_max_batch_per_round(self):
        """One hot shard must not inflate a delegate round past
        max_batch: a drained batch of 3x max_batch same-shard requests
        commits in >= 3 delegate rounds."""
        from corda_tpu.node.notary import CoalescingUniquenessProvider

        p = make_provider(4)
        seen = []
        orig = p.commit_many

        def spy(reqs):
            seen.append(len(reqs))
            return orig(reqs)

        p.commit_many = spy
        c = CoalescingUniquenessProvider(p, max_batch=4)
        reqs = [([ref_on_shard(1, 4, tag=f"sk{i}")], tx_id_of(f"sk{i}"),
                 PARTY) for i in range(12)]
        assert c._commit_many_by_shard(reqs) == [None] * 12
        assert max(seen) <= 4 and len(seen) >= 3


class TestReviewHardening3:
    """Regression pins for the third review pass: the two-phase decision
    point must survive (or detect) prepare-TTL expiry, recovery must
    surface a conflicted re-drive, the "committing" flip must be as
    durable as the commits it orders, and a CAS-miss soft-lock
    diagnostic must not fail a flow over a racing sibling release."""

    def test_expired_prepare_aborts_at_decision_point(self):
        """Prepares that eat the whole TTL: a sibling purges the locks
        and commits a competitor — the decision point must detect the
        lost reservation and abort with the competitor's attribution,
        never finalise a torn commit."""
        clock = [1000.0]
        p = make_provider(4, clock=lambda: clock[0], prepare_ttl_s=5.0)
        a = ref_on_shard(0, 4, tag="xa")
        b = ref_on_shard(1, 4, tag="xb")
        victim, competitor = tx_id_of("slow-crosser"), tx_id_of("sibling")
        orig = p._prepare_shard_batch

        def slow_prepare(shard, todo, expires):
            out = orig(shard, todo, expires)
            if shard == 1:  # last shard prepared; TTL now expires
                clock[0] += 6.0
                p._stores[0].purge_expired(clock[0])
                assert p.delegates[0].commit_many(
                    [([a], competitor, PARTY)]
                ) == [None]
            return out

        p._prepare_shard_batch = slow_prepare
        res = p.commit_many([([a, b], victim, PARTY)])[0]
        assert res is not None
        assert res.consumed[repr(a)] == competitor
        # nothing torn: b stays free, the journal drained
        assert not p.is_consumed(b)
        assert p.journal.items() == []
        p.commit([b], tx_id_of("later"), PARTY)

    def test_slow_finalise_keeps_locks_alive(self):
        """Past the decision point the survivors' locks are extended:
        a sibling purge + competing spend mid-finalise must lose, and
        the cross-shard commit completes untorn."""
        clock = [1000.0]
        p = make_provider(4, clock=lambda: clock[0], prepare_ttl_s=5.0)
        a = ref_on_shard(0, 4, tag="fa")
        b = ref_on_shard(1, 4, tag="fb")
        crosser = tx_id_of("crosser")
        orig = p._finalise_shard_batch
        stolen = []

        def slow_finalise(shard, items):
            if shard == 0 and not stolen:
                stolen.append(True)
                clock[0] += 6.0  # past the PREPARE-phase expiry
                p.reservations.purge_expired(clock[0])
                r = p.commit_many([([b], tx_id_of("thief"), PARTY)])[0]
                assert r is not None
                assert r.consumed[repr(b)] == crosser
            return orig(shard, items)

        p._finalise_shard_batch = slow_finalise
        assert p.commit_many([([a, b], crosser, PARTY)]) == [None]
        assert p.is_consumed(a) and p.is_consumed(b)

    def test_recover_surfaces_conflicted_redrive(self):
        """A "committing" round whose refs a competitor consumed during
        the outage window: recovery must count it `conflicted`, not
        paper it over as a recovered commit."""
        db = NodeDatabase(":memory:")
        p = make_provider(4, db=db)
        a = ref_on_shard(0, 4, tag="rc")
        victim = tx_id_of("victim")
        key = a.txhash.bytes + (0).to_bytes(4, "big")
        t = {
            "tx_hex": victim.bytes.hex(), "tx_id": victim, "party": PARTY,
            "keys_by_shard": {0: [key]}, "ref_of_key": {key: a},
            "shards": [0],
        }
        p.journal.put(t["tx_hex"], p._journal_record(
            "committing", [0], [t], p.clock() + 30
        ))
        assert p.delegates[0].commit_many(
            [([a], tx_id_of("competitor"), PARTY)]
        ) == [None]
        rep = p.recover()
        assert rep["conflicted"] == 1 and rep["committed"] == 0
        assert p.recovered_commits == 0
        assert p.journal.items() == []

    def test_committing_flip_raises_durability(self):
        """On a synchronous=NORMAL coordination db the "committing" put
        (and only it) brackets itself in PRAGMA synchronous=FULL."""
        from corda_tpu.node.sharded_notary import PrepareJournal

        db = NodeDatabase(":memory:")
        pragmas = []
        orig = db.execute

        def spy(sql, params=()):
            if isinstance(sql, str) and sql.startswith(
                "PRAGMA synchronous="
            ):
                pragmas.append(sql)
            return orig(sql, params)

        db.execute = spy
        j = PrepareJournal(db)
        j.put("aa", {"phase": "prepare", "txs": {}})
        assert pragmas == []
        j.put("aa", {"phase": "committing", "txs": {}})
        assert pragmas == [
            "PRAGMA synchronous=FULL", "PRAGMA synchronous=1",
        ]

    def test_soft_lock_cas_miss_retries_when_free(self):
        """CAS misses, the diagnostic re-read finds the state FREE (the
        holder — a sibling worker process — released between the two
        statements): the reserve must retry the CAS and win, not raise
        a spurious "locked by None"."""
        from corda_tpu.node.services import VaultService

        db = NodeDatabase(":memory:")
        v = VaultService(db, is_relevant=lambda *a: True)
        db.execute(
            "INSERT INTO vault_states "
            "(tx_id, output_index, state_blob, contract_name) "
            "VALUES (?, ?, ?, ?)", (b"t" * 32, 0, b"x", "C"),
        )
        ref = StateRef(SecureHash(b"t" * 32), 0)
        v.soft_lock_reserve("other", [ref])
        orig_q = db.query

        def q(sql, params=()):
            if "SELECT lock_id" in sql:
                db.query = orig_q  # interpose exactly once
                v.soft_lock_release("other")
            return orig_q(sql, params)

        db.query = q
        v.soft_lock_reserve("mine", [ref])
        rows = orig_q(
            "SELECT lock_id FROM vault_states WHERE tx_id = ?",
            (b"t" * 32,),
        )
        assert rows[0][0] == "mine"


class TestReviewHardening4:
    """Regression pins for the fourth review pass: the router must
    dispatch on the sender-stamped route-hint header without codec-
    decoding payloads on its one thread, worker messaging must carry
    the hint through egress, and /workers probes run concurrently."""

    def test_route_hint_agrees_with_payload_routing(self):
        """Every hint the senders emit must land on the SAME worker as
        payload decode (a retransmit can arrive once with and once
        without the header; session dedup needs both on one worker)."""
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.session import (
            SessionConfirm,
            SessionData,
            SessionEnd,
            SessionInit,
            SessionReject,
            route_hint,
        )
        from corda_tpu.node.shardhost import (
            route_session_hint,
            route_session_payload,
        )

        msgs = [
            SessionInit("sess-1", "Flow", 1, b""),
            SessionData("w1-f:0", 0, b"x"),
            SessionEnd("w2-f:0", None),
            SessionConfirm("w3-f:0", "peer:1"),
            SessionReject("plain:0", "no"),
        ]
        for m in msgs:
            hint = route_hint(m)
            assert hint is not None
            assert route_session_hint(hint, 4) == route_session_payload(
                serialize(m), 4
            ), type(m).__name__

    def test_route_hint_malformed_falls_back(self):
        from corda_tpu.node.shardhost import _NO_HINT, route_session_hint

        for bad in (None, "", "x", "t:", "z:w1-f:0", "th", "h:"):
            assert route_session_hint(bad, 4) is _NO_HINT, bad
        # well-formed tag hint for an untagged id: supervisor, no decode
        assert route_session_hint("t:plain:0", 4) is None
        # tag beyond the worker count: supervisor
        assert route_session_hint("t:w9-f:0", 4) is None

    def test_router_routes_on_hint_without_decoding(self):
        """Junk payloads (undecodable — payload routing would fall to
        the supervisor) route to the hinted worker on headers alone."""
        from corda_tpu.messaging import Broker
        from corda_tpu.node.session import ROUTE_HINT_HEADER, SESSION_TOPIC
        from corda_tpu.node.shardhost import (
            ShardRouter,
            route_session_hint,
            supervisor_queue,
            worker_queue,
        )

        broker = Broker()
        name = "O=Hint,L=L,C=GB"
        broker.create_queue(f"p2p.inbound.{name}")
        broker.create_queue(worker_queue(name, 0))
        broker.create_queue(worker_queue(name, 1))
        broker.create_queue(supervisor_queue(name))
        router = ShardRouter(broker, name, 2).start()
        try:
            broker.send(
                f"p2p.inbound.{name}", b"\xff\xfe junk",
                {"topic": SESSION_TOPIC, ROUTE_HINT_HEADER: "t:w1-f:0"},
            )
            hashed = route_session_hint("h:sess-9", 2)
            broker.send(
                f"p2p.inbound.{name}", b"\xff junk2",
                {"topic": SESSION_TOPIC, ROUTE_HINT_HEADER: "h:sess-9"},
            )
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and router.routed < 2:
                time.sleep(0.01)
            counts = {
                k: broker.message_count(worker_queue(name, k))
                for k in (0, 1)
            }
            expected = {0: 0, 1: 1}
            expected[hashed] += 1
            assert counts == expected
            assert broker.message_count(supervisor_queue(name)) == 0
        finally:
            router.stop()

    def test_worker_messaging_send_carries_route_hint(self):
        """A worker flow's session send (statemachine passes headers=)
        must not TypeError, and the hint must ride the egress envelope
        so the PEER's router keeps its fast path."""
        from corda_tpu.messaging import Broker
        from corda_tpu.node.session import ROUTE_HINT_HEADER
        from corda_tpu.node.shardhost import (
            EGRESS_QUEUE,
            make_worker_messaging,
        )

        broker = Broker()
        broker.create_queue(EGRESS_QUEUE)
        key = type("K", (), {"encoded": b"\x01\x02"})()
        me = type("P", (), {"name": "O=W,L=L,C=GB", "owning_key": key})()
        peer = type("P", (), {"name": "O=Peer,L=L,C=GB"})()
        svc = make_worker_messaging(broker, me, worker_index=1)
        svc.send(peer, "p2p.session", b"payload",
                 headers={ROUTE_HINT_HEADER: "t:w1-f:0"})
        consumer = broker.create_consumer(EGRESS_QUEUE)
        msg = consumer.receive(timeout=2)
        assert msg is not None
        assert msg.headers["x-dest"] == "O=Peer,L=L,C=GB"
        assert msg.headers[ROUTE_HINT_HEADER] == "t:w1-f:0"

    def test_workers_probe_concurrently(self):
        """/workers with M wedged workers costs ~ONE probe timeout, not
        M sequential ones."""
        from corda_tpu.messaging import Broker
        from corda_tpu.node.shardhost import ShardSupervisor

        class _Health:
            def register(self, *a, **k):
                pass

        class _Metrics:
            def gauge(self, *a, **k):
                pass

        node = type("N", (), {
            "info": type("P", (), {"name": "O=Probe,L=L,C=GB"})(),
            "metrics": _Metrics(), "health": _Health(),
        })()
        sup = ShardSupervisor(Broker(), node, ".", 4, broker_port=0)

        class _Proc:
            pid = 4242

            def poll(self):
                return None

        for w in sup.workers:
            w.proc = _Proc()
        sup._worker_ops_port = lambda i: 1

        def slow_fetch(port, path):
            time.sleep(0.5)
            return {"status": "ok"}

        sup._fetch_json = slow_fetch
        t0 = time.monotonic()
        snap = sup.snapshot()
        elapsed = time.monotonic() - t0
        assert all(
            e["healthz"] == "ok" for e in snap["detail"].values()
        )
        assert elapsed < 1.5, elapsed  # sequential would be >= 2.0s

    def test_mem_reservation_store_thread_safe(self):
        """Concurrent reserve_many (drain threads) vs release_tx/
        purge_expired (abort/recovery) on the in-memory store: no
        'dictionary changed size during iteration'."""
        from corda_tpu.node.sharded_notary import ReservationStore

        rs = ReservationStore()
        stop = threading.Event()
        errors = []

        def churn_reserve():
            i = 0
            while not stop.is_set():
                try:
                    rs.reserve_many(
                        {f"tx{i % 7}": [f"k{i % 97}".encode()]}, 10.0, 0.0
                    )
                except Exception as exc:
                    errors.append(exc)
                    stop.set()
                i += 1

        def churn_release():
            i = 0
            while not stop.is_set():
                try:
                    rs.release_tx(f"tx{i % 7}")
                    rs.purge_expired(0.0)
                    rs.holders([f"k{i % 97}".encode()], 0.0)
                except Exception as exc:
                    errors.append(exc)
                    stop.set()
                i += 1

        threads = [
            threading.Thread(target=churn_reserve),
            threading.Thread(target=churn_reserve),
            threading.Thread(target=churn_release),
        ]
        for t in threads:
            t.start()
        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors, errors

    def test_router_stop_mid_backpressure_loses_nothing(self):
        """stop() during the QueueFullError wait must NOT ack the
        unforwarded batch: consumer close requeues it, so every message
        survives on some queue (at-least-once, never silently consumed)."""
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.messaging import Broker
        from corda_tpu.node.session import SESSION_TOPIC, SessionData
        from corda_tpu.node.shardhost import (
            ShardRouter,
            supervisor_queue,
            worker_queue,
        )

        broker = Broker()
        name = "O=Stop,L=L,C=GB"
        broker.create_queue(f"p2p.inbound.{name}")
        broker.create_queue(worker_queue(name, 0))
        broker.create_queue(supervisor_queue(name))
        # worker queue full at depth 1: the router's fallback loop blocks
        broker.set_queue_bound(worker_queue(name, 0), 1, "reject")
        broker.send(worker_queue(name, 0), b"filler", {})
        n = 4
        for i in range(n):
            broker.send(
                f"p2p.inbound.{name}",
                serialize(SessionData("w0-f:0", i, b"p")),
                {"topic": SESSION_TOPIC},
            )
        router = ShardRouter(broker, name, 1).start()
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and broker.message_count(
                f"p2p.inbound.{name}"
            ) >= n:
                time.sleep(0.01)  # wait for the router to pick the batch up
        finally:
            router.stop()
        remaining = (
            broker.message_count(f"p2p.inbound.{name}")
            + broker.message_count(worker_queue(name, 0))
            - 1  # the filler
        )
        assert remaining == n, remaining
        assert router.routed == 0  # nothing was acked as routed

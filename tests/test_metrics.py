"""Metric registry + export tests (reference `MonitoringService.kt`,
`StateMachineManager.kt:127-133` metric names, JMX export `Node.kt:305-310`
replaced by RPC/webserver JSON snapshots)."""
import json
import time
import urllib.request

from corda_tpu.core.flows import FlowLogic, startable_by_rpc
from corda_tpu.rpc import CordaRPCOps
from corda_tpu.testing import MockNetwork
from corda_tpu.utils.metrics import MetricRegistry, Timer
from corda_tpu.webserver import WebServer


class TestRegistry:
    def test_counter(self):
        reg = MetricRegistry()
        c = reg.counter("x")
        c.inc()
        c.inc(4)
        c.dec()
        assert reg.counter("x").value == 4
        assert reg.snapshot()["x"] == {"type": "counter", "count": 4}

    def test_meter_counts_and_rates(self):
        reg = MetricRegistry()
        m = reg.meter("events")
        for _ in range(10):
            m.mark()
        snap = m.snapshot()
        assert snap["count"] == 10
        assert snap["mean_rate"] > 0

    def test_timer_percentiles_bounded(self):
        t = Timer()
        for i in range(Timer.RESERVOIR + 500):
            t.update(i / 1000.0)
        snap = t.snapshot()
        assert snap["count"] == Timer.RESERVOIR + 500
        assert len(t._durations) == Timer.RESERVOIR  # bounded reservoir
        assert snap["min"] <= snap["p50"] <= snap["p95"] <= snap["max"]

    def test_timer_context_manager(self):
        t = Timer()
        with t.time():
            time.sleep(0.01)
        assert t.count == 1
        assert t.snapshot()["max"] >= 0.005

    def test_gauge(self):
        reg = MetricRegistry()
        box = {"v": 7}
        reg.gauge("g", lambda: box["v"])
        assert reg.gauge("g").value == 7
        box["v"] = 9
        assert reg.snapshot()["g"]["value"] == 9

    def test_type_conflict_rejected(self):
        reg = MetricRegistry()
        reg.counter("dup")
        try:
            reg.meter("dup")
        except TypeError:
            pass
        else:
            raise AssertionError("expected TypeError")

    def test_snapshot_json_serializable(self):
        reg = MetricRegistry()
        reg.counter("c").inc()
        reg.meter("m").mark()
        reg.timer("t").update(0.5)
        reg.gauge("g", lambda: 1.0)
        json.dumps(reg.snapshot())

    def test_gauge_reregistration_replaces_callable(self):
        # A recreated service re-registering its gauge must not leave the
        # snapshot reading the stale (dead) closure.
        reg = MetricRegistry()
        g1 = reg.gauge("g", lambda: 1)
        g2 = reg.gauge("g", lambda: 2)
        assert g1 is g2  # same metric object, rebound callable
        assert reg.gauge("g").value == 2
        assert reg.snapshot()["g"]["value"] == 2

    def test_gauge_name_collision_with_other_type_raises(self):
        import pytest

        reg = MetricRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x", lambda: 1)
        reg.gauge("g", lambda: 1)
        with pytest.raises(TypeError):
            reg.counter("g")

    def test_timer_meter_collision_semantics(self):
        # Same-type re-access returns the SAME instance; cross-type is a
        # consistent TypeError in both directions.
        import pytest

        reg = MetricRegistry()
        t = reg.timer("dur")
        assert reg.timer("dur") is t
        m = reg.meter("rate")
        assert reg.meter("rate") is m
        with pytest.raises(TypeError):
            reg.meter("dur")
        with pytest.raises(TypeError):
            reg.timer("rate")


@startable_by_rpc
class _NapFlow(FlowLogic):
    def call(self):
        return 42
        yield  # pragma: no cover


class TestNodeMetrics:
    def setup_method(self):
        self.net = MockNetwork()
        self.node = self.net.create_node("O=Metrics,L=London,C=GB")
        self.ops = CordaRPCOps(self.node.services, self.node.smm)

    def teardown_method(self):
        self.net.stop_nodes()

    def test_flow_metrics_marked(self):
        handle = self.node.start_flow(_NapFlow())
        self.net.run_network()
        assert handle.result.result(timeout=5) == 42
        snap = self.ops.node_metrics()
        assert snap["Flows.Started"]["count"] == 1
        assert snap["Flows.Finished"]["count"] == 1
        assert snap["Flows.InFlight"]["value"] == 0

    def test_checkpointing_rate_metered(self):
        # Checkpoints are written at suspension points; a flow with none
        # still writes its initial pre-start state only when it suspends,
        # so use the registry directly for the marked-by-SMM invariant.
        m = self.node.smm.metrics.meter("Flows.CheckpointingRate")
        before = m.count
        assert before == self.node.smm.checkpoints_written

    def test_webserver_metrics_endpoint(self):
        web = WebServer(self.ops, port=0)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{web.port}/api/metrics", timeout=5
            ) as resp:
                body = json.loads(resp.read())
            assert "Flows.InFlight" in body
        finally:
            web.stop()


class TestKillFlow:
    def setup_method(self):
        self.net = MockNetwork()
        self.node = self.net.create_node("O=Killer,L=London,C=GB")
        self.ops = CordaRPCOps(self.node.services, self.node.smm)

    def teardown_method(self):
        self.net.stop_nodes()

    def test_kill_unknown_is_false(self):
        assert self.ops.kill_flow("nope") is False

    def test_kill_live_flow(self):
        from corda_tpu.core.flows.api import (
            FlowKilledException,
            initiating_flow,
        )

        @initiating_flow
        class StuckFlow(FlowLogic):
            def __init__(self, peer):
                self.peer = peer

            def call(self):
                yield self.receive(self.peer)

        peer = self.net.create_node("O=Peer,L=Paris,C=GB")
        self.node.register_peer(peer.info)
        # Don't pump the network: the peer would reject the unknown session;
        # unpumped, the flow stays suspended in Receive.
        handle = self.node.start_flow(StuckFlow(peer.info), peer.info)
        fsm = self.node.smm.flows[handle.flow_id]
        assert not fsm.done
        assert self.ops.kill_flow(handle.flow_id) is True
        assert fsm.done
        try:
            handle.result.result(timeout=1)
        except FlowKilledException as exc:
            # a kill is distinguishable from an ordinary flow failure
            assert "killed" in str(exc)
        else:
            raise AssertionError("expected FlowKilledException")
        # checkpoint dropped: nothing to restore
        assert self.ops.kill_flow(handle.flow_id) is False

"""Test configuration: force the JAX CPU backend with a virtual 8-device mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective tests
run against 8 virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

The axon sitecustomize imports jax at interpreter startup and latches
JAX_PLATFORMS to "axon,cpu", so env vars alone cannot move the suite off the
real TPU tunnel: we must call jax.config.update after import. XLA_FLAGS still
takes effect as long as it is set before the first CPU backend initialization.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the kernel tests are dominated by XLA CPU
# compiles (round-1 suite wall time 18:47); cache them across runs.
_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_ed25519_rule_pin():
    """The ed25519 acceptance rule is pinned per PROCESS in production;
    tests flip DISPATCH/mesh config per test, so each test gets a fresh
    pin (tests asserting the pin's behavior set it explicitly)."""
    from corda_tpu.core.crypto import batch as crypto_batch

    crypto_batch._pinned_rule = None
    yield
    crypto_batch._pinned_rule = None


# The nightly tier (r3 VERDICT #9): these files dominate suite wall time
# on the 1-core CI box (the kernel differential ladders are XLA-compile
# bound; the real-process suites boot cordform networks of OS processes).
# Fast coverage of the same behavior runs by default: field/row unit tests
# for the kernels, the in-process MockNetwork suites for the node.
_HEAVY_FILES = frozenset({
    "test_ops_ed25519.py",
    "test_ops_ecdsa.py",
    "test_real_disruption.py",
    "test_process.py",
    "test_capsule_install.py",
})


def pytest_addoption(parser):
    parser.addoption(
        "--heavy-compile",
        action="store_true",
        default=False,
        help="run the XLA-compile-dominated kernel differential tests "
        "(several minutes each on the CPU backend, even warm — the cost "
        "is tracing + executable deserialization, which the persistent "
        "compile cache cannot remove)",
    )
    parser.addoption(
        "--heavy",
        action="store_true",
        default=False,
        help="run the nightly tier: kernel differential ladders and "
        "real-OS-process suites (see the 'heavy' marker)",
    )


def pytest_collection_modifyitems(config, items):
    heavy_compile_opt = config.getoption("--heavy-compile")
    for item in items:
        if os.path.basename(str(item.fspath)) in _HEAVY_FILES:
            item.add_marker(pytest.mark.heavy)
    if not config.getoption("--heavy"):
        skip_heavy = pytest.mark.skip(
            reason="nightly tier; opt in with --heavy"
        )
        for item in items:
            # --heavy-compile is its own explicit opt-in: it must keep
            # selecting the compile-ladder tests even though their files
            # sit in the heavy tier
            if "heavy" in item.keywords and not (
                heavy_compile_opt and "heavy_compile" in item.keywords
            ):
                item.add_marker(skip_heavy)
    if heavy_compile_opt:
        return
    skip = pytest.mark.skip(
        reason="needs --heavy-compile; fast component coverage of the same "
        "math runs by default (tests/test_field_secp_rows.py)"
    )
    for item in items:
        if "heavy_compile" in item.keywords:
            item.add_marker(skip)

"""Test configuration: force the JAX CPU backend with a virtual 8-device mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective tests
run against 8 virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip). Must run before any
`import jax` anywhere in the test session.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Concurrency correctness suite (corda_tpu/analysis, docs/static-analysis.md).

Tier-1 gates:
  * the whole package lints CLEAN against the pinned
    analysis_manifest.json (any new finding fails here first);
  * a synthetic violation of EACH static pass produces a named finding
    and fails `tools/lint.py`;
  * the kernel-jaxpr lint matches its pinned counts (0 dynamic-update-
    slice / 0 unbounded while in every verify kernel) and a synthetic
    d-u-s injection trips the gate;
  * the true positives this suite surfaced and fixed (unguarded batcher
    counters, anonymous threads, silent handler/timer swallows) are
    pinned as regressions — the baseline must shrink, not grow.
"""
import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from corda_tpu.analysis import (
    astlint,
    check_findings,
    envknobs,
    kernel_lint,
    load_manifest,
    manifest as manifest_mod,
    run_passes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_CLI = os.path.join(REPO, "tools", "lint.py")


def _lint_file(tmp_path, name, source, passes=None):
    """Run the static passes over one synthetic file."""
    pkg = tmp_path / "corda_tpu"
    pkg.mkdir(exist_ok=True)
    f = pkg / name
    f.write_text(textwrap.dedent(source))
    return run_passes(paths=[str(f)], root=str(tmp_path), passes=passes)


# -- per-pass behaviour -------------------------------------------------------

class TestGuardedBy:
    def test_unguarded_write_flagged(self, tmp_path):
        fs = _lint_file(tmp_path, "g.py", """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0  # guarded-by: _lock

                def bump(self):
                    self.count += 1
        """, passes=["guarded_by"])
        assert len(fs) == 1
        assert fs[0].pass_id == "guarded_by"
        assert "C.count@C.bump" in fs[0].symbol

    def test_locked_write_and_init_exempt(self, tmp_path):
        fs = _lint_file(tmp_path, "g.py", """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0  # guarded-by: _lock
                    self.count = 1  # __init__ re-write: exempt

                def bump(self):
                    with self._lock:
                        self.count += 1
        """, passes=["guarded_by"])
        assert fs == []

    def test_mutating_container_call_flagged(self, tmp_path):
        fs = _lint_file(tmp_path, "g.py", """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []  # guarded-by: _lock

                def push(self, x):
                    self.items.append(x)
        """, passes=["guarded_by"])
        assert len(fs) == 1

    def test_alternative_locks_and_module_globals(self, tmp_path):
        fs = _lint_file(tmp_path, "g.py", """
            import threading

            _lock = threading.Lock()
            _cv = threading.Condition(_lock)
            _state = {}  # guarded-by: _lock, _cv

            def ok():
                with _cv:
                    _state["a"] = 1

            def bad():
                _state["b"] = 2
        """, passes=["guarded_by"])
        assert len(fs) == 1
        assert "@bad" in fs[0].symbol

    def test_suppression(self, tmp_path):
        fs = _lint_file(tmp_path, "g.py", """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0  # guarded-by: _lock

                def bump_caller_holds(self):
                    # lint: allow(guarded_by) — caller holds _lock
                    self.count += 1
        """, passes=["guarded_by"])
        assert fs == []


class TestBlockingUnderLock:
    SRC = """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def naps(self):
                with self._lock:
                    time.sleep(1)

            def waits_future(self, fut):
                with self._lock:
                    return fut.result()

            def sends(self, broker):
                with self._lock:
                    broker.send("q", b"x")

            def commits(self, conn):
                with self._lock:
                    conn.commit()

            def foreign_wait(self, event):
                with self._lock:
                    event.wait_for(lambda: True)
    """

    def test_blocking_calls_flagged(self, tmp_path):
        fs = _lint_file(tmp_path, "b.py", self.SRC,
                        passes=["blocking_under_lock"])
        kinds = sorted(f.symbol.split(":")[1] for f in fs)
        assert kinds == sorted([
            "time.sleep", "fut.result", "broker.send", "conn.commit",
            "event.wait_for",
        ])

    def test_own_cv_wait_not_flagged(self, tmp_path):
        fs = _lint_file(tmp_path, "b.py", """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)

                def park(self):
                    with self._cv:
                        self._cv.wait()

                def park_under_lock(self):
                    with self._lock:
                        self._cv.wait()  # same owner: cv wraps _lock
        """, passes=["blocking_under_lock"])
        assert fs == []

    def test_nested_def_not_under_lock(self, tmp_path):
        fs = _lint_file(tmp_path, "b.py", """
            import threading
            import time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def deferred(self):
                    with self._lock:
                        def later():
                            time.sleep(1)  # runs AFTER the with
                        return later
        """, passes=["blocking_under_lock"])
        assert fs == []

    def test_dict_get_not_flagged(self, tmp_path):
        fs = _lint_file(tmp_path, "b.py", """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queues = {}

                def look(self, q):
                    with self._lock:
                        a = self._queues.get("name")
                        b = self._queues.get("name", None)
                        return a, b, q.get(timeout=1)
        """, passes=["blocking_under_lock"])
        # only the real Queue.get (kwargs-only signature) is flagged
        assert len(fs) == 1 and "q.get" in fs[0].symbol


class TestThreadDaemonAndSwallow:
    def test_thread_missing_kwargs(self, tmp_path):
        fs = _lint_file(tmp_path, "t.py", """
            import threading

            def spawn():
                threading.Thread(target=print).start()

            def ok():
                threading.Thread(target=print, daemon=True,
                                 name="x").start()
        """, passes=["thread_daemon"])
        assert len(fs) == 1
        assert "daemon and name" in fs[0].message

    def test_swallow_variants(self, tmp_path):
        fs = _lint_file(tmp_path, "s.py", """
            def silent():
                try:
                    work()
                except Exception:
                    pass

            def bare_silent():
                try:
                    work()
                except:
                    return None

            def reraises():
                try:
                    work()
                except Exception:
                    raise

            def logs(log):
                try:
                    work()
                except Exception as exc:
                    log.warning("boom %s", exc)

            def uses_exc(out):
                try:
                    work()
                except Exception as exc:
                    out.set_exception(exc)

            def narrow():
                try:
                    work()
                except ValueError:
                    pass
        """, passes=["swallow"])
        assert sorted(f.symbol for f in fs) == [
            "bare_silent:bare", "silent:Exception",
        ]


class TestEnvRegistry:
    def test_unregistered_knob_flagged(self, tmp_path):
        fs = _lint_file(tmp_path, "e.py", """
            import os

            A = os.environ.get("CORDA_TPU_BOGUS_KNOB", "1")
            B = os.environ.get("CORDA_TPU_TRACING", "1")  # registered
        """, passes=["guarded_by", "env_registry"])
        assert [f.symbol for f in fs] == ["CORDA_TPU_BOGUS_KNOB"]

    def test_registry_is_complete_and_documented(self):
        """The three-way invariant on the real tree: every read
        registered, every entry documented + actually read."""
        findings = [f for f in run_passes(passes=["env_registry"])]
        assert findings == [], [f.message for f in findings]

    def test_registry_docs_exist(self):
        for knob in envknobs.KNOBS.values():
            assert os.path.exists(os.path.join(REPO, knob.doc)), knob

    def test_stale_registry_entry_flagged(self, monkeypatch):
        """A registered-but-never-read knob must fire (the registry's
        own registration literals don't count as reads)."""
        fake = dict(envknobs.KNOBS)
        fake["CORDA_TPU_NEVER_READ"] = envknobs.Knob(
            "CORDA_TPU_NEVER_READ", "0", "docs/running-nodes.md", "x"
        )
        monkeypatch.setattr(envknobs, "KNOBS", fake)
        findings = run_passes(passes=["env_registry"])
        symbols = {f.symbol for f in findings}
        assert "CORDA_TPU_NEVER_READ:stale" in symbols
        assert "CORDA_TPU_NEVER_READ:undocumented" in symbols

    def test_doc_check_is_delimited_not_substring(self, tmp_path,
                                                  monkeypatch):
        """CORDA_TPU_LOCKCHECK's missing row must not ride on the
        CORDA_TPU_LOCKCHECK_HOLD_MS row."""
        docs = tmp_path / "docs"
        docs.mkdir()
        (docs / "running-nodes.md").write_text(
            "| `CORDA_TPU_LOCKCHECK_HOLD_MS` | 1000 | x |\n"
        )
        fake = {
            n: envknobs.KNOBS[n]
            for n in ("CORDA_TPU_LOCKCHECK", "CORDA_TPU_LOCKCHECK_HOLD_MS")
        }
        monkeypatch.setattr(envknobs, "KNOBS", fake)
        reads = {n: [("f.py", 1)] for n in fake}
        findings = astlint._env_registry_finalize(reads, str(tmp_path))
        symbols = {f.symbol for f in findings}
        assert "CORDA_TPU_LOCKCHECK:undocumented" in symbols
        assert "CORDA_TPU_LOCKCHECK_HOLD_MS:undocumented" not in symbols


# -- manifest baseline mechanics ---------------------------------------------

class TestManifest:
    def test_pin_roundtrip_and_new_finding_fails(self, tmp_path):
        f1 = astlint.Finding("swallow", "corda_tpu/x.py", 3, "f:Exception",
                             "m")
        f2 = astlint.Finding("swallow", "corda_tpu/x.py", 9, "g:bare", "m")
        path = str(tmp_path / "m.json")
        manifest_mod.pin_manifest(path=path, findings=[f1], kernels={})
        m = manifest_mod.load_manifest(path)
        res = manifest_mod.check_findings([f1], m)
        assert res["new"] == [] and res["stale"] == []
        res = manifest_mod.check_findings([f1, f2], m)
        assert [n["key"] for n in res["new"]] == [f2.key]
        res = manifest_mod.check_findings([], m)
        assert res["new"] == [] and res["stale"] == [f1.key]

    def test_partial_pin_preserves_kernels(self, tmp_path):
        path = str(tmp_path / "m.json")
        manifest_mod.pin_manifest(
            path=path, findings=[], kernels={"k": {"dynamic_loops": 0}}
        )
        manifest_mod.pin_manifest(path=path, findings=[], kernels=None)
        assert manifest_mod.load_manifest(path)["kernels"] == {
            "k": {"dynamic_loops": 0}
        }

    def test_partial_pass_pin_preserves_other_passes(self, tmp_path):
        """`--pin --pass thread_daemon` must not wipe the swallow
        baseline (re-pinning one pass never resurrects the others'
        accepted findings as NEW)."""
        path = str(tmp_path / "m.json")
        f_swallow = astlint.Finding("swallow", "corda_tpu/x.py", 1,
                                    "f:Exception", "m")
        manifest_mod.pin_manifest(path=path, findings=[f_swallow],
                                  kernels={})
        manifest_mod.pin_manifest(path=path, findings=[],
                                  passes=["thread_daemon"])
        m = manifest_mod.load_manifest(path)
        assert m["passes"]["swallow"] == [f_swallow.key]
        assert m["passes"]["thread_daemon"] == []

    def test_kernel_gate_zero_pin_fails_any_growth(self):
        m = {"tolerance": 0.05, "kernels": {
            "k": {"dynamic_update_slice": 0, "dynamic_loops": 0},
        }}
        ok = manifest_mod.check_kernels(
            {"k": {"dynamic_update_slice": 0, "dynamic_loops": 0}}, m
        )
        assert ok == []
        grew = manifest_mod.check_kernels(
            {"k": {"dynamic_update_slice": 2, "dynamic_loops": 0}}, m
        )
        assert [v["kind"] for v in grew] == ["grew"]
        unpinned = manifest_mod.check_kernels({"other": {}}, m)
        assert [v["kind"] for v in unpinned] == ["unpinned"]
        assert manifest_mod.fatal_kernel_violations(grew + unpinned)


# -- THE tier-1 gate ----------------------------------------------------------

class TestPackageGate:
    def test_whole_package_clean_vs_pinned_baseline(self):
        result = check_findings()
        assert result["new"] == [], (
            "NEW lint finding(s) — fix them or suppress with a reasoned "
            "`# lint: allow(...)`; do not re-pin to absorb them silently: "
            + json.dumps(result["new"], indent=1)
        )
        assert result["stale"] == [], (
            "baseline entries fixed — run `python tools/lint.py --pin` "
            "so the baseline shrinks: " + json.dumps(result["stale"])
        )

    def test_fixed_true_positives_stay_fixed(self):
        """Regression pins for the findings this PR fixed: the keys must
        be absent from both the current findings and the baseline."""
        current = {f.key for f in run_passes()}
        pinned = {
            k for keys in load_manifest()["passes"].values() for k in keys
        }
        fixed = [
            # unguarded multi-writer batcher counters (now annotated +
            # written under _lock in _run_batch)
            "guarded_by:corda_tpu/verifier/batcher.py:"
            "SignatureBatcher.flushes@SignatureBatcher._run_batch",
            "guarded_by:corda_tpu/verifier/batcher.py:"
            "SignatureBatcher.items_verified@SignatureBatcher._run_batch",
            # silently-swallowed p2p handler / timer-callback exceptions
            # (now eventlogged)
            "swallow:corda_tpu/node/network.py:"
            "BrokerMessagingService._consume_from:Exception",
            "swallow:corda_tpu/utils/timerwheel.py:_guarded:Exception",
            # anonymous threads (now daemon= + name=)
            "thread_daemon:corda_tpu/loadtest/procdriver.py:"
            "PairDriver.__init__",
            "thread_daemon:corda_tpu/loadtest/latency.py:"
            "measure_uniqueness_batch.burst",
            "thread_daemon:corda_tpu/loadtest/real.py:run",
            "thread_daemon:corda_tpu/node/shardhost.py:"
            "ShardSupervisor.snapshot",
        ]
        for key in fixed:
            assert key not in current, f"regressed: {key}"
            assert key not in pinned, f"crept back into baseline: {key}"

    def test_no_accepted_debt_in_strict_passes(self):
        """guarded_by / thread_daemon / env_registry start (and must
        stay) at ZERO accepted findings — new debt in these passes is
        never baselined, only fixed."""
        baseline = load_manifest()["passes"]
        for strict in ("guarded_by", "thread_daemon", "env_registry"):
            assert baseline[strict] == [], baseline[strict]


# -- tools/lint.py CLI --------------------------------------------------------

VIOLATIONS = {
    "guarded_by": """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # guarded-by: _lock

            def bump(self):
                self.n += 1
    """,
    "blocking_under_lock": """
        import threading
        import time

        _lock = threading.Lock()

        def nap():
            with _lock:
                time.sleep(1)
    """,
    "thread_daemon": """
        import threading

        def spawn():
            threading.Thread(target=print).start()
    """,
    "swallow": """
        def silent():
            try:
                pass
            except Exception:
                pass
    """,
    "env_registry": """
        import os

        V = os.environ.get("CORDA_TPU_BOGUS_KNOB")
    """,
}


class TestLintCLI:
    @pytest.mark.parametrize("pass_id", sorted(VIOLATIONS))
    def test_synthetic_violation_fails_cli_with_named_finding(
        self, tmp_path, pass_id
    ):
        root = tmp_path / "minirepo"
        (root / "corda_tpu").mkdir(parents=True)
        (root / "tools").mkdir()
        (root / "docs").mkdir()
        # real knob table so the env pass's doc check sees its entries
        shutil.copy(os.path.join(REPO, "docs", "running-nodes.md"),
                    root / "docs" / "running-nodes.md")
        bad = root / "corda_tpu" / f"bad_{pass_id}.py"
        bad.write_text(textwrap.dedent(VIOLATIONS[pass_id]))
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--baseline", "--no-kernel",
             "--root", str(root), "--pass", pass_id],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1, proc.stderr
        expected = f"NEW FINDING {pass_id}:corda_tpu/bad_{pass_id}.py:"
        assert expected in proc.stderr, proc.stderr

    def test_clean_repo_passes_cli_static_only(self):
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--baseline", "--no-kernel",
             "--json"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout.splitlines()[-1])
        assert out["ok"] and out["accepted"] > 0

    def test_pin_refuses_foreign_root(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--pin", "--root", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 2


# -- kernel-jaxpr lint --------------------------------------------------------

class TestKernelLint:
    def test_pinned_kernels_clean(self):
        """Every verify kernel matches its pin: 0 dynamic-update-slice,
        0 unbounded while. Shares the opbudget per-process trace cache
        with tests/test_opbudget.py."""
        violations = kernel_lint.check_all()
        assert violations == [], violations

    def test_synthetic_dus_trips_gate(self):
        from corda_tpu.ops import opbudget

        opbudget._TEST_EXTRA_DUS = 3
        try:
            violations = kernel_lint.check_all(
                names=["ed25519_xla"], use_cache=False
            )
        finally:
            opbudget._TEST_EXTRA_DUS = 0
            opbudget._clear_cache("ed25519_xla")
        assert [(v["kind"], v["metric"]) for v in violations] == [
            ("grew", "dynamic_update_slice")
        ]
        assert violations[0]["measured"] >= 3

    def test_walker_counts_dus_and_while(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from corda_tpu.ops.opbudget import _count_fn

        def with_dus(x):
            return lax.dynamic_update_slice(x, x[0:1], (0,))

        def with_while(x):
            return lax.while_loop(
                lambda v: v[0] < 100, lambda v: v + 1, x
            )

        s = jax.ShapeDtypeStruct((8,), jnp.uint32)
        dus = _count_fn(with_dus, (s,), {})
        assert dus["dus_eqns"] == 1 and dus["dynamic_loops"] == 0
        wl = _count_fn(with_while, (s,), {})
        assert wl["dynamic_loops"] == 1 and wl["dus_eqns"] == 0

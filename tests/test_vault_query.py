"""Vault query engine tests: criteria, paging, sorting, tracking.

Reference parity: `node/src/test/kotlin/net/corda/node/services/vault/
VaultQueryTests.kt` shapes — status filters, criteria composition,
paging with total count, sorting, participant lookup.
"""
import time
from dataclasses import dataclass
from typing import List

import pytest

from corda_tpu.core.contracts import (
    Contract,
    ContractState,
    StateAndRef,
    TypeOnlyCommandData,
    contract,
)
from corda_tpu.core.serialization.codec import corda_serializable
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.node.vault_query import (
    ALL,
    CONSUMED,
    UNCONSUMED,
    Page,
    PageSpecification,
    Sort,
    VaultQueryCriteria,
    VaultQueryError,
)
from corda_tpu.testing.mocknetwork import MockNetwork


@corda_serializable
@dataclass(frozen=True)
class QState(ContractState):
    parties: tuple = ()
    n: int = 0
    contract_name = "QContract"

    @property
    def participants(self) -> List:
        return list(self.parties)


@corda_serializable
@dataclass(frozen=True)
class QCommand(TypeOnlyCommandData):
    pass


@contract(name="QContract")
class QContract(Contract):
    def verify(self, tx) -> None:
        pass


@contract(name="QContract2")
class QContract2(Contract):
    def verify(self, tx) -> None:
        pass


@corda_serializable
@dataclass(frozen=True)
class QState2(ContractState):
    parties: tuple = ()
    n: int = 0
    contract_name = "QContract2"

    @property
    def participants(self) -> List:
        return list(self.parties)


class TestVaultQuery:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.vault = self.alice.services.vault_service

    def teardown_method(self):
        self.net.stop_nodes()

    def _issue(self, n, cls=QState, count=1):
        refs = []
        for i in range(count):
            b = TransactionBuilder(notary=self.notary.info)
            b.add_output_state(cls(parties=(self.alice.info,), n=n + i))
            b.add_command(QCommand(), self.alice.info.owning_key)
            stx = self.alice.services.sign_initial_transaction(b)
            self.alice.services.record_transactions([stx])
            refs.append(stx.tx.out_ref(0))
        return refs

    def _consume(self, ref: StateAndRef):
        b = TransactionBuilder(notary=self.notary.info)
        b.add_input_state(ref)
        b.add_output_state(QState(parties=(self.alice.info,), n=999))
        b.add_command(QCommand(), self.alice.info.owning_key)
        stx = self.alice.services.sign_initial_transaction(b)
        self.alice.services.record_transactions([stx])

    def test_status_filters(self):
        refs = self._issue(0, count=3)
        self._consume(refs[0])
        unconsumed = self.vault.query(VaultQueryCriteria(status=UNCONSUMED))
        consumed = self.vault.query(VaultQueryCriteria(status=CONSUMED))
        everything = self.vault.query(VaultQueryCriteria(status=ALL))
        # consuming produced one new state: 3 - 1 + 1 = 3 unconsumed
        assert unconsumed.total_states_available == 3
        assert consumed.total_states_available == 1
        assert everything.total_states_available == 4

    def test_contract_filter_and_composition(self):
        self._issue(0, count=2)
        self._issue(10, cls=QState2, count=3)
        only_q = self.vault.query(
            VaultQueryCriteria(contract_names=("QContract",))
        )
        assert only_q.total_states_available == 2
        both = self.vault.query(
            VaultQueryCriteria(contract_names=("QContract",)).or_(
                VaultQueryCriteria(contract_names=("QContract2",))
            )
        )
        assert both.total_states_available == 5

    def test_paging_with_total(self):
        self._issue(0, count=25)
        page1 = self.vault.query(
            paging=PageSpecification(page_number=1, page_size=10)
        )
        page3 = self.vault.query(
            paging=PageSpecification(page_number=3, page_size=10)
        )
        assert page1.total_states_available == 25
        assert len(page1.states) == 10
        assert len(page3.states) == 5
        # no overlap between pages
        ids1 = {s.ref for s in page1.states}
        ids3 = {s.ref for s in page3.states}
        assert not ids1 & ids3

    def test_sorting(self):
        self._issue(0, count=5)
        asc = self.vault.query(sort=Sort("state_ref", descending=False))
        desc = self.vault.query(sort=Sort("state_ref", descending=True))
        assert [s.ref for s in asc.states] == [s.ref for s in reversed(desc.states)]
        with pytest.raises(VaultQueryError):
            self.vault.query(sort=Sort("evil; DROP TABLE vault_states"))

    def test_participant_criteria(self):
        self._issue(0, count=2)
        mine = self.vault.query(
            VaultQueryCriteria(
                participant_keys=(self.alice.info.owning_key.encoded,)
            )
        )
        assert mine.total_states_available == 2
        nobody = self.vault.query(
            VaultQueryCriteria(participant_keys=(b"\x01" * 32,))
        )
        assert nobody.total_states_available == 0

    def test_time_window(self):
        self._issue(0, count=1)
        cutoff = time.time() + 1
        recent = self.vault.query(
            VaultQueryCriteria(recorded_before=cutoff)
        )
        assert recent.total_states_available == 1
        future = self.vault.query(VaultQueryCriteria(recorded_after=cutoff))
        assert future.total_states_available == 0

    def test_state_ref_lookup(self):
        refs = self._issue(0, count=3)
        one = self.vault.query(
            VaultQueryCriteria(state_refs=(refs[1].ref,))
        )
        assert one.total_states_available == 1
        assert one.states[0].ref == refs[1].ref

    def test_page_spec_validation(self):
        with pytest.raises(VaultQueryError):
            PageSpecification(page_number=0)
        with pytest.raises(VaultQueryError):
            PageSpecification(page_size=0)

"""Vault query engine tests: criteria, paging, sorting, tracking.

Reference parity: `node/src/test/kotlin/net/corda/node/services/vault/
VaultQueryTests.kt` shapes — status filters, criteria composition,
paging with total count, sorting, participant lookup.
"""
import time
from dataclasses import dataclass
from typing import List

import pytest

from corda_tpu.core.contracts import (
    Contract,
    ContractState,
    StateAndRef,
    TypeOnlyCommandData,
    contract,
)
from corda_tpu.core.serialization.codec import corda_serializable
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.node.vault_query import (
    ALL,
    CONSUMED,
    UNCONSUMED,
    Page,
    PageSpecification,
    Sort,
    VaultQueryCriteria,
    VaultQueryError,
)
from corda_tpu.testing.mocknetwork import MockNetwork


@corda_serializable
@dataclass(frozen=True)
class QState(ContractState):
    parties: tuple = ()
    n: int = 0
    contract_name = "QContract"

    @property
    def participants(self) -> List:
        return list(self.parties)


@corda_serializable
@dataclass(frozen=True)
class QCommand(TypeOnlyCommandData):
    pass


@contract(name="QContract")
class QContract(Contract):
    def verify(self, tx) -> None:
        pass


@contract(name="QContract2")
class QContract2(Contract):
    def verify(self, tx) -> None:
        pass


@corda_serializable
@dataclass(frozen=True)
class QState2(ContractState):
    parties: tuple = ()
    n: int = 0
    contract_name = "QContract2"

    @property
    def participants(self) -> List:
        return list(self.parties)


class TestVaultQuery:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.vault = self.alice.services.vault_service

    def teardown_method(self):
        self.net.stop_nodes()

    def _issue(self, n, cls=QState, count=1):
        refs = []
        for i in range(count):
            b = TransactionBuilder(notary=self.notary.info)
            b.add_output_state(cls(parties=(self.alice.info,), n=n + i))
            b.add_command(QCommand(), self.alice.info.owning_key)
            stx = self.alice.services.sign_initial_transaction(b)
            self.alice.services.record_transactions([stx])
            refs.append(stx.tx.out_ref(0))
        return refs

    def _consume(self, ref: StateAndRef):
        b = TransactionBuilder(notary=self.notary.info)
        b.add_input_state(ref)
        b.add_output_state(QState(parties=(self.alice.info,), n=999))
        b.add_command(QCommand(), self.alice.info.owning_key)
        stx = self.alice.services.sign_initial_transaction(b)
        self.alice.services.record_transactions([stx])

    def test_status_filters(self):
        refs = self._issue(0, count=3)
        self._consume(refs[0])
        unconsumed = self.vault.query(VaultQueryCriteria(status=UNCONSUMED))
        consumed = self.vault.query(VaultQueryCriteria(status=CONSUMED))
        everything = self.vault.query(VaultQueryCriteria(status=ALL))
        # consuming produced one new state: 3 - 1 + 1 = 3 unconsumed
        assert unconsumed.total_states_available == 3
        assert consumed.total_states_available == 1
        assert everything.total_states_available == 4

    def test_contract_filter_and_composition(self):
        self._issue(0, count=2)
        self._issue(10, cls=QState2, count=3)
        only_q = self.vault.query(
            VaultQueryCriteria(contract_names=("QContract",))
        )
        assert only_q.total_states_available == 2
        both = self.vault.query(
            VaultQueryCriteria(contract_names=("QContract",)).or_(
                VaultQueryCriteria(contract_names=("QContract2",))
            )
        )
        assert both.total_states_available == 5

    def test_paging_with_total(self):
        self._issue(0, count=25)
        page1 = self.vault.query(
            paging=PageSpecification(page_number=1, page_size=10)
        )
        page3 = self.vault.query(
            paging=PageSpecification(page_number=3, page_size=10)
        )
        assert page1.total_states_available == 25
        assert len(page1.states) == 10
        assert len(page3.states) == 5
        # no overlap between pages
        ids1 = {s.ref for s in page1.states}
        ids3 = {s.ref for s in page3.states}
        assert not ids1 & ids3

    def test_sorting(self):
        self._issue(0, count=5)
        asc = self.vault.query(sort=Sort("state_ref", descending=False))
        desc = self.vault.query(sort=Sort("state_ref", descending=True))
        assert [s.ref for s in asc.states] == [s.ref for s in reversed(desc.states)]
        with pytest.raises(VaultQueryError):
            self.vault.query(sort=Sort("evil; DROP TABLE vault_states"))

    def test_participant_criteria(self):
        self._issue(0, count=2)
        mine = self.vault.query(
            VaultQueryCriteria(
                participant_keys=(self.alice.info.owning_key.encoded,)
            )
        )
        assert mine.total_states_available == 2
        nobody = self.vault.query(
            VaultQueryCriteria(participant_keys=(b"\x01" * 32,))
        )
        assert nobody.total_states_available == 0

    def test_time_window(self):
        self._issue(0, count=1)
        cutoff = time.time() + 1
        recent = self.vault.query(
            VaultQueryCriteria(recorded_before=cutoff)
        )
        assert recent.total_states_available == 1
        future = self.vault.query(VaultQueryCriteria(recorded_after=cutoff))
        assert future.total_states_available == 0

    def test_state_ref_lookup(self):
        refs = self._issue(0, count=3)
        one = self.vault.query(
            VaultQueryCriteria(state_refs=(refs[1].ref,))
        )
        assert one.total_states_available == 1
        assert one.states[0].ref == refs[1].ref

    def test_page_spec_validation(self):
        with pytest.raises(VaultQueryError):
            PageSpecification(page_number=0)
        with pytest.raises(VaultQueryError):
            PageSpecification(page_size=0)


# ---------------------------------------------------------------------------
# Criteria families (reference HibernateQueryCriteriaParser:
# LinearStateQueryCriteria -> VaultLinearStates, FungibleAssetQueryCriteria
# -> CashSchemaV1 columns, VaultCustomQueryCriteria -> MappedSchema)
# ---------------------------------------------------------------------------

from corda_tpu.core.contracts import UniqueIdentifier  # noqa: E402
from corda_tpu.core.contracts.amount import Amount, Issued  # noqa: E402
from corda_tpu.core.identity import PartyAndReference  # noqa: E402
from corda_tpu.finance.cash import CashState  # noqa: E402
from corda_tpu.node.vault_query import (  # noqa: E402
    CustomAttributeCriteria,
    FungibleAssetQueryCriteria,
    LinearStateQueryCriteria,
)


@corda_serializable
@dataclass(frozen=True)
class QLinear(ContractState):
    parties: tuple = ()
    linear_id: UniqueIdentifier = None
    contract_name = "QContract"

    @property
    def participants(self) -> List:
        return list(self.parties)


@corda_serializable
@dataclass(frozen=True)
class QDeal(ContractState):
    """Custom-schema state: exposes a maturity column via
    vault_attributes() (per-contract MappedSchema analogue)."""

    parties: tuple = ()
    maturity: float = 0.0
    deal_ref: str = ""
    contract_name = "QContract"

    @property
    def participants(self) -> List:
        return list(self.parties)

    def vault_attributes(self):
        return {"maturity": self.maturity, "deal_ref": self.deal_ref}


class TestCriteriaFamilies:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.bob = self.net.create_node("O=Bob,L=Paris,C=FR")
        self.vault = self.alice.services.vault_service

    def teardown_method(self):
        self.net.stop_nodes()

    def _record(self, state):
        b = TransactionBuilder(notary=self.notary.info)
        b.add_output_state(state)
        b.add_command(QCommand(), self.alice.info.owning_key)
        stx = self.alice.services.sign_initial_transaction(b)
        self.alice.services.record_transactions([stx])
        return stx.tx.out_ref(0)

    def _cash(self, quantity, issuer, ref=b"\x01", owner=None, product="USD"):
        token = Issued(PartyAndReference(issuer, ref), product)
        return self._record(
            CashState(amount=Amount(quantity, token),
                      owner=owner or self.alice.info)
        )

    def test_cash_by_issuer_and_quantity(self):
        self._cash(100, self.alice.info)
        self._cash(2500, self.bob.info)
        self._cash(900, self.bob.info, ref=b"\x02")

        by_issuer = self.vault.query(
            FungibleAssetQueryCriteria(issuer_names=(self.bob.info.name,))
        )
        assert by_issuer.total_states_available == 2

        big = self.vault.query(
            FungibleAssetQueryCriteria(quantity=(">=", 900))
        )
        assert big.total_states_available == 2

        bob_big = self.vault.query(
            FungibleAssetQueryCriteria(
                issuer_names=(self.bob.info.name,), quantity=(">", 1000)
            )
        )
        assert bob_big.total_states_available == 1
        assert bob_big.states[0].state.data.amount.quantity == 2500

        by_ref = self.vault.query(
            FungibleAssetQueryCriteria(issuer_refs=(b"\x02",))
        )
        assert by_ref.total_states_available == 1

    def test_cash_by_owner_and_product(self):
        self._cash(10, self.alice.info, owner=self.alice.info)
        self._cash(20, self.alice.info, owner=self.bob.info)
        self._cash(30, self.alice.info, product="GBP")

        mine = self.vault.query(
            FungibleAssetQueryCriteria(
                owner_keys=(self.alice.info.owning_key.encoded,)
            )
        )
        # owner=bob state is still recorded in alice's vault (alice is
        # not a participant -> is_relevant may skip it); assert on owners
        assert all(
            s.state.data.owner == self.alice.info for s in mine.states
        )
        assert mine.total_states_available == 2
        gbp = self.vault.query(
            FungibleAssetQueryCriteria(products=("GBP",))
        )
        assert gbp.total_states_available == 1

    def test_linear_id_and_external_id(self):
        lid1 = UniqueIdentifier(external_id="deal-A")
        lid2 = UniqueIdentifier()
        self._record(QLinear(parties=(self.alice.info,), linear_id=lid1))
        self._record(QLinear(parties=(self.alice.info,), linear_id=lid2))

        one = self.vault.query(
            LinearStateQueryCriteria(linear_ids=(lid1,))
        )
        assert one.total_states_available == 1
        assert one.states[0].state.data.linear_id == lid1

        by_ext = self.vault.query(
            LinearStateQueryCriteria(external_ids=("deal-A",))
        )
        assert by_ext.total_states_available == 1

        both = self.vault.query(
            LinearStateQueryCriteria(linear_ids=(lid1, lid2))
        )
        assert both.total_states_available == 2

    def test_linear_chain_head_by_status(self):
        """Consuming a linear state and reissuing under the same
        linear_id: UNCONSUMED finds only the chain head (reference
        VaultQueryTests linear-head semantics)."""
        lid = UniqueIdentifier(external_id="chain")
        ref = self._record(QLinear(parties=(self.alice.info,), linear_id=lid))
        b = TransactionBuilder(notary=self.notary.info)
        b.add_input_state(ref)
        b.add_output_state(QLinear(parties=(self.alice.info,), linear_id=lid))
        b.add_command(QCommand(), self.alice.info.owning_key)
        stx = self.alice.services.sign_initial_transaction(b)
        self.alice.services.record_transactions([stx])

        heads = self.vault.query(LinearStateQueryCriteria(linear_ids=(lid,)))
        assert heads.total_states_available == 1
        assert heads.states[0].ref.txhash == stx.id
        history = self.vault.query(
            LinearStateQueryCriteria(linear_ids=(lid,), status=ALL)
        )
        assert history.total_states_available == 2

    def test_big_integer_quantity_exact(self):
        """Quantities above 2^53 must compare exactly (NUMERIC affinity,
        no float rounding — round-3 review finding)."""
        big = 2**53 + 1
        self._cash(big, self.alice.info)
        exact = self.vault.query(
            FungibleAssetQueryCriteria(quantity=("=", big))
        )
        assert exact.total_states_available == 1
        off_by_one = self.vault.query(
            FungibleAssetQueryCriteria(quantity=("=", 2**53))
        )
        assert off_by_one.total_states_available == 0
        above = self.vault.query(
            FungibleAssetQueryCriteria(quantity=(">", 2**53))
        )
        assert above.total_states_available == 1

    def test_custom_attribute_criteria(self):
        self._record(QDeal(parties=(self.alice.info,), maturity=100.0,
                           deal_ref="D1"))
        self._record(QDeal(parties=(self.alice.info,), maturity=500.0,
                           deal_ref="D2"))

        soon = self.vault.query(
            CustomAttributeCriteria("maturity", "<=", 200.0, numeric=True)
        )
        assert soon.total_states_available == 1
        assert soon.states[0].state.data.deal_ref == "D1"

        named = self.vault.query(
            CustomAttributeCriteria("deal_ref", "=", "D2")
        )
        assert named.total_states_available == 1

        with pytest.raises(VaultQueryError):
            CustomAttributeCriteria("x", "BOGUS", 1).compile()

    def test_family_composes_with_general_criteria(self):
        self._cash(50, self.alice.info)
        self._record(QState(parties=(self.alice.info,), n=1))
        combined = self.vault.query(
            VaultQueryCriteria(
                contract_names=("corda_tpu.finance.Cash",)
            ).and_(FungibleAssetQueryCriteria(quantity=(">=", 10)))
        )
        assert combined.total_states_available == 1

    def test_criteria_roundtrip_codec(self):
        from corda_tpu.core.serialization.codec import deserialize, serialize

        for crit in (
            LinearStateQueryCriteria(external_ids=("x",)),
            FungibleAssetQueryCriteria(quantity=(">=", 7)),
            CustomAttributeCriteria("m", "<", 3.5, numeric=True),
        ):
            rt = deserialize(serialize(crit))
            assert rt.compile() == crit.compile()

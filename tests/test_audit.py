"""Audit service + network-map feed tests (reference AuditService.kt,
CordaRPCOps.networkMapFeed)."""
from corda_tpu.core.flows import FlowLogic, startable_by_rpc
from corda_tpu.node.audit import DummyAuditService, MemoryAuditService
from corda_tpu.rpc import CordaRPCOps
from corda_tpu.testing import MockNetwork


@startable_by_rpc
class _AuditedFlow(FlowLogic):
    def call(self):
        return "ok"
        yield  # pragma: no cover


class TestMemoryAuditService:
    def test_record_and_filter(self):
        svc = MemoryAuditService(capacity=4)
        svc.record_event("O=A", "flow.started", flow_id="1")
        svc.record_event("O=A", "flow.finished", flow_id="1")
        svc.record_event("O=B", "flow.started", flow_id="2")
        assert len(svc.events("flow.started")) == 2
        assert len(svc.events(principal="O=B")) == 1
        assert svc.events("flow.finished")[0].context["flow_id"] == "1"

    def test_bounded(self):
        svc = MemoryAuditService(capacity=3)
        for i in range(10):
            svc.record_event("O=A", "e", n=i)
        assert len(svc) == 3
        assert svc.events()[0].context["n"] == 7

    def test_subscriber_errors_swallowed(self):
        svc = MemoryAuditService()
        svc.subscribe(lambda e: 1 / 0)
        svc.record_event("O=A", "e")  # must not raise
        assert len(svc) == 1

    def test_dummy_drops(self):
        svc = DummyAuditService()
        svc.record_event("O=A", "e")  # no-op, no error


class TestNodeAuditTrail:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.node = self.net.create_node("O=Audited,L=London,C=GB")
        self.ops = CordaRPCOps(self.node.services, self.node.smm)

    def teardown_method(self):
        self.net.stop_nodes()

    def test_flow_lifecycle_audited(self):
        h = self.node.start_flow(_AuditedFlow())
        self.net.run_network()
        h.result.result(timeout=5)
        trail = self.ops.audit_events("flow.started")
        assert any(
            e["context"]["flow"].endswith("_AuditedFlow") for e in trail
        )
        assert self.ops.audit_events("flow.finished")

    def test_notary_commit_audited(self):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.finance.flows import CashIssueFlow, CashPaymentFlow

        bank = self.node
        h = bank.start_flow(CashIssueFlow(
            Amount(500, "USD"), b"\x01", bank.info, self.notary.info
        ))
        self.net.run_network()
        h.result.result(timeout=10)
        token = Issued(bank.info.ref(1), "USD")
        h = bank.start_flow(CashPaymentFlow(
            Amount(500, token), bank.info, self.notary.info
        ))
        self.net.run_network()
        h.result.result(timeout=10)
        notary_ops = CordaRPCOps(self.notary.services, self.notary.smm)
        commits = notary_ops.audit_events("notary.commit")
        assert len(commits) == 1
        assert commits[0]["context"]["inputs"] == 1


class TestNetworkMapFeed:
    def test_snapshot_and_changes(self):
        net = MockNetwork()
        a = net.create_node("O=FeedA,L=London,C=GB")
        ops = CordaRPCOps(a.services, a.smm)
        feed = ops.network_map_feed()
        assert any(p.name == a.info.name for p in feed.snapshot)
        changes = []
        feed.updates.subscribe(changes.append)
        b = net.create_node("O=FeedB,L=Paris,C=FR")
        assert any(
            c["change"] == "ADDED" and c["party"].name == b.info.name
            for c in changes
        )
        a.services.network_map_cache.remove_node(b.info.name)
        assert any(c["change"] == "REMOVED" for c in changes)
        net.stop_nodes()


class TestFlowTxMapping:
    def test_mapping_recorded_for_flow_finality(self):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.finance.flows import CashIssueFlow

        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        bank = net.create_node("O=MapBank,L=London,C=GB")
        ops = CordaRPCOps(bank.services, bank.smm)
        feed = ops.state_machine_recorded_transaction_mapping_feed()
        assert feed.snapshot == []
        live = []
        feed.updates.subscribe(live.append)
        h = bank.start_flow(CashIssueFlow(
            Amount(100, "USD"), b"\x01", bank.info, notary.info
        ))
        net.run_network()
        h.result.result(timeout=10)
        assert len(live) == 1
        assert live[0]["flow_id"] == h.flow_id
        assert ops.state_machine_recorded_transaction_mapping_feed().snapshot
        net.stop_nodes()


class TestVaultTransactionNotes:
    def test_notes_round_trip(self):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.finance.flows import CashIssueFlow

        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        bank = net.create_node("O=NoteBank,L=London,C=GB")
        ops = CordaRPCOps(bank.services, bank.smm)
        h = bank.start_flow(CashIssueFlow(
            Amount(100, "USD"), b"\x01", bank.info, notary.info
        ))
        net.run_network()
        h.result.result(timeout=10)
        stx = ops.verified_transactions_feed().snapshot[0]
        assert ops.get_vault_transaction_notes(stx.id) == []
        ops.add_vault_transaction_note(stx.id, "month-end issuance")
        ops.add_vault_transaction_note(stx.id, "audited")
        assert ops.get_vault_transaction_notes(stx.id) == [
            "month-end issuance", "audited",
        ]
        net.stop_nodes()

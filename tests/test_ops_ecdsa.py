"""ECDSA batch kernel tests (differential vs the host oracle).

Small batches (pad 8) so each curve's 256-bit ladder compiles once; the
compile dominates runtime on the CPU CI backend.
"""
import numpy as np
import pytest

from corda_tpu.core.crypto import (
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    crypto,
)
from corda_tpu.core.crypto.secp_math import SECP256K1, der_encode_sig, ecdsa_sign
from corda_tpu.ops import ecdsa_batch

# secp256k1 runs by default: its XLA-kernel compile is shared by every
# other default test in this file, so the marginal cost is one compile.
# secp256r1's separate multi-minute compile is opt-in (--heavy-compile);
# its curve constants keep fast default coverage via the component
# differentials in tests/test_field_secp_rows.py (the ladder/point code
# between the curves is identical — only constants differ).
CURVES = [
    (ECDSA_SECP256K1_SHA256, "secp256k1"),
    pytest.param(
        ECDSA_SECP256R1_SHA256, "secp256r1", marks=pytest.mark.heavy_compile
    ),
]


@pytest.mark.parametrize("scheme,cname", CURVES)
def test_valid_and_forged_batch(scheme, cname):
    pubs, sigs, msgs = [], [], []
    for i in range(8):
        kp = crypto.generate_keypair(scheme)
        m = b"ecdsa message %d" % i
        pubs.append(kp.public.encoded)
        sigs.append(crypto.do_sign(kp.private, m))
        msgs.append(m)
    msgs[2] = b"forged content"       # digest mismatch
    sigs[5] = sigs[4]                 # signature for another key/message
    out = ecdsa_batch.verify_batch(cname, pubs, sigs, msgs)
    expected = [True, True, False, True, True, False, True, True]
    assert out == expected
    # differential: host oracle agrees on every row
    from corda_tpu.core.crypto.keys import SchemePublicKey

    host = [
        crypto.is_valid(
            SchemePublicKey(scheme.scheme_code_name, pubs[i]), sigs[i], msgs[i]
        )
        for i in range(8)
    ]
    assert host == expected


def test_malformed_rows_are_false_not_errors():
    kp = crypto.generate_keypair(ECDSA_SECP256K1_SHA256)
    m = b"x"
    good = (kp.public.encoded, crypto.do_sign(kp.private, m), m)
    rows = [
        good,
        (b"\x02" + b"\xff" * 32, good[1], m),   # x not on curve
        (good[0], b"\x30\x02\x01\x01", m),      # truncated DER
        (good[0], der_encode_sig(0, 5), m),     # r = 0
        (good[0], der_encode_sig(SECP256K1.n, 5), m),  # r = n
    ]
    out = ecdsa_batch.verify_batch(
        "secp256k1",
        [r[0] for r in rows], [r[1] for r in rows], [r[2] for r in rows],
    )
    assert out == [True, False, False, False, False]


def test_high_s_and_rfc6979_vectors():
    # deterministic signing: same (key, msg) -> same sig; kernel verifies it
    curve = SECP256K1
    priv = 0xC9AFA9D845BA75166B5C215767B1D6934E50C3DB36E89B127B8A622B120F6721
    pub = curve.mul(priv, curve.g)
    msg = b"sample"
    r, s = ecdsa_sign(curve, priv, msg)
    der = der_encode_sig(r, s)
    out = ecdsa_batch.verify_batch(
        "secp256k1",
        [curve.encode_point(pub)] * 2, [der, der], [msg, b"not sample"],
    )
    assert out == [True, False]


class TestWycheproofStyleVectors:
    """Edge-case classes modelled on the Wycheproof ECDSA suites (the
    reference leans on BouncyCastle's hardening; the batch kernel must
    reject the same malformed classes — VERDICT round-1 weak #5)."""

    @pytest.fixture(scope="class")
    def fixture(self):
        kp = crypto.generate_keypair(ECDSA_SECP256K1_SHA256)
        msg = b"wycheproof style"
        return kp, msg, crypto.do_sign(kp.private, msg)

    def _run(self, rows):
        out = ecdsa_batch.verify_batch(
            "secp256k1",
            [r[0] for r in rows], [r[1] for r in rows], [r[2] for r in rows],
        )
        # differential: the host oracle must agree on every row
        from corda_tpu.core.crypto.keys import SchemePublicKey

        host = []
        for pub, sig, m in rows:
            try:
                host.append(
                    crypto.is_valid(
                        SchemePublicKey("ECDSA_SECP256K1_SHA256", pub), sig, m
                    )
                )
            except Exception:
                host.append(False)
        assert out == host, (out, host)
        return out

    def test_scalar_range_classes(self, fixture):
        kp, msg, good = fixture
        from corda_tpu.core.crypto.secp_math import der_decode_sig

        r, s = der_decode_sig(good)
        n = SECP256K1.n
        rows = [
            (kp.public.encoded, good, msg),                       # baseline
            (kp.public.encoded, der_encode_sig(r, 0), msg),        # s = 0
            (kp.public.encoded, der_encode_sig(r, n), msg),        # s = n
            (kp.public.encoded, der_encode_sig(r, n + s), msg),    # s > n
            (kp.public.encoded, der_encode_sig(r + n, s), msg),    # r > n
            (kp.public.encoded, der_encode_sig(n - r, s), msg),    # wrong r
            (kp.public.encoded, der_encode_sig(r, n - s), msg),    # s' = n-s
        ]
        out = self._run(rows)
        assert out[0] is True
        assert out[1:5] == [False] * 4
        # row 5 is a different signature; row 6 (low/high-s twin) validity
        # must MATCH the host oracle exactly (checked in _run), whatever
        # the canonicalisation policy.
        assert out[5] is False

    def test_der_malformation_classes(self, fixture):
        kp, msg, good = fixture
        from corda_tpu.core.crypto.secp_math import der_decode_sig

        r, s = der_decode_sig(good)

        def raw_der(parts: bytes) -> bytes:
            return b"\x30" + bytes([len(parts)]) + parts

        def int_der(v: bytes) -> bytes:
            return b"\x02" + bytes([len(v)]) + v

        r_b = r.to_bytes(32, "big")
        s_b = s.to_bytes(32, "big")
        rows = [
            (kp.public.encoded, good, msg),
            (kp.public.encoded, good + b"\x00", msg),            # trailing junk
            (kp.public.encoded, good[:-1], msg),                 # truncated
            (kp.public.encoded, raw_der(int_der(r_b)), msg),     # missing s
            (kp.public.encoded, b"", msg),                       # empty
            (kp.public.encoded, b"\x31" + good[1:], msg),        # wrong tag
            (kp.public.encoded, raw_der(int_der(b"") + int_der(s_b)), msg),  # empty int
        ]
        out = self._run(rows)
        assert out[0] is True and not any(out[1:])

    def test_public_key_classes(self, fixture):
        kp, msg, good = fixture
        curve = SECP256K1
        # a valid point that is NOT the signer's key
        other = crypto.generate_keypair(ECDSA_SECP256K1_SHA256)
        # x >= p (invalid field element, compressed)
        bad_x = b"\x03" + (curve.p + 1).to_bytes(32, "big")
        # uncompressed point not on the curve
        not_on_curve = b"\x04" + (5).to_bytes(32, "big") + (5).to_bytes(32, "big")
        rows = [
            (kp.public.encoded, good, msg),
            (other.public.encoded, good, msg),
            (bad_x, good, msg),
            (not_on_curve, good, msg),
            (b"\x00", good, msg),          # point at infinity encoding
            (b"", good, msg),               # empty key
        ]
        out = self._run(rows)
        assert out[0] is True and not any(out[1:])


class TestPallasCore:
    """The Pallas ECDSA kernel's math core run on CPU with array-backed
    accessors must agree with the host oracle (same pattern as
    tests/test_ops_ed25519.py TestPallasCore)."""

    @pytest.mark.heavy_compile
    @pytest.mark.parametrize("curve_name", ["secp256k1", "secp256r1"])
    def test_verify_core_off_tpu(self, curve_name):
        import jax.numpy as jnp
        from jax import lax

        from corda_tpu.core.crypto import secp_math
        from corda_tpu.ops import ecdsa_batch, ecdsa_pallas

        curve = (
            secp_math.SECP256K1 if curve_name == "secp256k1"
            else secp_math.SECP256R1
        )
        width = 8
        rng = np.random.default_rng(11)
        pubs, sigs, msgs, expect = [], [], [], []
        for i in range(width):
            priv = int.from_bytes(rng.bytes(32), "big") % (curve.n - 1) + 1
            pub = curve.mul(priv, curve.g)
            msg = rng.bytes(40)
            r, s = secp_math.ecdsa_sign(curve, priv, msg)
            sig = secp_math.der_encode_sig(r, s)
            if i == 1:
                msg = msg + b"!"          # digest mismatch
            elif i == 2:
                other = curve.mul(priv + 1, curve.g)
                pub = other               # wrong key
            pubs.append(curve.encode_point(pub))
            sigs.append(sig)
            msgs.append(msg)
            pt = curve.decode_point(pubs[-1])
            rr, ss = secp_math.der_decode_sig(sig)
            expect.append(
                secp_math.ecdsa_verify(curve, pt, msg, rr, ss)
            )
        kwargs, _ = ecdsa_batch.prepare_batch(
            curve_name, pubs, sigs, msgs, pad_to=width
        )

        table = {}
        idx_rows = {}
        stacked = {}

        def read_idx(t):
            if "idx" not in stacked:
                stacked["idx"] = jnp.concatenate(
                    [idx_rows[k] for k in range(128)], axis=0
                )
            return lax.dynamic_slice_in_dim(stacked["idx"], t, 1, axis=0)

        mask = ecdsa_pallas._verify_core(
            curve_name,
            width,
            jnp.asarray(np.asarray(kwargs["qx"]).T),
            jnp.asarray(np.asarray(kwargs["qy"]).T),
            jnp.asarray(np.asarray(kwargs["u1_words"]).T),
            jnp.asarray(np.asarray(kwargs["u2_words"]).T),
            jnp.asarray(np.asarray(kwargs["r_cmp"]).T),
            jnp.asarray(np.asarray(kwargs["ok"])[None, :].astype(np.uint32)),
            write_table=table.__setitem__,
            read_table=table.__getitem__,
            write_idx=idx_rows.__setitem__,
            read_idx=read_idx,
        )
        got = [bool(v) for v in np.asarray(mask)[0]]
        assert got == expect
        assert got[0] is True and got[1] is False and got[2] is False


def test_self_check_vectors_match_host_oracle():
    """The ECDSA Pallas self-check's known-answer vectors must agree with
    the host oracle (they gate the TPU kernel's verdicts at runtime)."""
    from corda_tpu.core.crypto import secp_math
    from corda_tpu.ops import ecdsa_batch

    pubs, sigs, msgs, expect = ecdsa_batch._self_check_vectors("secp256k1")
    _f, _a, curve = ecdsa_batch._CURVES["secp256k1"]
    got = []
    for p_, s_, m_ in zip(pubs, sigs, msgs):
        try:
            r, sv = secp_math.der_decode_sig(s_)
            pt = curve.decode_point(p_)
            got.append(secp_math.ecdsa_verify(curve, pt, m_, r, sv))
        except Exception:
            got.append(False)
    assert got == expect == [True] * 4 + [False] * 4


@pytest.mark.heavy_compile
def test_ecdsa_kernel_lowers_for_tpu():
    """jax.export TPU cross-lowering of the ECDSA Pallas kernel (~3 min:
    the trace alone is large). Guards against reintroducing primitives
    Mosaic cannot lower (dynamic_slice in pow_const was caught here)."""
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    from corda_tpu.ops import ecdsa_pallas

    BLK = ecdsa_pallas.BLK
    args = (
        jnp.zeros((16, BLK), jnp.uint32), jnp.zeros((16, BLK), jnp.uint32),
        jnp.zeros((8, BLK), jnp.uint32), jnp.zeros((8, BLK), jnp.uint32),
        jnp.zeros((16, BLK), jnp.uint32), jnp.zeros((1, BLK), jnp.uint32),
    )
    fn = jax.jit(
        lambda *a: ecdsa_pallas.verify_kernel_pallas("secp256k1", *a)
    )
    jexport.export(fn, platforms=["tpu"])(*args)

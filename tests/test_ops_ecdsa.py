"""ECDSA batch kernel tests (differential vs the host oracle).

Small batches (pad 8) so each curve's 256-bit ladder compiles once; the
compile dominates runtime on the CPU CI backend.
"""
import numpy as np
import pytest

from corda_tpu.core.crypto import (
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    crypto,
)
from corda_tpu.core.crypto.secp_math import SECP256K1, der_encode_sig, ecdsa_sign
from corda_tpu.ops import ecdsa_batch

CURVES = [
    (ECDSA_SECP256K1_SHA256, "secp256k1"),
    (ECDSA_SECP256R1_SHA256, "secp256r1"),
]


@pytest.mark.parametrize("scheme,cname", CURVES)
def test_valid_and_forged_batch(scheme, cname):
    pubs, sigs, msgs = [], [], []
    for i in range(8):
        kp = crypto.generate_keypair(scheme)
        m = b"ecdsa message %d" % i
        pubs.append(kp.public.encoded)
        sigs.append(crypto.do_sign(kp.private, m))
        msgs.append(m)
    msgs[2] = b"forged content"       # digest mismatch
    sigs[5] = sigs[4]                 # signature for another key/message
    out = ecdsa_batch.verify_batch(cname, pubs, sigs, msgs)
    expected = [True, True, False, True, True, False, True, True]
    assert out == expected
    # differential: host oracle agrees on every row
    from corda_tpu.core.crypto.keys import SchemePublicKey

    host = [
        crypto.is_valid(
            SchemePublicKey(scheme.scheme_code_name, pubs[i]), sigs[i], msgs[i]
        )
        for i in range(8)
    ]
    assert host == expected


def test_malformed_rows_are_false_not_errors():
    kp = crypto.generate_keypair(ECDSA_SECP256K1_SHA256)
    m = b"x"
    good = (kp.public.encoded, crypto.do_sign(kp.private, m), m)
    rows = [
        good,
        (b"\x02" + b"\xff" * 32, good[1], m),   # x not on curve
        (good[0], b"\x30\x02\x01\x01", m),      # truncated DER
        (good[0], der_encode_sig(0, 5), m),     # r = 0
        (good[0], der_encode_sig(SECP256K1.n, 5), m),  # r = n
    ]
    out = ecdsa_batch.verify_batch(
        "secp256k1",
        [r[0] for r in rows], [r[1] for r in rows], [r[2] for r in rows],
    )
    assert out == [True, False, False, False, False]


def test_high_s_and_rfc6979_vectors():
    # deterministic signing: same (key, msg) -> same sig; kernel verifies it
    curve = SECP256K1
    priv = 0xC9AFA9D845BA75166B5C215767B1D6934E50C3DB36E89B127B8A622B120F6721
    pub = curve.mul(priv, curve.g)
    msg = b"sample"
    r, s = ecdsa_sign(curve, priv, msg)
    der = der_encode_sig(r, s)
    out = ecdsa_batch.verify_batch(
        "secp256k1",
        [curve.encode_point(pub)] * 2, [der, der], [msg, b"not sample"],
    )
    assert out == [True, False]

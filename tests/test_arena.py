"""Runtime arena-lifetime checker (CORDA_TPU_ARENA_CHECK; ISSUE 13).

Pins the checker's contract (docs/static-analysis.md):

  * disabled (the default): the receive plane is untouched — plain
    memoryview payloads, no tracker state, zero overhead;
  * armed: payloads are expiry-checked ArenaView proxies that decode,
    snapshot and compare normally WITHIN their drain cycle;
  * the next drain recycles: outstanding views expire, the arena is
    poisoned (0xDD), and any later touch raises a typed
    ArenaUseAfterDrainError carrying the view's creation stack plus an
    eventlog `arena` record;
  * the armed plane stays green across a realistic multi-cycle broker
    round trip (the false-positive guard the acceptance criteria name).
"""
import pytest

from corda_tpu.core.serialization import codec
from corda_tpu.messaging import arenacheck, pumpcore
from corda_tpu.messaging.arenacheck import (
    POISON,
    ArenaUseAfterDrainError,
    ArenaView,
)
from corda_tpu.messaging.broker import Broker
from corda_tpu.messaging.net import BrokerServer, RemoteBroker
from corda_tpu.utils import eventlog


@pytest.fixture
def armed():
    arenacheck.enable(True)
    try:
        yield
    finally:
        arenacheck.enable(False)


@pytest.fixture
def rig():
    broker = Broker()
    broker.create_queue("q")
    server = BrokerServer(broker).start()
    remote = RemoteBroker(server.host, server.port)
    try:
        yield broker, remote
    finally:
        remote.close()
        server.stop()


def _drain(consumer, broker, n, tag):
    for i in range(n):
        broker.send("q", codec.serialize({"tag": tag, "i": i}), {"h": tag})
    return [consumer.receive(timeout=2) for _ in range(n)]


class TestDisabled:
    def test_zero_state_when_off(self, rig):
        broker, remote = rig
        assert not arenacheck.enabled()
        consumer = remote.create_consumer("q")
        assert consumer._arena is None
        (msg,) = _drain(consumer, broker, 1, "off")
        assert isinstance(msg.payload, memoryview)
        assert not isinstance(msg.payload, ArenaView)
        assert codec.deserialize(msg.payload) == {"tag": "off", "i": 0}
        consumer.close()

    def test_arming_is_per_consumer_creation(self, rig):
        """The zero-overhead contract: a consumer created BEFORE arming
        carries no checker state at all."""
        broker, remote = rig
        before = remote.create_consumer("q")
        arenacheck.enable(True)
        try:
            after = RemoteBroker(
                remote.host, remote.port
            ).create_consumer("q")
            assert before._arena is None
            assert after._arena is not None
        finally:
            arenacheck.enable(False)
            after.close()
            before.close()


class TestArmedWithinCycle:
    def test_views_behave_bytes_like(self, armed, rig):
        broker, remote = rig
        consumer = remote.create_consumer("q")
        msgs = _drain(consumer, broker, 3, "a")
        payload = msgs[0].payload
        assert isinstance(payload, ArenaView)
        raw = bytes(payload)
        assert raw.startswith(codec._MAGIC)
        assert len(payload) == len(raw)
        assert payload == raw and payload != raw + b"x"
        assert payload[0] == raw[0]
        assert bytes(payload[1:4]) == raw[1:4]
        assert list(iter(payload)) == list(raw)
        assert payload.hex() == raw.hex()
        assert payload.tobytes() == raw
        # codec decodes through the unwrap seam, single and batch
        assert codec.deserialize(payload) == {"tag": "a", "i": 0}
        assert codec.deserialize_many(
            [m.payload for m in msgs]
        ) == [{"tag": "a", "i": i} for i in range(3)]
        consumer.close()

    def test_reframe_through_pump_within_cycle(self, armed, rig):
        broker, remote = rig
        consumer = remote.create_consumer("q")
        (msg,) = _drain(consumer, broker, 1, "rf")
        body = pumpcore.frame_send_many(
            [("q2", msg.payload, dict(msg.headers))], 11
        )
        (queue, payload, headers) = pumpcore.parse_send_many(body)[0]
        assert queue == "q2" and bytes(payload) == bytes(msg.payload)
        consumer.close()


class TestUseAfterDrain:
    def test_typed_error_with_creation_stack(self, armed, rig):
        broker, remote = rig
        consumer = remote.create_consumer("q")
        (held,) = _drain(consumer, broker, 1, "old")
        stale = held.payload
        assert codec.deserialize(stale) == {"tag": "old", "i": 0}
        # the next drain recycles the arena
        (fresh,) = _drain(consumer, broker, 1, "new")
        assert codec.deserialize(fresh.payload) == {"tag": "new", "i": 0}
        before = arenacheck.meta()["violations"]
        with pytest.raises(ArenaUseAfterDrainError) as ei:
            codec.deserialize(stale)
        assert "use" in str(ei.value) and "drain" in str(ei.value)
        assert ei.value.created_stack.strip(), "creation stack missing"
        assert "receive" in ei.value.created_stack or "track" in \
            ei.value.created_stack
        assert arenacheck.meta()["violations"] == before + 1
        # every bytes-like touch is checked, not just the codec seam
        for op in (lambda: bytes(stale), lambda: len(stale),
                   lambda: stale[0], lambda: stale == b"x",
                   lambda: list(iter(stale)), lambda: stale.hex()):
            with pytest.raises(ArenaUseAfterDrainError):
                op()
        # and the re-framing seam refuses the stale view too
        with pytest.raises(ArenaUseAfterDrainError):
            pumpcore.frame_send_many([("q2", stale, {})], 11)
        consumer.close()

    def test_eventlog_arena_record(self, armed, rig):
        broker, remote = rig
        consumer = remote.create_consumer("q")
        (held,) = _drain(consumer, broker, 1, "ev")
        stale = held.payload
        _drain(consumer, broker, 1, "ev2")
        log = eventlog.get_event_log()
        base = len(log.records(component="arena"))
        with pytest.raises(ArenaUseAfterDrainError):
            bytes(stale)
        recs = log.records(component="arena")
        assert len(recs) == base + 1
        assert recs[-1]["level"] == "error"
        assert "use-after-drain" in recs[-1]["message"]
        consumer.close()

    def test_arena_poisoned_on_recycle(self, armed, rig):
        """A raw memoryview that ESCAPED the proxy (via the unwrap seam)
        must read poison after recycle, never silently-valid stale
        bytes."""
        broker, remote = rig
        consumer = remote.create_consumer("q")
        (held,) = _drain(consumer, broker, 1, "p")
        raw = held.payload._arena_unwrap()  # within-cycle: legal
        assert bytes(raw).startswith(codec._MAGIC)
        _drain(consumer, broker, 1, "p2")
        assert set(bytes(raw)) == {POISON}
        consumer.close()

    def test_snapshot_before_drain_survives(self, armed, rig):
        broker, remote = rig
        consumer = remote.create_consumer("q")
        (held,) = _drain(consumer, broker, 1, "s")
        snapshot = bytes(held.payload)  # the documented discipline
        _drain(consumer, broker, 1, "s2")
        assert codec.deserialize(snapshot) == {"tag": "s", "i": 0}
        consumer.close()

    def test_subslice_expires_with_parent(self, armed, rig):
        broker, remote = rig
        consumer = remote.create_consumer("q")
        (held,) = _drain(consumer, broker, 1, "sub")
        sub = held.payload[1:5]
        assert isinstance(sub, ArenaView)
        _drain(consumer, broker, 1, "sub2")
        with pytest.raises(ArenaUseAfterDrainError):
            bytes(sub)
        consumer.close()


class TestArmedSuiteGreen:
    def test_multi_cycle_traffic_stays_green(self, armed, rig):
        """The false-positive guard: drain -> decode -> (snapshot where
        the contract says so) across many cycles never trips the
        checker, and the counters show it was actually armed."""
        broker, remote = rig
        consumer = remote.create_consumer("q")
        before = arenacheck.meta()
        for cycle in range(8):
            msgs = _drain(consumer, broker, 4, f"c{cycle}")
            decoded = codec.deserialize_many([m.payload for m in msgs])
            assert [d["i"] for d in decoded] == list(range(4))
            for m in msgs:
                consumer.ack(m)
        after = arenacheck.meta()
        assert after["violations"] == before["violations"]
        assert after["cycles"] >= before["cycles"] + 8
        assert after["views"] >= before["views"] + 32
        assert after["poisoned_bytes"] > before["poisoned_bytes"]
        consumer.close()


class TestTrackerUnit:
    def test_cycle_mechanics_without_sockets(self, armed):
        tr = arenacheck.tracker("unit")
        arena = tr.new_cycle(b"hello world")
        assert isinstance(arena, bytearray)
        view = tr.track(memoryview(arena)[0:5])
        assert bytes(view) == b"hello"
        arena2 = tr.new_cycle(b"second")
        assert set(arena) == {POISON}  # old arena poisoned
        with pytest.raises(ArenaUseAfterDrainError):
            bytes(view)
        v2 = tr.track(memoryview(arena2)[0:3])
        assert bytes(v2) == b"sec"
        assert tr.cycle == 2

    def test_repr_marks_expired(self, armed):
        tr = arenacheck.tracker("r")
        v = tr.track(memoryview(tr.new_cycle(b"x")))
        assert "EXPIRED" not in repr(v)
        tr.recycle()
        assert "EXPIRED" in repr(v)  # repr itself must not raise

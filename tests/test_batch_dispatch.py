"""Mixed-scheme batch dispatch (BASELINE.md 'mixed-scheme batch' config):
verify_batch buckets by scheme and returns positionally-correct verdicts
regardless of which bucket (device kernel or host) handled each item."""
import pytest

from corda_tpu.core.crypto import (
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
    RSA_SHA256,
    crypto,
)
from corda_tpu.core.crypto import batch as crypto_batch


def _items(schemes, tamper_idx=()):
    items = []
    for i, scheme in enumerate(schemes):
        kp = crypto.generate_keypair(scheme)
        content = b"mixed %d" % i
        sig = crypto.do_sign(kp.private, content)
        if i in tamper_idx:
            content = b"tampered %d" % i
        items.append((kp.public, sig, content))
    return items


@pytest.mark.skipif(
    not crypto.OPENSSL_AVAILABLE,
    reason="RSA needs the 'cryptography' package",
)
def test_mixed_scheme_host_path():
    schemes = [
        EDDSA_ED25519_SHA512, ECDSA_SECP256K1_SHA256,
        ECDSA_SECP256R1_SHA256, RSA_SHA256, EDDSA_ED25519_SHA512,
    ]
    items = _items(schemes, tamper_idx={1, 4})
    out = crypto_batch.verify_batch(items)
    assert out == [True, False, True, True, False]


def test_ed25519_bucket_hits_device_kernel(monkeypatch):
    monkeypatch.setattr(crypto_batch, "DISPATCH", "device")
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 4)
    calls = {}
    from corda_tpu import ops

    real = ops.ed25519_verify_batch

    def spy(*a, **k):
        calls["hit"] = True
        return real(*a, **k)

    monkeypatch.setattr(ops, "ed25519_verify_batch", spy)
    items = _items([EDDSA_ED25519_SHA512] * 5, tamper_idx={2})
    out = crypto_batch.verify_batch(items)
    assert out == [True, True, False, True, True]
    assert calls.get("hit")


def _composite_item(n_leaves=3, threshold=2, sign_with=None, tamper=False):
    """One (CompositeKey, serialized sigs, content) item with ed25519 leaves."""
    from corda_tpu.core.crypto.composite import (
        CompositeKey,
        CompositeSignaturesWithKeys,
    )

    kps = [crypto.generate_keypair(EDDSA_ED25519_SHA512) for _ in range(n_leaves)]
    builder = CompositeKey.Builder()
    for kp in kps:
        builder.add_key(kp.public)
    ckey = builder.build(threshold)
    content = b"composite batch content"
    signers = kps if sign_with is None else [kps[i] for i in sign_with]
    pairs = [(kp.public, crypto.do_sign(kp.private, content)) for kp in signers]
    if tamper and pairs:
        pub, _ = pairs[0]
        pairs[0] = (pub, b"\x00" * 64)
    return ckey, CompositeSignaturesWithKeys(tuple(pairs)).serialize(), content


def test_composite_leaves_ride_device_bitmask(monkeypatch):
    """BASELINE.md multi-sig config: composite constituents are flattened
    into the scheme buckets and the threshold tree evaluates over the
    device kernel's bitmask."""
    monkeypatch.setattr(crypto_batch, "DISPATCH", "device")
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 4)
    calls = {"n": 0}
    from corda_tpu import ops

    real = ops.ed25519_verify_batch

    def spy(pubs, *a, **k):
        calls["n"] = len(pubs)
        return real(pubs, *a, **k)

    monkeypatch.setattr(ops, "ed25519_verify_batch", spy)
    good = _composite_item(n_leaves=3, threshold=2)
    plain = _items([EDDSA_ED25519_SHA512] * 2, tamper_idx={1})
    out = crypto_batch.verify_batch([plain[0], good, plain[1]])
    assert out == [True, True, False]
    # 3 composite leaves + 2 plain sigs all rode one device bucket
    assert calls["n"] == 5


def test_composite_semantics_match_host_path():
    """Flattened evaluation must agree with CompositeKey.verify_composite
    for: all-signed, threshold-met subset, below-threshold subset, one
    invalid constituent, malformed blob."""
    cases = [
        _composite_item(),                                  # all 3 sign
        _composite_item(sign_with=[0, 2]),                  # 2 of 3: meets
        _composite_item(sign_with=[1]),                     # 1 of 3: below
        _composite_item(tamper=True),                       # invalid leaf
    ]
    items = [(k, s, c) for k, s, c in cases]
    items.append((cases[0][0], b"not a composite blob", cases[0][2]))
    out = crypto_batch.verify_batch(items)
    host = [crypto.is_valid(k, s, c) for k, s, c in items]
    assert out == host == [True, True, False, False, False]


def test_mesh_failure_falls_back_to_single_device(monkeypatch):
    """A mesh-path failure (e.g. Pallas-under-shard_map lowering on real
    pods) must fall through to the single-device path, not sink the
    whole verification batch."""
    from corda_tpu.parallel import mesh as mesh_mod

    def boom(*a, **k):
        raise RuntimeError("mesh lowering failed (simulated)")

    monkeypatch.setattr(mesh_mod, "shard_verify", boom)
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 4)
    items = _items([EDDSA_ED25519_SHA512] * 6, tamper_idx={3})
    crypto_batch.configure_mesh(object(), min_batch=4)  # any truthy mesh
    try:
        out = crypto_batch.verify_batch(items)
    finally:
        crypto_batch.configure_mesh(None)
    assert out == [True, True, True, False, True, True]


def test_small_buckets_stay_on_host(monkeypatch):
    from corda_tpu import ops

    def boom(*a, **k):
        raise AssertionError("device kernel must not run for tiny buckets")

    monkeypatch.setattr(ops, "ed25519_verify_batch", boom)
    monkeypatch.setattr(ops, "ecdsa_verify_batch", boom)
    items = _items([EDDSA_ED25519_SHA512, ECDSA_SECP256K1_SHA256])
    assert crypto_batch.verify_batch(items) == [True, True]


def test_cpu_backend_routes_large_buckets_to_host(monkeypatch):
    """The backend-aware dispatch policy (VERDICT r3 #2): on a CPU-only
    backend even device-kernel-sized buckets must take the host OpenSSL
    path — the portable XLA kernel is ~200x slower there."""
    from corda_tpu import ops

    def boom(*a, **k):
        raise AssertionError(
            "device kernel must not run when the backend resolves to CPU"
        )

    monkeypatch.setattr(ops, "ed25519_verify_batch", boom)
    monkeypatch.setattr(ops, "ecdsa_verify_batch", boom)
    monkeypatch.setattr(crypto_batch, "DISPATCH", "auto")
    monkeypatch.setattr(crypto_batch, "_resolved_backend", "cpu")
    items = _items(
        [EDDSA_ED25519_SHA512] * 40 + [ECDSA_SECP256K1_SHA256] * 40,
        tamper_idx={3, 77},
    )
    out = crypto_batch.verify_batch(items)
    assert out == [i not in {3, 77} for i in range(80)]


def test_accelerator_backend_uses_device_kernel(monkeypatch):
    """Same policy, other side: an accelerator backend keeps the device
    kernels for large buckets."""
    from corda_tpu import ops

    calls = {}
    real = ops.ed25519_verify_batch

    def spy(*a, **k):
        calls["hit"] = True
        return real(*a, **k)

    monkeypatch.setattr(ops, "ed25519_verify_batch", spy)
    monkeypatch.setattr(crypto_batch, "DISPATCH", "auto")
    monkeypatch.setattr(crypto_batch, "_resolved_backend", "tpu")
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 4)
    items = _items([EDDSA_ED25519_SHA512] * 5, tamper_idx={2})
    assert crypto_batch.verify_batch(items) == [True, True, False, True, True]
    assert calls.get("hit")


def test_dispatch_host_override(monkeypatch):
    from corda_tpu import ops

    def boom(*a, **k):
        raise AssertionError("CORDA_TPU_DISPATCH=host must disable kernels")

    monkeypatch.setattr(ops, "ed25519_verify_batch", boom)
    monkeypatch.setattr(crypto_batch, "DISPATCH", "host")
    monkeypatch.setattr(crypto_batch, "_resolved_backend", "tpu")
    items = _items([EDDSA_ED25519_SHA512] * 40, tamper_idx={1})
    assert crypto_batch.verify_batch(items) == [i != 1 for i in range(40)]


def test_host_thread_pool_path(monkeypatch):
    """The pooled host path returns positionally-correct verdicts (the
    strided chunking must not scramble rows)."""
    import os as _os

    monkeypatch.setattr(crypto_batch, "DISPATCH", "host")
    monkeypatch.setattr(crypto_batch, "_HOST_POOL_MIN", 8)
    monkeypatch.setattr(_os, "cpu_count", lambda: 4)
    items = _items([EDDSA_ED25519_SHA512] * 24, tamper_idx={0, 7, 23})
    out = crypto_batch.verify_batch(items)
    assert out == [i not in {0, 7, 23} for i in range(24)]


def test_undersized_ed25519_bucket_on_device_avoids_cofactored_msm(monkeypatch):
    """Advisor (r4, high): the verification rule must be ONE rule per
    deployment. Device deployments verify cofactorless (device kernels +
    OpenSSL loop); routing an undersized ed25519 bucket to the cofactored
    native MSM would make acceptance of a torsion-component signature
    depend on how the batcher grouped it — splitting notary replicas."""
    from corda_tpu.core.crypto import host_batch

    def msm_boom(*a, **k):  # the cofactored path must NOT run
        raise AssertionError(
            "cofactored MSM used on a device deployment (rule split)"
        )

    monkeypatch.setattr(crypto_batch, "DISPATCH", "device")
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 32)
    monkeypatch.setattr(host_batch, "verify_batch_host", msm_boom)
    items = _items([EDDSA_ED25519_SHA512] * 5, tamper_idx={3})
    out = crypto_batch.verify_batch(items)
    assert out == [True, True, True, False, True]


def test_cpu_deployment_routes_every_ed25519_size_to_msm(monkeypatch):
    """The complementary invariant: CPU deployments apply the cofactored
    ZIP-215 rule at EVERY bucket size (the MSM handles n=1 through n=4k),
    so no size threshold flips the rule there either."""
    from corda_tpu.core.crypto import host_batch

    if not host_batch.available():
        pytest.skip("native MSM extension unavailable")
    calls = []
    real = host_batch.verify_batch_host

    def spy(rows):
        calls.append(len(rows))
        return real(rows)

    monkeypatch.setattr(crypto_batch, "DISPATCH", "host")
    monkeypatch.setattr(host_batch, "verify_batch_host", spy)
    for n in (1, 2, 5):
        items = _items([EDDSA_ED25519_SHA512] * n)
        assert crypto_batch.verify_batch(items) == [True] * n
    assert calls == [1, 2, 5]


def test_rule_stays_pinned_across_mesh_failure(monkeypatch):
    """Code-review finding (r5): on a CPU backend with a configured mesh,
    the first mesh failure latches _mesh_failed_once and flips
    _use_device_kernels() False mid-process. The ACCEPTANCE RULE must not
    flip with the engine: a process that started cofactorless must route
    later ed25519 rows to the cofactorless OpenSSL loop, never to the
    cofactored MSM."""
    from corda_tpu.core.crypto import host_batch
    from corda_tpu.parallel import mesh as mesh_mod

    def msm_boom(*a, **k):
        raise AssertionError("cofactored MSM after a cofactorless pin")

    def mesh_boom(*a, **k):
        raise RuntimeError("mesh lowering failed")

    monkeypatch.setattr(crypto_batch, "DISPATCH", "auto")
    monkeypatch.setattr(crypto_batch, "_resolved_backend", "cpu")
    monkeypatch.setattr(crypto_batch, "_MESH", object())
    monkeypatch.setattr(crypto_batch, "_mesh_failed_once", False)
    monkeypatch.setattr(crypto_batch, "_pinned_rule", None)
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 4)
    monkeypatch.setattr(crypto_batch, "MESH_MIN_BATCH", 4)
    monkeypatch.setattr(mesh_mod, "shard_verify", mesh_boom)
    monkeypatch.setattr(host_batch, "verify_batch_host", msm_boom)

    items = _items([EDDSA_ED25519_SHA512] * 5, tamper_idx={1})
    # first dispatch: mesh configured -> pin cofactorless; the mesh path
    # throws, latches _mesh_failed_once, falls back to single-device
    out = crypto_batch.verify_batch(items)
    assert out == [True, False, True, True, True]
    assert crypto_batch._mesh_failed_once
    assert crypto_batch._pinned_rule == "cofactorless"
    # second dispatch: engine flipped to host — the rule must not; the
    # MSM boom above fails the test if the cofactored path runs
    out2 = crypto_batch.verify_batch(items)
    assert out2 == [True, False, True, True, True]


def test_pin_reflects_engine_availability(monkeypatch):
    """A replica whose native MSM is unavailable (failed build or
    CORDA_TPU_HOST_BATCH=0) verifies through the cofactorless OpenSSL
    loop — its pin must say so, not claim 'cofactored'."""
    from corda_tpu.core.crypto import host_batch

    monkeypatch.setattr(crypto_batch, "DISPATCH", "host")
    monkeypatch.setattr(crypto_batch, "_pinned_rule", None)
    monkeypatch.setattr(host_batch, "available", lambda: False)
    assert crypto_batch._ed25519_rule() == "cofactorless"

    monkeypatch.setattr(crypto_batch, "_pinned_rule", None)
    monkeypatch.setattr(host_batch, "available", lambda: True)
    assert crypto_batch._ed25519_rule() == "cofactored"


def test_mixed_ed25519_bls_batch_groups_by_scheme_id():
    """Satellite (round 12): submitted items are grouped by
    scheme_number_id before dispatch — a BLS group rides the host path
    next to the ed25519 bucket and every verdict stays positional."""
    from corda_tpu.core.crypto.schemes import BLS_BLS12381

    ed = _items([EDDSA_ED25519_SHA512] * 3, tamper_idx={1})
    bls_kp = crypto.generate_keypair(BLS_BLS12381)
    bls_sig = crypto.do_sign(bls_kp.private, b"bls vote")
    items = [
        ed[0],
        (bls_kp.public, bls_sig, b"bls vote"),
        ed[1],
        (bls_kp.public, bls_sig, b"tampered vote"),
        ed[2],
    ]
    assert crypto_batch.verify_batch(items) == [
        True, True, False, False, True,
    ]


def test_unregistered_scheme_degrades_per_group_not_per_batch():
    """An id this build has never heard of (a NEWER peer's scheme) must
    cost its OWN group a False verdict — before the scheme grouping one
    such row raised out of verify_batch and poisoned the whole batch."""
    from corda_tpu.core.crypto.keys import SchemePublicKey

    good = _items([EDDSA_ED25519_SHA512] * 2)
    future = (SchemePublicKey("SCHEME_FROM_THE_FUTURE", b"\x01" * 48),
              b"\x00" * 64, b"payload")
    out = crypto_batch.verify_batch([good[0], future, good[1]])
    assert out == [True, False, True]


def test_scheme_group_exception_degrades_only_that_group(monkeypatch):
    """A scheme whose host verify RAISES (half-landed implementation,
    broken native lib) fails its group closed; co-batched schemes keep
    their verdicts."""
    from corda_tpu.core.crypto import bls_math
    from corda_tpu.core.crypto.schemes import BLS_BLS12381

    ed = _items([EDDSA_ED25519_SHA512] * 2, tamper_idx={1})
    kp = crypto.generate_keypair(BLS_BLS12381)
    sig = crypto.do_sign(kp.private, b"m")

    def boom(*a, **k):
        raise RuntimeError("BLS backend exploded")

    monkeypatch.setattr(bls_math, "verify", boom)
    out = crypto_batch.verify_batch([ed[0], (kp.public, sig, b"m"), ed[1]])
    assert out == [True, False, False]


def test_backend_probe_uses_subprocess_when_unpinned(monkeypatch):
    """The hang-proofing path itself (review finding r5): when the
    process is NOT cpu-pinned, resolution must go through a subprocess
    (whose hang cannot poison this process's JAX state), propagate the
    parent's platform pin, and accept only plausible backend names."""
    import subprocess as sp
    import types

    calls = {}

    def fake_run(argv, capture_output, text, env, timeout):
        calls["env_platforms"] = env.get("JAX_PLATFORMS")
        calls["timeout"] = timeout
        return types.SimpleNamespace(
            stdout="some runtime banner line\ntpu\n", returncode=0
        )

    class _Cfg:
        jax_platforms = "axon,cpu"  # tunnel-backed: NOT pure cpu

    class _FakeJax:
        config = _Cfg()

    import sys as _sys

    monkeypatch.setitem(_sys.modules, "jax", _FakeJax())
    monkeypatch.setattr(sp, "run", fake_run)
    monkeypatch.setattr(crypto_batch, "_resolved_backend", None)
    assert crypto_batch._resolve_backend_without_hanging() == "tpu"
    assert calls["env_platforms"] == "axon,cpu"  # pin propagated

    # a hung probe (TimeoutExpired) latches the host paths
    def hang_run(*a, **k):
        raise sp.TimeoutExpired(cmd="jax", timeout=k.get("timeout", 0))

    monkeypatch.setattr(sp, "run", hang_run)
    assert crypto_batch._resolve_backend_without_hanging() == "cpu"

    # banner-only stdout (no plausible backend name) must not be
    # mistaken for a backend
    def garbage_run(argv, capture_output, text, env, timeout):
        return types.SimpleNamespace(
            stdout="W0000 something experimental!\n", returncode=0
        )

    monkeypatch.setattr(sp, "run", garbage_run)
    assert crypto_batch._resolve_backend_without_hanging() == "cpu"


def test_backend_probe_inline_when_cpu_pinned():
    """The suite runs cpu-pinned (conftest), so the inline path must
    resolve without any subprocess."""
    import subprocess as sp

    def boom(*a, **k):
        raise AssertionError("subprocess probe used on a cpu-pinned process")

    import unittest.mock as mock

    with mock.patch.object(sp, "run", boom):
        assert crypto_batch._resolve_backend_without_hanging() == "cpu"

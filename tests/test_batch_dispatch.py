"""Mixed-scheme batch dispatch (BASELINE.md 'mixed-scheme batch' config):
verify_batch buckets by scheme and returns positionally-correct verdicts
regardless of which bucket (device kernel or host) handled each item."""
import pytest

from corda_tpu.core.crypto import (
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
    RSA_SHA256,
    crypto,
)
from corda_tpu.core.crypto import batch as crypto_batch


def _items(schemes, tamper_idx=()):
    items = []
    for i, scheme in enumerate(schemes):
        kp = crypto.generate_keypair(scheme)
        content = b"mixed %d" % i
        sig = crypto.do_sign(kp.private, content)
        if i in tamper_idx:
            content = b"tampered %d" % i
        items.append((kp.public, sig, content))
    return items


def test_mixed_scheme_host_path():
    schemes = [
        EDDSA_ED25519_SHA512, ECDSA_SECP256K1_SHA256,
        ECDSA_SECP256R1_SHA256, RSA_SHA256, EDDSA_ED25519_SHA512,
    ]
    items = _items(schemes, tamper_idx={1, 4})
    out = crypto_batch.verify_batch(items)
    assert out == [True, False, True, True, False]


def test_ed25519_bucket_hits_device_kernel(monkeypatch):
    monkeypatch.setattr(crypto_batch, "MIN_DEVICE_BATCH", 4)
    calls = {}
    from corda_tpu import ops

    real = ops.ed25519_verify_batch

    def spy(*a, **k):
        calls["hit"] = True
        return real(*a, **k)

    monkeypatch.setattr(ops, "ed25519_verify_batch", spy)
    items = _items([EDDSA_ED25519_SHA512] * 5, tamper_idx={2})
    out = crypto_batch.verify_batch(items)
    assert out == [True, True, False, True, True]
    assert calls.get("hit")


def test_small_buckets_stay_on_host(monkeypatch):
    from corda_tpu import ops

    def boom(*a, **k):
        raise AssertionError("device kernel must not run for tiny buckets")

    monkeypatch.setattr(ops, "ed25519_verify_batch", boom)
    monkeypatch.setattr(ops, "ecdsa_verify_batch", boom)
    items = _items([EDDSA_ED25519_SHA512, ECDSA_SECP256K1_SHA256])
    assert crypto_batch.verify_batch(items) == [True, True]

"""Fast differential tests for the Pallas ECDSA kernel's math components.

The full-ladder differential tests (tests/test_ops_ecdsa.py TestPallasCore
and the secp256r1 XLA-kernel run) are XLA-CPU *compile*-dominated — 2-5
minutes each even on a warm persistent cache, because the win is capped by
~55s of tracing plus ~60s of executable deserialization per curve per
process (measured round 3).  They carry a `heavy_compile` marker and are
deselected by default; THIS file keeps every distinct piece of math under
fast default-on coverage:

  * `_RowField` (limbs-on-sublanes Montgomery field, ecdsa_pallas) —
    mul/add/sub/inv differential vs plain Python ints, both curves;
  * row-layout `_double` / `_add_general` vs the host curve oracle,
    including every degenerate case (infinity operands, doubling,
    inverse points), batched across lanes so ONE compile covers all;
  * the Shamir digit/table indexing used by `_verify_core`.

Cost is kept trivial by running the components EAGERLY (no jit):
XLA-CPU's pipeline costs minutes for these unrolled graphs even on a
warm persistent cache (measured: add+double 63s jitted/warm vs 3.9s
eager), while eager per-op dispatch at width 8 is seconds. Production
always runs these ops inside the jitted kernels; the differential
targets the math, which is identical either way.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from corda_tpu.core.crypto import secp_math
from corda_tpu.ops import ecdsa_pallas
from corda_tpu.ops.ecdsa_batch import _CURVES, _double
from corda_tpu.ops.ed25519_pallas import _cat, _const_col, _limbs
from corda_tpu.ops.field_secp import FIELD_K1, FIELD_R1

W = 8  # lane width for all tests

FIELDS = [("secp256k1", FIELD_K1), ("secp256r1", FIELD_R1)]


def _col_from_ints(values, field):
    """(16, W) Montgomery rows from W Python ints."""
    assert len(values) == W
    cols = [
        _const_col(_limbs((v * field.r_int) % field.p_int), 1)
        for v in values
    ]
    return jnp.concatenate(cols, axis=1)


def _ints_from_col(col, field):
    """W Python ints (standard domain) from (16, W) Montgomery rows."""
    arr = np.asarray(col)
    out = []
    rinv = pow(field.r_int, -1, field.p_int)
    for j in range(W):
        v = sum(int(arr[k, j]) << (16 * k) for k in range(16))
        out.append((v * rinv) % field.p_int)
    return out


@pytest.mark.parametrize("fname,field", FIELDS)
def test_rowfield_mul_add_sub_inv(fname, field):
    rf = ecdsa_pallas._RowField(field)
    rng = np.random.default_rng(3)
    a_int = [int.from_bytes(rng.bytes(32), "big") % field.p_int for _ in range(W)]
    b_int = [int.from_bytes(rng.bytes(32), "big") % field.p_int for _ in range(W)]
    # edge values in fixed lanes: 0, 1, p-1
    a_int[0], b_int[0] = 0, 0
    a_int[1], b_int[1] = field.p_int - 1, field.p_int - 1
    a_int[2], b_int[2] = 1, field.p_int - 1
    a = _col_from_ints(a_int, field)
    b = _col_from_ints(b_int, field)

    got_mul, got_add, got_sub, got_inv = (
        rf.mul(a, b), rf.add(a, b), rf.sub(a, b), rf.inv(a)
    )
    assert _ints_from_col(got_mul, field) == [
        (x * y) % field.p_int for x, y in zip(a_int, b_int)
    ]
    assert _ints_from_col(got_add, field) == [
        (x + y) % field.p_int for x, y in zip(a_int, b_int)
    ]
    assert _ints_from_col(got_sub, field) == [
        (x - y) % field.p_int for x, y in zip(a_int, b_int)
    ]
    exp_inv = [pow(x, -1, field.p_int) if x else 0 for x in a_int]
    # inv(0) = 0^(p-2) = 0 — the kernel relies on this to keep Z=0 rows inert
    assert _ints_from_col(got_inv, field) == exp_inv


@pytest.mark.parametrize("fname,field", FIELDS)
def test_rowfield_mul_fast_differential(fname, field):
    """The Mosaic-only live-row CIOS variant must agree with the dense
    formulation bit-for-bit (swapped in only while the TPU kernel body
    is traced — same switch as ed25519's _mul_fast)."""
    from corda_tpu.ops.ed25519_pallas import _fast_mul_trace

    rf = ecdsa_pallas._RowField(field)
    rng = np.random.default_rng(23)
    a_int = [int.from_bytes(rng.bytes(32), "big") % field.p_int
             for _ in range(W)]
    b_int = [int.from_bytes(rng.bytes(32), "big") % field.p_int
             for _ in range(W)]
    a_int[0], b_int[0] = field.p_int - 1, field.p_int - 1
    a, b = _col_from_ints(a_int, field), _col_from_ints(b_int, field)

    dense = rf.mul(a, b)
    with _fast_mul_trace():
        fast = rf.mul(a, b)
    assert np.array_equal(np.asarray(dense), np.asarray(fast))
    assert _ints_from_col(fast, field) == [
        (x * y) % field.p_int for x, y in zip(a_int, b_int)
    ]


@pytest.mark.parametrize("fname,field", FIELDS)
def test_rowfield_predicates(fname, field):
    rf = ecdsa_pallas._RowField(field)
    vals = [0, 1, field.p_int - 1, 7, 0, 7, 2, 3]
    a = _col_from_ints(vals, field)
    b = _col_from_ints([0, 1, 5, 7, 3, 0, 2, field.p_int - 3], field)
    is_zero, eq = rf.is_zero(a), rf.eq(a, b)
    assert [bool(v) for v in np.asarray(is_zero)[0]] == [
        v == 0 for v in vals
    ]
    assert [bool(v) for v in np.asarray(eq)[0]] == [
        True, True, False, True, False, False, True, False,
    ]


@pytest.mark.parametrize("cname", ["secp256k1", "secp256r1"])
def test_row_point_ops_vs_host_oracle(cname):
    """One jitted (double, general-add) pass whose W lanes are W distinct
    cases: generic adds, P+inf, inf+P, P+P (H=0,r=0), P+(-P) (H=0,r!=0).
    Differential vs the host curve oracle incl. r1's a=-3 doubling term."""
    field, a_int, curve = _CURVES[cname]
    rf = ecdsa_pallas._RowField(field)
    rng = np.random.default_rng(5)

    pts1, pts2 = [], []
    for lane in range(W):
        k1 = int.from_bytes(rng.bytes(32), "big") % (curve.n - 1) + 1
        k2 = int.from_bytes(rng.bytes(32), "big") % (curve.n - 1) + 1
        p1 = curve.mul(k1, curve.g)
        p2 = curve.mul(k2, curve.g)
        if lane == 3:
            p2 = None            # P + inf
        elif lane == 4:
            p1 = None            # inf + P
        elif lane == 5:
            p2 = p1              # doubling through the general add
        elif lane == 6:
            p2 = (p1[0], (-p1[1]) % curve.p)  # inverse -> infinity
        pts1.append(p1)
        pts2.append(p2)

    def to_cols(pts):
        xs = [p[0] if p else 0 for p in pts]
        ys = [p[1] if p else 1 for p in pts]
        zs = [1 if p else 0 for p in pts]
        return (
            _col_from_ints(xs, field),
            _col_from_ints(ys, field),
            _col_from_ints(zs, field),
        )

    X1, Y1, Z1 = to_cols(pts1)
    X2, Y2, Z2 = to_cols(pts2)
    a_mont = rf.mont_const(a_int % field.p_int, W)

    AX, AY, AZ = ecdsa_pallas._add_general(rf, a_mont, X1, Y1, Z1, X2, Y2, Z2)
    DX, DY, DZ = _double(rf, a_mont, X1, Y1, Z1)

    def affine(xc, yc, zc, lane):
        x = _ints_from_col(xc, field)[lane]
        y = _ints_from_col(yc, field)[lane]
        z = _ints_from_col(zc, field)[lane]
        if z == 0:
            return None
        zi = pow(z, -1, field.p_int)
        return (x * zi * zi) % field.p_int, (y * zi * zi * zi) % field.p_int

    for lane in range(W):
        expected_add = curve.add(pts1[lane], pts2[lane])
        expected_dbl = curve.add(pts1[lane], pts1[lane])
        assert affine(AX, AY, AZ, lane) == expected_add, (cname, lane)
        assert affine(DX, DY, DZ, lane) == expected_dbl, (cname, "dbl", lane)


def test_shamir_digit_indexing():
    """`_verify_core`'s digit rows (via the shared `shamir_digit_row`
    helper — the exact code the kernel runs) must walk the scalars
    MSB-digit first the way the ladder consumes them (t = 127 - i)."""
    rng = np.random.default_rng(9)
    u1 = int.from_bytes(rng.bytes(32), "big") >> 1
    u2 = int.from_bytes(rng.bytes(32), "big") >> 1

    def words(x):
        return jnp.asarray(
            [[(x >> (32 * k)) & 0xFFFFFFFF] for k in range(8)], jnp.uint32
        )

    u1w, u2w = words(u1), words(u2)
    # reconstruct both scalars from the digit stream and verify
    r1 = r2 = 0
    for t in range(127, -1, -1):
        d = int(np.asarray(ecdsa_pallas.shamir_digit_row(u1w, u2w, t))[0, 0])
        r1 = (r1 << 2) | (d & 3)
        r2 = (r2 << 2) | (d >> 2)
    assert r1 == u1 and r2 == u2

"""Performance observatory: the sampling profiler (utils/sampler.py),
quiesced/attested measurement windows (utils/quiesce.py), the /profile
and /opbudget ops routes, labelled Prometheus families, the
fingerprint-aware bench gate, and tools/profile_report.py.
"""
import hashlib
import importlib.util
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from corda_tpu.utils import quiesce, sampler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _busy_thread(name="busy-worker"):
    stop = threading.Event()

    def spin():
        h = b"x"
        while not stop.is_set():
            h = hashlib.sha256(h).digest()

    t = threading.Thread(target=spin, name=name, daemon=True)
    t.start()
    return stop, t


# ---------------------------------------------------------------------------
# the sampler
# ---------------------------------------------------------------------------

class TestSampler:
    def test_capture_attributes_a_busy_thread(self):
        stop, t = _busy_thread()
        try:
            # under heavy box load the GIL convoy can squeeze a 0.4 s
            # window down to a couple of ticks — retry with a longer
            # window rather than flaking (the attribution asserts below
            # need >= 3 /proc readings to see a CPU delta)
            for seconds in (0.4, 0.8, 1.6):
                res = sampler.capture(seconds=seconds, interval=0.01)
                if res["meta"]["ticks"] >= 3:
                    break
        finally:
            stop.set()
            t.join(timeout=5)
        meta = res["meta"]
        assert meta["ticks"] >= 3
        assert meta["profiler_cpu_s"] >= 0
        rows = {r["name"]: r for r in res["threads"]}
        busy = rows["busy-worker"]
        assert busy["samples"] > 0
        assert busy["cpu_s"] is not None and busy["cpu_s"] > 0
        # the spinner dominates the process's CPU share and shows
        # runnable, not waiting — the GIL-convoy table's core columns
        assert busy["cpu_share"] > 0.5
        assert busy["running"] >= busy["waiting"]
        # collapsed stacks carry the thread name prefix and reach the
        # spin function
        busy_stacks = [
            s for s in res["collapsed"] if s.startswith("busy-worker;")
        ]
        assert busy_stacks and any(":spin" in s for s in busy_stacks)
        # the sampler's own thread is flagged and excluded from stacks
        samplers = [r for r in res["threads"] if r["sampler"]]
        assert len(samplers) == 1
        assert not any(
            s.startswith(samplers[0]["name"] + ";")
            for s in res["collapsed"]
        )

    def test_single_capture_at_a_time(self):
        started = threading.Event()
        results = {}

        def long_capture():
            started.set()
            results["first"] = sampler.capture(seconds=0.6, interval=0.02)

        t = threading.Thread(target=long_capture)
        t.start()
        started.wait(5)
        time.sleep(0.05)
        with pytest.raises(sampler.CaptureBusyError):
            sampler.capture(seconds=0.1)
        t.join(timeout=10)
        assert results["first"]["meta"]["ticks"] > 0

    def test_collapsed_text_format(self):
        stop, t = _busy_thread()
        try:
            res = sampler.capture(seconds=0.2, interval=0.01)
        finally:
            stop.set()
            t.join(timeout=5)
        text = sampler.collapsed_text(res)
        line = text.splitlines()[0]
        stack, count = line.rsplit(" ", 1)
        assert ";" in stack and int(count) > 0

    def test_idle_means_no_sampler_state(self):
        # the <5% idle-overhead bound holds structurally: nothing runs
        # outside a capture
        assert sampler.active_captures() == 0
        assert not any(
            "sampler" in t.name.lower() for t in threading.enumerate()
        )


# ---------------------------------------------------------------------------
# quiesce + fingerprint
# ---------------------------------------------------------------------------

class TestQuiesce:
    def test_pause_resume_and_file_handshake(self, tmp_path, monkeypatch):
        path = str(tmp_path / "QUIESCE")
        monkeypatch.setenv("CORDA_TPU_QUIESCE_FILE", path)
        events = []
        quiesce.register(
            "t", lambda: events.append("pause"),
            lambda: events.append("resume"),
        )
        try:
            assert not quiesce.is_quiesced()
            with quiesce.quiesce(expected_s=60):
                assert quiesce.is_quiesced()
                assert quiesce.file_quiesced(path)
                with open(path) as fh:
                    rec = json.load(fh)
                assert rec["pid"] == os.getpid()
                assert rec["expires"] > time.time()
                # re-entrant: inner windows don't double-pause
                with quiesce.quiesce():
                    assert quiesce.is_quiesced()
                assert quiesce.is_quiesced()
                assert events == ["pause"]
            assert not quiesce.is_quiesced()
            assert not os.path.exists(path)
            assert events == ["pause", "resume"]
        finally:
            quiesce.unregister("t")

    def test_exit_never_deletes_another_holders_marker(self, tmp_path):
        # two benches overlapping cross-process: the one exiting first
        # must not delete the marker the other replaced it with — the
        # daemon would resume inside a still-open measurement window
        path = str(tmp_path / "QUIESCE")
        a = quiesce.quiesce(expected_s=60, path=path)
        a.__enter__()
        with open(path, "w") as fh:
            json.dump({"pid": 99999, "token": "other-proc",
                       "ts": time.time(), "expires": time.time() + 60}, fh)
        a.__exit__(None, None, None)
        assert os.path.exists(path)
        assert quiesce.file_quiesced(path)

    def test_expired_marker_is_ignored(self, tmp_path):
        path = str(tmp_path / "QUIESCE")
        with open(path, "w") as fh:
            json.dump({"pid": 1, "expires": time.time() - 5}, fh)
        assert not quiesce.file_quiesced(path)
        with open(path, "w") as fh:
            fh.write("garbage")
        assert not quiesce.file_quiesced(path)

    def test_hw_capture_daemon_honours_the_marker(self, tmp_path,
                                                  monkeypatch):
        spec = importlib.util.spec_from_file_location(
            "hw_capture", os.path.join(REPO, "tools", "hw_capture.py")
        )
        hw = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hw)
        # the daemon reads through the writer module's path resolution,
        # so the relocation override reaches BOTH sides of the handshake
        marker = str(tmp_path / "QUIESCE")
        monkeypatch.setenv("CORDA_TPU_QUIESCE_FILE", marker)
        assert not hw.quiesced()
        with quiesce.quiesce(expected_s=60):
            assert hw.quiesced()
        assert not hw.quiesced()

    def test_env_fingerprint_shape(self, tmp_path):
        fp = quiesce.env_fingerprint()
        for key in quiesce.FINGERPRINT_KEYS:
            assert key in fp
        assert fp["cpus"] == os.cpu_count()
        assert fp["quiesced"] is False
        with quiesce.quiesce(path=str(tmp_path / "QUIESCE")):
            assert quiesce.env_fingerprint()["quiesced"] is True
        # before the backend is initialized the fingerprint must report
        # "uninitialized" rather than initialize one; after a real
        # dispatch it reads the live answer
        import jax.numpy as jnp

        jnp.zeros(1).block_until_ready()
        assert quiesce.env_fingerprint()["backend"] == "cpu"

    def test_fingerprint_mismatch(self):
        fp = quiesce.env_fingerprint()
        assert quiesce.fingerprint_mismatch(fp, dict(fp)) == []
        diff = quiesce.fingerprint_mismatch(dict(fp, backend="tpu"), fp)
        assert diff == [{
            "key": "backend", "prev": "tpu", "cur": fp["backend"],
        }]
        # unknown fingerprints compare as no-mismatch (old artifacts
        # keep the gate's teeth)
        assert quiesce.fingerprint_mismatch(None, fp) == []


# ---------------------------------------------------------------------------
# the fingerprint-aware regression gate
# ---------------------------------------------------------------------------

class TestFingerprintGate:
    PREV = {
        "p50_notarise_ms": 20.0,
        "env_fingerprint": {
            "backend": "tpu", "device": "TPU v5e", "python": "3.10.16",
            "jax": "0.4.37", "numpy": "1.26", "platform": "Linux-x86_64",
            "cpus": 1,
        },
    }

    def _cur(self, backend="cpu"):
        fp = dict(self.PREV["env_fingerprint"], backend=backend,
                  device=None if backend == "cpu" else "TPU v5e",
                  cpus=2 if backend == "cpu" else 1)
        return {"p50_notarise_ms": 60.0, "env_fingerprint": fp}

    def test_cross_environment_regressions_demote_to_warnings(self):
        from corda_tpu.loadtest.gate import run_gate

        result = run_gate(self._cur("cpu"), self.PREV)
        assert result["ok"], result
        assert result["regressions"] == []
        assert result["warnings"] and (
            result["warnings"][0]["key"] == "p50_notarise_ms"
        )
        assert any(
            m["key"] == "backend" for m in result["fingerprint_mismatch"]
        )

    def test_same_environment_still_fails(self):
        from corda_tpu.loadtest.gate import run_gate

        cur = self._cur("tpu")
        cur["env_fingerprint"] = dict(self.PREV["env_fingerprint"])
        result = run_gate(cur, self.PREV)
        assert not result["ok"]
        assert result["regressions"] and result["warnings"] == []

    def test_missing_fingerprint_keeps_teeth(self):
        from corda_tpu.loadtest.gate import run_gate

        prev = {"p50_notarise_ms": 20.0}
        cur = {"p50_notarise_ms": 60.0}
        result = run_gate(cur, prev)
        assert not result["ok"]
        assert result["regressions"]

    def test_slo_bounds_stay_hard_across_environments(self):
        from corda_tpu.loadtest.gate import run_gate

        result = run_gate(
            self._cur("cpu"), self.PREV,
            slos={"p50_notarise_ms": {"max": 30.0}},
        )
        assert not result["ok"]
        assert result["slo_violations"]

    def test_bench_gate_cli_warns_not_fails(self, tmp_path):
        cur_file = tmp_path / "cur.json"
        prev_file = tmp_path / "prev.json"
        cur_file.write_text(json.dumps(self._cur("cpu")))
        prev_file.write_text(json.dumps({"parsed": self.PREV}))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--current", str(cur_file), "--baseline", str(prev_file)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "CROSS-ENV WARNING" in proc.stderr
        assert "ENV MISMATCH backend" in proc.stderr
        result = json.loads(proc.stdout)
        assert result["ok"] and result["warnings"]


# ---------------------------------------------------------------------------
# ops endpoint: /profile, /opbudget, labelled /metrics families
# ---------------------------------------------------------------------------

class TestOpsEndpoint:
    @pytest.fixture()
    def node_port(self):
        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        try:
            node = net.create_node("O=Observatory,L=London,C=GB",
                                   ops_port=0)
            yield node, node.ops_server.port
        finally:
            net.stop_nodes()

    def test_profile_endpoint_serves_capture(self, node_port):
        _node, port = node_port
        stop, t = _busy_thread("endpoint-busy")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/profile?seconds=0.3", timeout=15
            ) as resp:
                cap = json.loads(resp.read())
        finally:
            stop.set()
            t.join(timeout=5)
        assert cap["meta"]["ticks"] > 0
        assert cap["collapsed"], "no collapsed stacks"
        names = {row["name"] for row in cap["threads"]}
        assert "endpoint-busy" in names
        shares = [
            row["cpu_share"] for row in cap["threads"]
            if row["cpu_share"] is not None and not row["sampler"]
        ]
        assert shares and max(shares) > 0

    def test_profile_collapsed_format_and_bad_input(self, node_port):
        _node, port = node_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/profile?seconds=0.1&format=collapsed",
            timeout=15,
        ) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        for line in body.strip().splitlines():
            assert re.match(r".+ \d+$", line), line
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/profile?seconds=bogus", timeout=5
            )
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/profile?seconds=1e9", timeout=5
            )
        assert err.value.code == 400

    def test_opbudget_endpoint_cached_view(self, node_port):
        _node, port = node_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/opbudget", timeout=5
        ) as resp:
            body = json.loads(resp.read())
        # no compute requested: the route never traces (and never
        # imports jax through the package __init__ by itself) — it
        # serves whatever this process already counted
        assert "kernels" in body and "computed" in body
        if "corda_tpu.ops.opbudget" in sys.modules:
            from corda_tpu.ops import opbudget

            assert set(body["kernels"]) == set(opbudget.KERNEL_NAMES)

    def test_labelled_families_render_valid_prometheus(self, node_port):
        from corda_tpu.utils import profiling

        _node, port = node_port
        profiling.record_compile("ed25519.batch_shape", "4096")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        assert 'corda_tpu_jax_compile_count{bucket="4096"}' in body
        assert (
            'corda_tpu_kernel_op_budget_field_muls_per_sig'
            '{kernel="ed25519_pallas"}'
        ) in body
        for family in (
            "corda_tpu_profiler_captures",
            "corda_tpu_profiler_samples",
            "corda_tpu_profiler_active",
        ):
            assert f"\n{family} " in body, family
        # strict exposition validity + family uniqueness over the whole
        # scrape (labelled variants must MERGE into their base family)
        sample_re = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
            r'(\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+="[^"]*")*\})?'
            r" -?[0-9.eE+-]+$"
        )
        families = []
        for line in body.splitlines():
            if line.startswith("# TYPE "):
                families.append(line.split()[2])
                continue
            if line.startswith("#"):
                continue
            assert sample_re.match(line), f"bad sample line: {line}"
        assert len(families) == len(set(families)), "duplicate TYPE family"

    def test_rpc_node_profile(self, node_port):
        from corda_tpu.rpc.ops import CordaRPCOps

        node, _port = node_port
        ops = CordaRPCOps(node.services, node.smm)
        res = ops.node_profile(seconds=0.2)
        assert res["meta"]["ticks"] > 0
        assert res["threads"]

    def test_capture_emits_flight_recorder_event(self, node_port):
        from corda_tpu.utils.eventlog import get_event_log

        sampler.capture(seconds=0.05, interval=0.01)
        events = get_event_log().records(component="profiler", limit=5)
        assert any(
            e["message"] == "profile capture complete" for e in events
        )


# ---------------------------------------------------------------------------
# tools/profile_report.py
# ---------------------------------------------------------------------------

class TestProfileReport:
    def test_report_from_saved_capture(self, tmp_path):
        stop, t = _busy_thread("report-busy")
        try:
            cap = sampler.capture(seconds=0.3, interval=0.01)
        finally:
            stop.set()
            t.join(timeout=5)
        path = tmp_path / "cap.json"
        path.write_text(json.dumps(cap))
        folded = tmp_path / "out.folded"
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "profile_report.py"),
             str(path), "--top", "5", "--collapsed", str(folded)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "report-busy" in proc.stdout
        assert "top" in proc.stdout and "sampled stacks" in proc.stdout
        assert "process CPU" in proc.stdout
        lines = folded.read_text().strip().splitlines()
        assert lines and all(
            re.match(r".+ \d+$", line) for line in lines
        )

    def test_report_rejects_non_capture(self, tmp_path):
        path = tmp_path / "junk.json"
        path.write_text(json.dumps({"foo": 1}))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "profile_report.py"), str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 2

"""TPU/JAX batched ed25519 kernel vs the host oracle.

Test layer parity: reference `core/src/test/kotlin/net/corda/core/crypto/
CryptoUtilsTest.kt` (per-scheme sign/verify vectors) applied to the batch
path; elementwise agreement with ed25519_math.verify is the invariant.
"""
import hashlib
import os

import numpy as np
import pytest

from corda_tpu.core.crypto import ed25519_math
from corda_tpu.ops import field25519 as F
from corda_tpu.ops import ed25519_batch


def _keypair(seed: bytes):
    return ed25519_math.public_from_seed(seed), seed


def _sign(seed: bytes, msg: bytes) -> bytes:
    return ed25519_math.sign(seed, msg)


class TestField:
    def test_mul_matches_bigint(self):
        rng = np.random.default_rng(0)
        xs = [int.from_bytes(rng.bytes(32), "little") % 2**256 for _ in range(32)]
        ys = [int.from_bytes(rng.bytes(32), "little") % 2**256 for _ in range(32)]
        a = np.stack([F.int_to_limbs(x) for x in xs])
        b = np.stack([F.int_to_limbs(y) for y in ys])
        got = np.asarray(F.canonical(F.mul(a, b)))
        for i in range(32):
            assert F.limbs_to_int(got[i]) == xs[i] * ys[i] % F.P_INT

    def test_add_sub_roundtrip(self):
        rng = np.random.default_rng(1)
        xs = [int.from_bytes(rng.bytes(32), "little") for _ in range(16)]
        ys = [int.from_bytes(rng.bytes(32), "little") for _ in range(16)]
        a = np.stack([F.int_to_limbs(x) for x in xs])
        b = np.stack([F.int_to_limbs(y) for y in ys])
        s = np.asarray(F.canonical(F.add(a, b)))
        d = np.asarray(F.canonical(F.sub(a, b)))
        for i in range(16):
            assert F.limbs_to_int(s[i]) == (xs[i] + ys[i]) % F.P_INT
            assert F.limbs_to_int(d[i]) == (xs[i] - ys[i]) % F.P_INT

    def test_edge_values(self):
        edges = [0, 1, 19, F.P_INT - 1, F.P_INT, F.P_INT + 1, 2**256 - 1, 2**255 - 1]
        a = np.stack([F.int_to_limbs(x) for x in edges])
        sq = np.asarray(F.canonical(F.mul(a, a)))
        for i, x in enumerate(edges):
            assert F.limbs_to_int(sq[i]) == x * x % F.P_INT
        assert list(np.asarray(F.lt_p(a))) == [x < F.P_INT for x in edges]

    def test_sub_underflow_edge(self):
        # b > a + 2p drives the borrow chain negative; the result must stay
        # congruent mod p (regression: negative carry cast to huge uint32)
        cases = [
            (0, 2**256 - 2),
            (0, 2**256 - 1),
            (5, 2**256 - 10),
            (36, 2**256 - 1),
            (2**256 - 1, 1),
            (0, 0),
        ]
        a = np.stack([F.int_to_limbs(x) for x, _ in cases])
        b = np.stack([F.int_to_limbs(y) for _, y in cases])
        d = np.asarray(F.canonical(F.sub(a, b)))
        for i, (x, y) in enumerate(cases):
            assert F.limbs_to_int(d[i]) == (x - y) % F.P_INT, cases[i]

    def test_pow_const(self):
        x = 123456789
        a = F.int_to_limbs(x)[None, :]
        e = (F.P_INT - 5) // 8
        got = F.limbs_to_int(np.asarray(F.canonical(F.pow_const(a, e)))[0])
        assert got == pow(x, e, F.P_INT)


class TestBatchVerify:
    def test_valid_batch(self):
        msgs = [f"message {i}".encode() for i in range(20)]
        pubs, sigs = [], []
        for i, m in enumerate(msgs):
            pub, seed = _keypair(hashlib.sha256(f"k{i}".encode()).digest())
            pubs.append(pub)
            sigs.append(_sign(seed, m))
        mask = ed25519_batch.verify_batch(pubs, sigs, msgs)
        assert mask.all()

    def test_tampered_rejected(self):
        pub, seed = _keypair(os.urandom(32))
        msg = b"pay 100 to alice"
        sig = _sign(seed, msg)
        bad_sig = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
        bad_msg = b"pay 999 to mallory"
        other_pub, _ = _keypair(os.urandom(32))
        mask = ed25519_batch.verify_batch(
            [pub, pub, pub, other_pub],
            [sig, bad_sig, sig, sig],
            [msg, msg, bad_msg, msg],
        )
        assert list(mask) == [True, False, False, False]

    def test_malformed_inputs(self):
        pub, seed = _keypair(os.urandom(32))
        msg = b"m"
        sig = _sign(seed, msg)
        # s >= L is non-canonical and must be rejected
        s_big = (F.L_INT + 5).to_bytes(32, "little")
        sig_bad_s = sig[:32] + s_big
        # y >= p is a non-canonical point encoding
        bad_y = (F.P_INT + 1).to_bytes(32, "little")
        mask = ed25519_batch.verify_batch(
            [pub, pub, bad_y, pub, b"\x01" * 7],
            [sig, sig_bad_s, sig, b"\x00" * 9, sig],
            [msg] * 5,
        )
        assert list(mask) == [True, False, False, False, False]

    def test_non_point_pubkey(self):
        pub, seed = _keypair(os.urandom(32))
        msg = b"hello"
        sig = _sign(seed, msg)
        # find a y that is not on the curve
        y = 2
        while ed25519_math.point_decompress(
            int(y).to_bytes(32, "little")
        ) is not None:
            y += 1
        not_a_point = int(y).to_bytes(32, "little")
        mask = ed25519_batch.verify_batch(
            [not_a_point, pub], [sig, sig], [msg, msg]
        )
        assert list(mask) == [False, True]

    def test_agrees_with_host_oracle_fuzz(self):
        rng = np.random.default_rng(42)
        pubs, sigs, msgs, expect = [], [], [], []
        for i in range(48):
            seed = rng.bytes(32)
            pub, _ = _keypair(seed)
            msg = rng.bytes(rng.integers(1, 200))
            sig = _sign(seed, msg)
            kind = i % 4
            if kind == 1:
                sig = bytes([sig[0] ^ 0xFF]) + sig[1:]
            elif kind == 2:
                msg = msg + b"!"
            elif kind == 3:
                pub = rng.bytes(32)  # random 32 bytes: usually not a valid key
            pubs.append(pub)
            sigs.append(sig)
            msgs.append(msg)
            expect.append(ed25519_math.verify(pub, msg, sig))
        mask = ed25519_batch.verify_batch(pubs, sigs, msgs)
        assert list(mask) == expect

    def test_empty_batch(self):
        assert ed25519_batch.verify_batch([], [], []).shape == (0,)


class TestFastMulVariants:
    """The Mosaic-only live-row accumulation variants must agree with the
    dense formulations bit-for-bit (they are swapped in only while the
    TPU kernel body is traced; docs/perf-roofline.md item 3)."""

    def test_mul_and_square_fast_differential(self):
        import jax
        import jax.numpy as jnp

        from corda_tpu.ops import ed25519_pallas as pl_mod
        from corda_tpu.ops.field25519 import P_INT

        rng = np.random.default_rng(17)
        vals_a = [int.from_bytes(rng.bytes(32), "little") % P_INT
                  for _ in range(8)]
        vals_b = [int.from_bytes(rng.bytes(32), "little") % P_INT
                  for _ in range(8)]
        vals_a[0], vals_b[0] = P_INT - 1, P_INT - 1  # worst-case carries
        vals_a[1], vals_b[1] = 0, 0

        def col(vals):
            return jnp.concatenate(
                [
                    jnp.asarray(
                        [[v] for v in pl_mod._limbs(x)], jnp.uint32
                    )
                    for x in vals
                ],
                axis=1,
            )

        a, b = col(vals_a), col(vals_b)
        f = jax.jit(
            lambda x, y: (
                pl_mod._canonical(pl_mod._mul(x, y)),
                pl_mod._canonical(pl_mod._mul_fast(x, y)),
                pl_mod._canonical(pl_mod._square(x)),
                pl_mod._canonical(pl_mod._square_fast(x)),
            )
        )
        mul_ref, mul_fast, sq_ref, sq_fast = f(a, b)
        assert np.array_equal(np.asarray(mul_ref), np.asarray(mul_fast))
        assert np.array_equal(np.asarray(sq_ref), np.asarray(sq_fast))
        # and against plain integer arithmetic
        got = np.asarray(mul_fast)
        for j, (x, y) in enumerate(zip(vals_a, vals_b)):
            want = pl_mod._limbs((x * y) % P_INT)
            assert [int(v) for v in got[:, j]] == want, j


class TestRadix13Field:
    """Per-op differentials for the radix-2^13 field vs python ints —
    including the edge paths the ladder only hits probabilistically:
    _sub13's zero 2^260-digit case (a << b), _canonical13 at the
    capacity ceiling (~32p), and fast/dense bit-equality. Eager (no jit):
    the ops are tiny at W=8."""

    W = 8

    @staticmethod
    def _col13(vals):
        import jax.numpy as jnp

        from corda_tpu.ops import ed25519_pallas as m

        return jnp.concatenate(
            [
                jnp.asarray([[v] for v in m._limbs13(x)], jnp.uint32)
                for x in vals
            ],
            axis=1,
        )

    @staticmethod
    def _ints13(col):
        from corda_tpu.ops import ed25519_pallas as m

        arr = np.asarray(col)
        return [
            sum(int(arr[k, j]) << (13 * k) for k in range(m.ROWS13))
            for j in range(arr.shape[1])
        ]

    def test_ops_vs_int_oracle(self):
        from corda_tpu.ops import ed25519_pallas as m
        from corda_tpu.ops.field25519 import P_INT

        rng = np.random.default_rng(42)
        W = self.W
        a_i = [int.from_bytes(rng.bytes(32), "little") % P_INT
               for _ in range(W)]
        b_i = [int.from_bytes(rng.bytes(32), "little") % P_INT
               for _ in range(W)]
        a_i[0], b_i[0] = P_INT - 1, P_INT - 1
        a_i[1], b_i[1] = 0, 0
        a_i[2], b_i[2] = 1, P_INT - 1
        a_i[3], b_i[3] = 0, P_INT - 1  # a << b: digit_260 == 0 in _sub13
        a, b = self._col13(a_i), self._col13(b_i)
        with m._radix13_trace():
            mul_d = m._mul(a, b)
            with m._fast_mul_trace():
                mul_f = m._mul(a, b)
            sq_d = m._square(a)
            with m._fast_mul_trace():
                sq_f = m._square(a)
            add, sub = m._add(a, b), m._sub(a, b)
            can = m._canonical(mul_d)
            neg = m._neg(a)
        assert np.array_equal(np.asarray(mul_d), np.asarray(mul_f))
        assert np.array_equal(np.asarray(sq_d), np.asarray(sq_f))
        P = P_INT
        assert [v % P for v in self._ints13(mul_d)] == [
            (x * y) % P for x, y in zip(a_i, b_i)]
        assert [v % P for v in self._ints13(sq_d)] == [
            (x * x) % P for x in a_i]
        assert [v % P for v in self._ints13(add)] == [
            (x + y) % P for x, y in zip(a_i, b_i)]
        assert [v % P for v in self._ints13(sub)] == [
            (x - y) % P for x, y in zip(a_i, b_i)]
        assert self._ints13(can) == [
            (x * y) % P for x, y in zip(a_i, b_i)]
        assert [v % P for v in self._ints13(neg)] == [(-x) % P for x in a_i]

    def test_canonical_at_capacity_and_conversion(self):
        import jax.numpy as jnp

        from corda_tpu.ops import ed25519_pallas as m
        from corda_tpu.ops.field25519 import P_INT

        # raw rows at the capacity ceiling: value 2^260 - 1 ~ 32p
        top = jnp.full((m.ROWS13, self.W), np.uint32(0x1FFF), jnp.uint32)
        with m._radix13_trace():
            can = m._canonical(top)
            sub = m._sub(self._col13([0] * self.W), top)  # 0 - (32p-ish)
        assert self._ints13(can) == [(2**260 - 1) % P_INT] * self.W
        assert [v % P_INT for v in self._ints13(sub)] == [
            (-(2**260 - 1)) % P_INT] * self.W
        # 16->13 conversion is value-preserving
        rng = np.random.default_rng(7)
        vals = [int.from_bytes(rng.bytes(32), "little") % 2**255
                for _ in range(self.W)]
        col16 = jnp.concatenate(
            [jnp.asarray([[v] for v in m._limbs(x)], jnp.uint32)
             for x in vals], axis=1)
        assert self._ints13(m._rows16_to_13(col16)) == vals

    def test_chained_stress(self):
        """Interleaved mul/sub/add/square chains keep agreeing with the
        int oracle — the bound argument holds across compositions."""
        from corda_tpu.ops import ed25519_pallas as m
        from corda_tpu.ops.field25519 import P_INT

        rng = np.random.default_rng(3)
        x_i = int.from_bytes(rng.bytes(32), "little") % P_INT
        y_i = int.from_bytes(rng.bytes(32), "little") % P_INT
        x, y = self._col13([x_i] * self.W), self._col13([y_i] * self.W)
        with m._radix13_trace():
            for _ in range(8):
                x, x_i = m._mul(x, y), (x_i * y_i) % P_INT
                y, y_i = m._sub(y, x), (y_i - x_i) % P_INT
                x, x_i = m._add(x, x), (2 * x_i) % P_INT
                y, y_i = m._square(y), (y_i * y_i) % P_INT
            assert self._ints13(m._canonical(x))[0] == x_i
            assert self._ints13(m._canonical(y))[0] == y_i


class TestPallasDegradation:
    """A Mosaic rejection must never sink verification (or the bench
    gate): fast-mul failure retries dense; dense failure latches over to
    the portable XLA kernel. Simulated by a raising dispatch — the same
    exception path a real compile error takes."""

    @pytest.fixture(autouse=True)
    def _restore_globals(self):
        from corda_tpu.ops import ed25519_pallas as pl_mod

        saved_fast = pl_mod._FAST_MUL_ENABLED
        saved_r13 = pl_mod._RADIX13_ENABLED
        saved_failed = ed25519_batch._pallas_failed_once
        saved_checked = set(ed25519_batch._selfchecked)
        # pin the chain's starting rung so the expected attempt sequence
        # is deterministic regardless of CORDA_TPU_ED25519_RADIX in the env
        pl_mod._RADIX13_ENABLED = False
        ed25519_batch._selfchecked.clear()
        yield
        pl_mod._FAST_MUL_ENABLED = saved_fast
        pl_mod._RADIX13_ENABLED = saved_r13
        ed25519_batch._pallas_failed_once = saved_failed
        ed25519_batch._selfchecked.clear()
        ed25519_batch._selfchecked.update(saved_checked)

    def _batch(self, n=6):
        rng = np.random.default_rng(11)
        pubs, sigs, msgs = [], [], []
        for i in range(n):
            seed = rng.bytes(32)
            msg = rng.bytes(32)
            pubs.append(ed25519_math.public_from_seed(seed))
            sig = ed25519_math.sign(seed, msg)
            if i == 2:
                sig = bytes([sig[0] ^ 1]) + sig[1:]
            sigs.append(sig)
            msgs.append(msg)
        expect = [
            ed25519_math.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)
        ]
        return pubs, sigs, msgs, expect

    def test_fast_failure_retries_dense_then_xla(self, monkeypatch):
        from corda_tpu.ops import ed25519_pallas as pl_mod

        pl_mod._FAST_MUL_ENABLED = True
        ed25519_batch._pallas_failed_once = False
        attempts = []

        def boom(kwargs):
            attempts.append(pl_mod._FAST_MUL_ENABLED)
            raise RuntimeError("Mosaic lowering failed (simulated)")

        monkeypatch.setattr(ed25519_batch, "_dispatch_pallas", boom)
        pubs, sigs, msgs, expect = self._batch()
        out = ed25519_batch._verify_batch_pallas(pubs, sigs, msgs)
        assert [bool(b) for b in out] == expect  # served by the XLA kernel
        assert attempts == [True, False]  # fast try, then dense try
        assert ed25519_batch._pallas_failed_once
        # latched: the next batch goes straight to XLA, no new attempts
        out2 = ed25519_batch._verify_batch_pallas(pubs, sigs, msgs)
        assert [bool(b) for b in out2] == expect
        assert attempts == [True, False]

    def test_fast_failure_settles_on_r13_dense(self, monkeypatch):
        """Fast-mul drops BEFORE the radix: when Mosaic rejects the
        live-row accumulation (the documented open question) but takes
        the dense r13 kernel, the ladder must settle on r13+dense (the
        projected above-target config), not regress to radix-16."""
        from corda_tpu.ops import ed25519_pallas as pl_mod

        pl_mod._RADIX13_ENABLED = True
        pl_mod._FAST_MUL_ENABLED = True
        ed25519_batch._pallas_failed_once = False

        def flaky(kwargs):
            if pl_mod._FAST_MUL_ENABLED:
                raise RuntimeError("live-row accumulation rejected (sim)")
            mask = ed25519_batch.verify_kernel(**kwargs)
            return mask[None, :]

        monkeypatch.setattr(ed25519_batch, "_dispatch_pallas", flaky)
        pubs, sigs, msgs, expect = self._batch()
        out = ed25519_batch._verify_batch_pallas(pubs, sigs, msgs)
        assert [bool(b) for b in out] == expect
        assert pl_mod._RADIX13_ENABLED  # radix kept
        assert not pl_mod._FAST_MUL_ENABLED
        assert not ed25519_batch._pallas_failed_once

    def test_r13_failure_recovers_r16_fast(self, monkeypatch):
        """If the kernel fails for a radix-13-specific reason, the ladder
        walks r13+fast -> r13+dense -> r16+fast (fast-mul re-enabled when
        the radix drops: the dense failure may have been r13-specific,
        and r16+fast was validated round 2) and stays on Pallas."""
        from corda_tpu.ops import ed25519_pallas as pl_mod

        pl_mod._RADIX13_ENABLED = True
        pl_mod._FAST_MUL_ENABLED = True
        ed25519_batch._pallas_failed_once = False
        attempts = []

        def flaky(kwargs):
            attempts.append(
                (pl_mod._RADIX13_ENABLED, pl_mod._FAST_MUL_ENABLED)
            )
            if pl_mod._RADIX13_ENABLED:
                raise RuntimeError("r13 rejected (simulated)")
            mask = ed25519_batch.verify_kernel(**kwargs)
            return mask[None, :]

        monkeypatch.setattr(ed25519_batch, "_dispatch_pallas", flaky)
        pubs, sigs, msgs, expect = self._batch()
        out = ed25519_batch._verify_batch_pallas(pubs, sigs, msgs)
        assert [bool(b) for b in out] == expect
        # each rung's first dispatch is the known-answer self-check; the
        # surviving config dispatches twice (self-check, then the batch)
        assert attempts == [
            (True, True), (True, False), (False, True), (False, True),
        ]
        assert pl_mod._FAST_MUL_ENABLED  # settled on r16+fast
        assert not ed25519_batch._pallas_failed_once

    def test_wrong_results_degrade_like_a_crash(self, monkeypatch):
        """Silently WRONG kernel output (a miscompiled lowering, not an
        exception) must be caught by the known-answer self-check and walk
        the ladder exactly like a compile failure — wrong verdicts from
        one config must never reach callers (consensus property)."""
        from corda_tpu.ops import ed25519_pallas as pl_mod

        pl_mod._FAST_MUL_ENABLED = True
        ed25519_batch._pallas_failed_once = False

        def miscompiled(kwargs):
            if pl_mod._FAST_MUL_ENABLED:
                # everything "verifies" — including the tampered rows
                n = kwargs["y_a"].shape[0]
                return np.ones((1, n), np.uint32)
            mask = ed25519_batch.verify_kernel(**kwargs)
            return mask[None, :]

        monkeypatch.setattr(ed25519_batch, "_dispatch_pallas", miscompiled)
        pubs, sigs, msgs, expect = self._batch()
        out = ed25519_batch._verify_batch_pallas(pubs, sigs, msgs)
        assert [bool(b) for b in out] == expect  # served by dense rung
        assert not pl_mod._FAST_MUL_ENABLED
        assert not ed25519_batch._pallas_failed_once

    def test_fast_failure_with_working_dense_stays_on_pallas(
        self, monkeypatch
    ):
        from corda_tpu.ops import ed25519_pallas as pl_mod

        pl_mod._FAST_MUL_ENABLED = True
        ed25519_batch._pallas_failed_once = False

        def flaky(kwargs):
            if pl_mod._FAST_MUL_ENABLED:
                raise RuntimeError("fast-mul rejected (simulated)")
            mask = ed25519_batch.verify_kernel(**kwargs)
            return mask[None, :]

        monkeypatch.setattr(ed25519_batch, "_dispatch_pallas", flaky)
        pubs, sigs, msgs, expect = self._batch()
        out = ed25519_batch._verify_batch_pallas(pubs, sigs, msgs)
        assert [bool(b) for b in out] == expect
        assert not ed25519_batch._pallas_failed_once  # dense Pallas serves
        assert not pl_mod._FAST_MUL_ENABLED


class TestPallasCore:
    @pytest.mark.parametrize("radix13", [False, True], ids=["r16", "r13"])
    def test_verify_core_off_tpu(self, radix13):
        """The Pallas kernel's math core (`ed25519_pallas._verify_core`) run
        on CPU with array-backed table/digit accessors must agree with the
        host oracle — so a ladder/table/decompress bug cannot hide behind
        the TPU-only dispatch (round-2 review finding). Covers BOTH limb
        radixes (the radix-2^13 variant is the round-3 perf lever)."""
        import contextlib

        import jax.numpy as jnp

        from corda_tpu.ops import ed25519_batch, ed25519_pallas

        width = 8
        rng = np.random.default_rng(5)
        pubs, sigs, msgs, expect = [], [], [], []
        for i in range(width):
            seed = rng.bytes(32)
            pub, _ = _keypair(seed)
            msg = rng.bytes(40)
            sig = _sign(seed, msg)
            if i == 1:
                sig = bytes([sig[0] ^ 1]) + sig[1:]
            elif i == 2:
                msg = msg + b"!"
            elif i == 3:
                pub = rng.bytes(32)
            pubs.append(pub)
            sigs.append(sig)
            msgs.append(msg)
            expect.append(ed25519_math.verify(pub, msg, sig))
        kwargs, _ = ed25519_batch.prepare_batch(pubs, sigs, msgs, pad_to=width)

        from jax import lax

        # Array-backed accessors that support the kernel's REAL control
        # flow (lax.fori_loop): digit rows are written with concrete
        # indices before the ladder, so they can be stacked into one array
        # the traced loop body dynamic-slices. This exercises the exact
        # ladder the Pallas kernel runs while tracing its body only once
        # (the fully-unrolled eager variant took ~3 min of dispatch).
        table = {}
        idx_rows = {}
        stacked = {}

        def read_idx(t):
            if "idx" not in stacked:
                stacked["idx"] = jnp.concatenate(
                    [idx_rows[k] for k in range(ed25519_pallas.NDIGITS)],
                    axis=0,
                )
            return lax.dynamic_slice_in_dim(stacked["idx"], t, 1, axis=0)

        ctx = (
            ed25519_pallas._radix13_trace()
            if radix13
            else contextlib.nullcontext()
        )
        with ctx:
            mask = ed25519_pallas._verify_core(
                width,
                jnp.asarray(np.asarray(kwargs["y_a"]).T),
                jnp.asarray(np.asarray(kwargs["sign_a"])[None, :]),
                jnp.asarray(np.asarray(kwargs["y_r"]).T),
                jnp.asarray(np.asarray(kwargs["sign_r"])[None, :]),
                jnp.asarray(np.asarray(kwargs["s_words"]).T),
                jnp.asarray(np.asarray(kwargs["h_words"]).T),
                jnp.asarray(
                    np.asarray(kwargs["s_ok"])[None, :].astype(np.uint32)
                ),
                write_table=table.__setitem__,
                read_table=table.__getitem__,
                write_idx=idx_rows.__setitem__,
                read_idx=read_idx,
            )
        got = [bool(v) for v in np.asarray(mask)[0]]
        assert got == expect

    def test_r13_decompress_edges_agree_with_oracle(self):
        """The radix-13 decompress/canonicalization must agree with the
        oracle on the adversarial encodings (small-order points,
        non-canonical y >= p, y=0 with sign=1) — these exercise exactly
        the code that differs by radix (_lt_p, _canonical13, parity)."""
        import jax.numpy as jnp
        from jax import lax

        from corda_tpu.ops import ed25519_batch, ed25519_pallas

        msg = b"edge-case message"
        seed = hashlib.sha256(b"edge").digest()
        good_pub, good_seed = _keypair(seed)
        good_sig = _sign(good_seed, msg)
        pubs, sigs, msgs, expect = [], [], [], []
        for enc in TestAdversarialVectors.SMALL_ORDER:
            pubs.append(enc)
            sigs.append(good_sig)
            msgs.append(msg)
            expect.append(ed25519_math.verify(enc, msg, good_sig))
            pubs.append(good_pub)
            sigs.append(enc + good_sig[32:])
            msgs.append(msg)
            expect.append(
                ed25519_math.verify(good_pub, msg, enc + good_sig[32:])
            )
        width = len(pubs)
        kwargs, _ = ed25519_batch.prepare_batch(pubs, sigs, msgs, pad_to=width)

        table = {}
        idx_rows = {}
        stacked = {}

        def read_idx(t):
            if "idx" not in stacked:
                stacked["idx"] = jnp.concatenate(
                    [idx_rows[k] for k in range(ed25519_pallas.NDIGITS)],
                    axis=0,
                )
            return lax.dynamic_slice_in_dim(stacked["idx"], t, 1, axis=0)

        with ed25519_pallas._radix13_trace():
            mask = ed25519_pallas._verify_core(
                width,
                jnp.asarray(np.asarray(kwargs["y_a"]).T),
                jnp.asarray(np.asarray(kwargs["sign_a"])[None, :]),
                jnp.asarray(np.asarray(kwargs["y_r"]).T),
                jnp.asarray(np.asarray(kwargs["sign_r"])[None, :]),
                jnp.asarray(np.asarray(kwargs["s_words"]).T),
                jnp.asarray(np.asarray(kwargs["h_words"]).T),
                jnp.asarray(
                    np.asarray(kwargs["s_ok"])[None, :].astype(np.uint32)
                ),
                write_table=table.__setitem__,
                read_table=table.__getitem__,
                write_idx=idx_rows.__setitem__,
                read_idx=read_idx,
            )
        assert [bool(v) for v in np.asarray(mask)[0]] == expect


class TestAdversarialVectors:
    """Wycheproof-style edge encodings: the kernel must AGREE with the host
    oracle on every one (consensus property — a node on the device path and
    a node on the host path must never split)."""

    # the eight small-order point encodings on edwards25519
    SMALL_ORDER = [
        bytes(32),                                        # y=0 variant (x=0? order 4)
        (1).to_bytes(32, "little"),                       # identity (y=1)
        bytes.fromhex(
            "26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc05"),
        bytes.fromhex(
            "c7176a703d4dd84fba3c0b760d10670f2a2053fa2c39ccc64ec7fd7792ac037a"),
        bytes.fromhex(
            "ecffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f"),  # y=-1
        bytes.fromhex(
            "edffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f"),  # y=p (non-canonical 0)
        bytes.fromhex(
            "eeffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f"),  # y=p+1
        bytes.fromhex(
            "0000000000000000000000000000000000000000000000000000000000000080"),  # y=0, sign=1
    ]

    def test_small_order_keys_agree_with_oracle(self):
        msg = b"edge-case message"
        seed = hashlib.sha256(b"edge").digest()
        good_pub, good_seed = _keypair(seed)
        good_sig = _sign(good_seed, msg)
        pubs, sigs, msgs, expect = [], [], [], []
        for enc in self.SMALL_ORDER:
            # small-order / non-canonical A with an honest signature blob
            pubs.append(enc)
            sigs.append(good_sig)
            msgs.append(msg)
            expect.append(ed25519_math.verify(enc, msg, good_sig))
            # and as the R component
            pubs.append(good_pub)
            sigs.append(enc + good_sig[32:])
            msgs.append(msg)
            expect.append(
                ed25519_math.verify(good_pub, msg, enc + good_sig[32:])
            )
        mask = ed25519_batch.verify_batch(pubs, sigs, msgs)
        assert [bool(b) for b in mask] == expect

    def test_zero_scalar_and_boundary_s(self):
        msg = b"boundary"
        seed = hashlib.sha256(b"boundary").digest()
        pub, sk = _keypair(seed)
        sig = _sign(sk, msg)
        cases = [
            sig[:32] + bytes(32),                          # s = 0
            sig[:32] + (F.L_INT - 1).to_bytes(32, "little"),  # s = L-1
            sig[:32] + F.L_INT.to_bytes(32, "little"),     # s = L (reject)
            sig[:32] + (2**256 - 1).to_bytes(32, "little"),  # max (reject)
        ]
        pubs = [pub] * len(cases)
        msgs = [msg] * len(cases)
        expect = [ed25519_math.verify(pub, msg, s) for s in cases]
        mask = ed25519_batch.verify_batch(pubs, cases, msgs)
        assert [bool(b) for b in mask] == expect
        assert expect[2] is False and expect[3] is False

    def test_signature_on_small_order_key_pair(self):
        """A signature 'from' the identity key: s*B == R + h*A with A = O
        means R must equal [s]B — craft it and confirm oracle+kernel agree
        (cofactorless semantics accept it iff the math holds)."""
        identity_pub = (1).to_bytes(32, "little")
        # choose s = 0 -> [0]B = O -> R must encode the identity as well
        sig = (1).to_bytes(32, "little") + bytes(32)
        msg = b"forged-by-identity"
        expect = ed25519_math.verify(identity_pub, msg, sig)
        mask = ed25519_batch.verify_batch([identity_pub], [sig], [msg])
        assert bool(mask[0]) == expect


class TestMosaicLoweringGate:
    """jax.export cross-platform lowering runs the REAL Pallas->Mosaic
    pipeline without TPU hardware — the gate that caught scatter-add
    (fast-mul) and dynamic_slice (ECDSA pow_const) being unimplemented
    before they could burn a live tunnel window. The DEFAULT config must
    always lower; the non-default radix is covered too (cheap)."""

    @staticmethod
    def _export_ed25519(fast_mul, radix13):
        import jax
        import jax.numpy as jnp
        from jax import export as jexport

        from corda_tpu.ops import ed25519_pallas as m

        BLK = m.BLK
        args = (
            jnp.zeros((16, BLK), jnp.uint32), jnp.zeros((1, BLK), jnp.uint32),
            jnp.zeros((16, BLK), jnp.uint32), jnp.zeros((1, BLK), jnp.uint32),
            jnp.zeros((8, BLK), jnp.uint32), jnp.zeros((8, BLK), jnp.uint32),
            jnp.zeros((1, BLK), jnp.uint32),
        )
        fn = jax.jit(
            lambda *a: m.verify_kernel_pallas(
                *a, fast_mul=fast_mul, radix13=radix13
            )
        )
        jexport.export(fn, platforms=["tpu"])(*args)

    def test_default_config_lowers_for_tpu(self):
        from corda_tpu.ops import ed25519_pallas as m

        self._export_ed25519(m._FAST_MUL_ENABLED, m._RADIX13_ENABLED)

    @pytest.mark.heavy_compile
    def test_radix16_dense_lowers_for_tpu(self):
        self._export_ed25519(False, False)

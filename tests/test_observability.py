"""The tracing spine + ops endpoint (docs/observability.md).

Covers: W3C-style context propagation through the broker headers and
the in-memory network, one trace crossing all four pipeline stages
(flow → P2P → verifier batch → notary commit) in a two-party
MockNetwork run, fan-in links on batch spans, bounded span storage,
the slow-span watchdog, the /metrics Prometheus exposition contract,
/traces retrieval, and the MiniWebServer static-page 500 regression.
"""
import json
import logging
import urllib.error
import urllib.request

import pytest

from corda_tpu.utils import tracing
from corda_tpu.utils.tracing import SpanContext, Tracer


@pytest.fixture()
def tracer():
    """Fresh process tracer per test (nodes resolve it dynamically)."""
    prev = tracing.set_tracer(Tracer())
    yield tracing.get_tracer()
    tracing.set_tracer(prev)


# ---------------------------------------------------------------------------
# Context + span mechanics
# ---------------------------------------------------------------------------

class TestSpanContext:
    def test_traceparent_round_trip(self):
        ctx = SpanContext("ab" * 16, "cd" * 8)
        parsed = SpanContext.from_traceparent(ctx.to_traceparent())
        assert parsed == ctx

    @pytest.mark.parametrize("bad", [
        None, "", "00-short-cdcdcdcdcdcdcdcd-01",
        "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",
        "no-dashes-here", "00-" + "ab" * 16 + "-" + "cd" * 8,
    ])
    def test_malformed_traceparent_is_none(self, bad):
        assert SpanContext.from_traceparent(bad) is None


class TestTracer:
    def test_nested_spans_build_a_tree(self, tracer):
        with tracer.span("root") as root:
            assert tracing.current_context() == root.context
            with tracer.span("child") as child:
                assert child.context.trace_id == root.context.trace_id
        tree = tracer.span_tree(root.context.trace_id)
        assert tree["roots"][0]["name"] == "root"
        assert tree["roots"][0]["children"][0]["name"] == "child"
        json.dumps(tree)  # the endpoint serves this verbatim

    def test_fan_in_span_indexed_under_every_linked_trace(self, tracer):
        with tracer.span("flow-a") as a:
            pass
        with tracer.span("flow-b") as b:
            pass
        batch = tracer.start_span("batch", links=[a.context, b.context])
        batch.finish()
        for parent in (a, b):
            tree = tracer.span_tree(parent.context.trace_id)
            # the batch hangs under the linked span in EACH trace
            root = tree["roots"][0]
            assert [c["name"] for c in root["children"]] == ["batch"]

    def test_trace_storage_is_bounded(self):
        t = Tracer(max_traces=8)
        for i in range(32):
            with t.span(f"s{i}"):
                pass
        assert len(t.trace_ids()) <= 8
        assert t.stats()["traces"] <= 8

    def test_slow_watchdog_logs_and_rings(self, caplog):
        t = Tracer(slow_threshold_ms=0.0001)
        with caplog.at_level(logging.WARNING, logger="corda_tpu.tracing"):
            with t.span("slow-root"):
                with t.span("slow-child"):
                    pass
        assert any("slow root span" in r.message for r in caplog.records)
        slow = t.slow_roots()
        assert slow and slow[0]["name"] == "slow-root"
        # threshold filter
        assert t.slow_roots(threshold_ms=1e9) == []

    def test_disabled_tracer_records_nothing_and_propagates_nothing(self):
        t = Tracer(enabled=False)
        with t.span("x") as sp:
            assert sp.context is None
            assert tracing.current_context() is None
        assert t.trace_ids() == []

    def test_summary_percentiles(self, tracer):
        for _ in range(10):
            with tracer.span("hop"):
                pass
        summary = tracer.summary()
        assert summary["hop"]["count"] == 10
        assert summary["hop"]["p50_ms"] <= summary["hop"]["p99_ms"]


class TestBrokerPropagation:
    def test_traceparent_rides_broker_headers(self, tracer):
        from corda_tpu.messaging import Broker

        broker = Broker()
        broker.create_queue("q")
        consumer = broker.create_consumer("q")
        with tracer.span("sender") as sp:
            broker.send("q", b"payload")
            expected = sp.context.to_traceparent()
        msg = consumer.receive(timeout=1)
        assert msg.headers["traceparent"] == expected
        # untraced sends stay header-free
        broker.send("q", b"payload2")
        assert "traceparent" not in consumer.receive(timeout=1).headers
        broker.close()


# ---------------------------------------------------------------------------
# End-to-end: one trace across RPC → flow → P2P → verifier → notary
# ---------------------------------------------------------------------------

class TestMockNetworkTracePropagation:
    def setup_method(self):
        self._prev = tracing.set_tracer(Tracer())
        from corda_tpu.testing.mocknetwork import MockNetwork

        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.alice = self.net.create_node(
            "O=TraceAlice,L=London,C=GB", ops_port=0
        )
        self.bob = self.net.create_node("O=TraceBob,L=Paris,C=FR")

    def teardown_method(self):
        self.net.stop_nodes()
        tracing.set_tracer(self._prev)

    def _run_payment(self):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.rpc import CordaRPCOps

        ops = CordaRPCOps(self.alice.services, self.alice.smm)
        fid = ops.start_flow_dynamic(
            "corda_tpu.finance.flows.CashIssueFlow",
            Amount(1000, "USD"), (1,), self.alice.info, self.notary.info,
        )
        self.net.run_network()
        assert ops.flow_result(fid, timeout=10) is not None
        token = Issued(self.alice.info.ref(1), "USD")
        fid = ops.start_flow_dynamic(
            "corda_tpu.finance.flows.CashPaymentFlow",
            Amount(400, token), self.bob.info, self.notary.info,
        )
        self.net.run_network()
        assert ops.flow_result(fid, timeout=10) is not None

    def _payment_trace_id(self, tracer):
        for tid in tracer.trace_ids():
            spans = tracer.get_trace(tid)
            if any(
                "CashPaymentFlow" in str(s["tags"].get("flow", ""))
                for s in spans
            ):
                return tid
        raise AssertionError("no trace contains the payment flow")

    def test_one_trace_crosses_all_four_stages(self):
        self._run_payment()
        tracer = self.net.tracer
        tid = self._payment_trace_id(tracer)
        spans = tracer.get_trace(tid)
        names = {s["name"] for s in spans}
        # RPC start + P2P hops + verifier batch + notary commit
        assert "rpc.start_flow" in names
        assert "p2p.deliver" in names
        assert "verifier.batch" in names
        assert "notary.commit" in names
        assert "notary.commit_batch" in names
        # BOTH parties' flow spans (plus the notary's serving flow)
        flow_nodes = {
            s["tags"].get("node")
            for s in spans if s["name"].startswith("flow.")
        }
        assert self.alice.info.name in flow_nodes
        assert self.bob.info.name in flow_nodes
        assert self.notary.info.name in flow_nodes
        # fan-in: the verifier batch span links parent trace(s)
        batch = next(s for s in spans if s["name"] == "verifier.batch")
        assert any(l["trace_id"] == tid for l in batch["links"])
        # and it is ONE tree rooted at the RPC start
        tree = tracer.span_tree(tid)
        assert tree["roots"][0]["name"] == "rpc.start_flow"

    def test_trace_retrievable_over_ops_endpoint(self):
        self._run_payment()
        tid = self._payment_trace_id(self.net.tracer)
        port = self.alice.ops_server.port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/traces/{tid}", timeout=5
        ) as resp:
            tree = json.loads(resp.read())
        assert tree["trace_id"] == tid
        assert tree["span_count"] >= 4
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/traces/slow?threshold_ms=0", timeout=5
        ) as resp:
            slow = json.loads(resp.read())
        assert any(e["name"] == "rpc.start_flow" for e in slow)
        # unknown trace -> JSON 404, not a dropped connection
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/traces/{'0' * 32}", timeout=5
            )
        assert err.value.code == 404


# ---------------------------------------------------------------------------
# /metrics Prometheus exposition contract (CI satellite: the format must
# not silently rot — name charset, HELP/TYPE lines, no duplicate families)
# ---------------------------------------------------------------------------

class TestPrometheusExposition:
    def test_scraped_metrics_are_valid_prometheus_text(self, tracer):
        import re

        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        try:
            node = net.create_node("O=Prom,L=London,C=GB", ops_port=0)
            # populate a few families: a flow + a timer + the gauge
            from corda_tpu.core.flows import FlowLogic

            class _Noop(FlowLogic):
                def call(self):
                    return 1

            node.start_flow(_Noop())
            net.run_network()
            node.smm.metrics.timer("RPC.demo").update(0.01)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{node.ops_server.port}/metrics", timeout=5
            ) as resp:
                assert resp.headers["Content-Type"].startswith("text/plain")
                body = resp.read().decode()
        finally:
            net.stop_nodes()

        name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
        sample_re = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"           # metric name
            r'(\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+="[^"]*")*\})?'
            r" -?[0-9.eE+-]+(\n|$)"                  # value
        )
        families = []
        helped = set()
        for line in body.splitlines():
            if line.startswith("# HELP "):
                helped.add(line.split()[2])
                continue
            if line.startswith("# TYPE "):
                _, _, fam, mtype = line.split()
                assert name_re.fullmatch(fam), fam
                assert mtype in {"counter", "gauge", "summary", "histogram",
                                 "untyped"}
                families.append(fam)
                continue
            assert not line.startswith("#"), f"unknown comment: {line}"
            assert sample_re.match(line + "\n"), f"bad sample line: {line}"
        # no duplicate families, every family carries a HELP line
        assert len(families) == len(set(families)), "duplicate TYPE family"
        assert set(families) <= helped
        # the node's core families made it out
        assert "corda_tpu_flows_started_total" in families
        assert "corda_tpu_flows_in_flight" in families
        assert "corda_tpu_rpc_demo_seconds" in families
        # every sample belongs to a declared family (allowing the summary
        # _sum/_count children)
        fam_set = set(families)
        for line in body.splitlines():
            if line.startswith("#"):
                continue
            name = re.match(r"[a-zA-Z0-9_:]+", line).group(0)
            base = re.sub(r"_(sum|count)$", "", name)
            assert name in fam_set or base in fam_set, name


# ---------------------------------------------------------------------------
# MiniWebServer regression: a missing static page must produce a JSON
# 500 body (the module's own contract), never a dropped connection.
# ---------------------------------------------------------------------------

class TestMiniWebStaticPages:
    def test_missing_static_file_returns_json_500(self):
        from corda_tpu.utils.miniweb import MiniWebServer

        class Server(MiniWebServer):
            pages = {"/": "this-file-does-not-exist.html"}

            def handle(self, method, path, query, body):
                raise KeyError(path)

        srv = Server(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/", timeout=5
                )
            assert err.value.code == 500
            payload = json.loads(err.value.read())
            assert "static page unavailable" in payload["error"]
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Fleet-observatory feeds: the three cursor-paginated drains on the ops
# endpoint (docs/observability.md). The contract under test everywhere:
# samples/spans/records STRICTLY after `since`, and a second poll from
# the reply's `next` re-reads NOTHING.
# ---------------------------------------------------------------------------

class TestFleetFeeds:
    def _get(self, port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as resp:
            return json.loads(resp.read())

    def test_metrics_history_cursor_never_rereads(self):
        from corda_tpu.node.opsserver import OpsServer
        from corda_tpu.utils.metrics import MetricRegistry
        from corda_tpu.utils.timeseries import MetricsHistory

        registry = MetricRegistry()
        counter = registry.counter("Fleet.TestCount")
        history = MetricsHistory(registry, interval_s=60.0)  # manual ticks
        counter.inc(3)
        history.sample_once(now=100.0)
        counter.inc(6)
        history.sample_once(now=101.0)
        srv = OpsServer(registry, history=history)
        try:
            page = self._get(srv.port, "/metrics/history?since=0")
            assert page["enabled"] is True
            assert [s["seq"] for s in page["samples"]] == [1, 2]
            # counter derived as a windowed rate: 6 incs over 1s
            second = page["samples"][1]["metrics"]["Fleet.TestCount"]
            assert second == {"count": 9.0, "rate": 6.0}
            # the resumed poll sees only what happened since
            counter.inc(1)
            history.sample_once(now=102.0)
            page2 = self._get(
                srv.port, f"/metrics/history?since={page['next']}"
            )
            assert [s["seq"] for s in page2["samples"]] == [3]
            assert self._get(
                srv.port, f"/metrics/history?since={page2['next']}"
            )["samples"] == []
            # a node without a history serves a well-formed empty page
            bare = OpsServer(MetricRegistry())
            try:
                off = self._get(bare.port, "/metrics/history")
                assert off == {"enabled": False, "samples": [],
                               "next": 0, "newest": 0}
            finally:
                bare.stop()
            # a garbage cursor is the client's fault: 400, never a 500
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics/history?since=x",
                    timeout=5,
                )
            assert err.value.code == 400
        finally:
            srv.stop()

    def test_traces_export_cursor_drain(self, tracer):
        from corda_tpu.node.opsserver import OpsServer
        from corda_tpu.utils.metrics import MetricRegistry

        with tracer.span("first"):
            pass
        srv = OpsServer(MetricRegistry())
        try:
            page = self._get(srv.port, "/traces/export?since=0")
            assert [s["name"] for s in page["spans"]] == ["first"]
            assert page["spans"][0]["seq"] == page["next"] == 1
            assert page["dropped"] == 0
            with tracer.span("second"):
                pass
            page2 = self._get(
                srv.port, f"/traces/export?since={page['next']}"
            )
            assert [s["name"] for s in page2["spans"]] == ["second"]
            assert self._get(
                srv.port, f"/traces/export?since={page2['next']}"
            )["spans"] == []
        finally:
            srv.stop()

    def test_logs_since_seq_two_polls_no_duplicates(self):
        from corda_tpu.node.opsserver import OpsServer
        from corda_tpu.utils.eventlog import EventLog
        from corda_tpu.utils.metrics import MetricRegistry

        log = EventLog()
        for i in range(3):
            log.emit("info", "fleet", f"before-{i}")
        srv = OpsServer(MetricRegistry(), event_log=log)
        try:
            first = self._get(srv.port, "/logs")["events"]
            assert [e["seq"] for e in first] == [1, 2, 3]
            cursor = max(e["seq"] for e in first)
            for i in range(2):
                log.emit("info", "fleet", f"after-{i}")
            second = self._get(
                srv.port, f"/logs?since_seq={cursor}"
            )["events"]
            # the second poll re-reads NOTHING and misses nothing
            assert [e["seq"] for e in second] == [4, 5]
            assert [e["message"] for e in second] == ["after-0", "after-1"]
            assert self._get(srv.port, "/logs?since_seq=5")["events"] == []
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/logs?since_seq=x",
                    timeout=5,
                )
            assert err.value.code == 400
        finally:
            srv.stop()

    def test_seq_survives_ring_eviction(self):
        from corda_tpu.utils.eventlog import EventLog

        log = EventLog(capacity=4)
        for i in range(10):
            log.emit("info", "fleet", f"m{i}")
        records = log.records()
        # eviction dropped the oldest but seq stays monotonic + global,
        # so a collector's since_seq cursor remains valid across drops
        assert [e["seq"] for e in records] == [7, 8, 9, 10]
        assert log.records(since_seq=8) == records[2:]
        assert log.stats()["emitted"] == 10

"""BLS12-381 aggregate signatures: reference math, RFC 9380 vectors,
scheme SPI, rogue-key defenses, the aggregating BFT committee, and the
jax pairing kernels (differential vs the pure-Python mirror)."""
import random
from collections import deque

import numpy as np
import pytest

from corda_tpu.core.crypto import bls_math as B
from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto.schemes import BLS_BLS12381
from corda_tpu.node.bft import BFTClient, BFTReplica, dev_bls_committee


@pytest.fixture(autouse=True)
def _fresh_pop_registry():
    saved = set(crypto._POP_REGISTRY)
    yield
    with crypto._POP_LOCK:
        crypto._POP_REGISTRY.clear()
        crypto._POP_REGISTRY.update(saved)


def _fp12_pow(a, e):
    out = B.FP12_ONE
    while e:
        if e & 1:
            out = B.fp12_mul(out, a)
        a = B.fp12_sq(a)
        e >>= 1
    return out


class TestReferenceMath:
    def test_derived_parameters_match_published(self):
        # p, r, cofactors regenerate from the curve parameter x; the
        # module asserts them at import — re-assert the relations here
        # so a refactor cannot silently drop the import-time checks
        assert B.P == (B.X - 1) ** 2 * (B.X**4 - B.X**2 + 1) // 3 + B.X
        assert B.R == B.X**4 - B.X**2 + 1
        assert (B.P**4 - B.P**2 + 1) % B.R == 0
        assert 3 * ((B.P**4 - B.P**2 + 1) // B.R) == (
            (B.X - 1) ** 2 * (B.X + B.P) * (B.X**2 + B.P**2 - 1) + 3
        )
        assert B.H_EFF_G2 % B.H2 == 0  # h_eff clears the G2 cofactor

    def test_generators_on_curve_and_in_subgroup(self):
        assert B.g1_on_curve(B.G1_GEN) and B.g1_in_subgroup(B.G1_GEN)
        assert B.g2_on_curve(B.G2_GEN) and B.g2_in_subgroup(B.G2_GEN)

    def test_fp12_frobenius_is_pth_power(self):
        random.seed(11)
        f = tuple(
            tuple((random.randrange(B.P), random.randrange(B.P))
                  for _ in range(3))
            for _ in range(2)
        )
        assert B.fp12_frob(f) == _fp12_pow(f, B.P)
        assert B.fp12_mul(f, B.fp12_inv(f)) == B.FP12_ONE

    def test_fp2_sqrt_self_verifies(self):
        random.seed(12)
        for _ in range(4):
            a = (random.randrange(B.P), random.randrange(B.P))
            sq = B.fp2_sq(a)
            root = B.fp2_sqrt(sq)
            assert root is not None and B.fp2_sq(root) == sq

    def test_jacobian_matches_affine_scalar_mult(self):
        random.seed(13)

        def affine_mul(p1, k, add):
            out, acc = None, p1
            while k:
                if k & 1:
                    out = add(out, acc)
                acc = add(acc, acc)
                k >>= 1
            return out

        q = affine_mul(B.G2_GEN, 987654321, B.g2_add)
        for k in (1, 2, 3, random.randrange(B.R), B.R - 1):
            assert B.g2_mul(q, k) == affine_mul(q, k, B.g2_add), k
            assert B.g1_mul(B.G1_GEN, k) == affine_mul(
                B.G1_GEN, k, B.g1_add
            ), k
        assert B.g1_mul(B.G1_GEN, B.R) is None
        assert B.g2_mul(B.G2_GEN, B.R) is None


class TestPairing:
    def test_bilinearity_and_order(self):
        e1 = B.pairing(B.G1_GEN, B.G2_GEN)
        assert e1 != B.FP12_ONE  # non-degenerate
        assert _fp12_pow(e1, B.R) == B.FP12_ONE  # lands in GT
        a, b = 31337, 271828
        eab = B.pairing(B.g1_mul(B.G1_GEN, a), B.g2_mul(B.G2_GEN, b))
        assert eab == _fp12_pow(e1, a * b % B.R)

    def test_product_check_shape(self):
        # e(-g1, k*Q) * e(k*g1, Q) == 1: the verification identity
        k = 424242
        assert B.pairings_equal_one([
            (B.g1_neg(B.G1_GEN), B.g2_mul(B.G2_GEN, k)),
            (B.g1_mul(B.G1_GEN, k), B.G2_GEN),
        ])
        assert not B.pairings_equal_one([
            (B.g1_neg(B.G1_GEN), B.g2_mul(B.G2_GEN, k + 1)),
            (B.g1_mul(B.G1_GEN, k), B.G2_GEN),
        ])


class TestHashToCurve:
    def test_expand_message_xmd_rfc9380_vectors(self):
        # RFC 9380 Appendix K.1 (SHA-256, len_in_bytes = 0x20)
        dst = b"QUUX-V01-CS02-with-expander-SHA256-128"
        assert B.expand_message_xmd(b"", dst, 0x20).hex() == (
            "68a985b87eb6b46952128911f2a4412bbc302a9d759667f8"
            "7f7a21d803f07235"
        )
        assert B.expand_message_xmd(b"abc", dst, 0x20).hex() == (
            "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b979"
            "02f53a8a0d605615"
        )

    def test_sswu_and_isogeny_land_on_curves(self):
        # SSWU output on E2' and iso_map output on E2: validates the
        # transcribed isogeny constant block (a wrong rational-map
        # coefficient lands off-curve with overwhelming probability)
        u0, u1 = B.hash_to_field_fp2(b"constants check", B.DST_SIG, 2)
        for u in (u0, u1):
            x, y = B._sswu_fp2(u)
            lhs = B.fp2_sq(y)
            rhs = B.fp2_add(
                B.fp2_add(B.fp2_mul(B.fp2_sq(x), x),
                          B.fp2_mul(B.SSWU_A, x)),
                B.SSWU_B,
            )
            assert lhs == rhs, "SSWU output off E2'"
            assert B.g2_on_curve(B._iso_map_g2((x, y))), (
                "isogeny output off E2"
            )

    def test_hash_to_curve_structural(self):
        h = B.hash_to_curve_g2(b"vote: block 9")
        assert h is not None and B.g2_on_curve(h)
        assert B.g2_in_subgroup(h), "cofactor clearing failed"
        assert B.hash_to_curve_g2(b"vote: block 9") == h  # deterministic
        assert B.hash_to_curve_g2(b"vote: block 10") != h
        # domain separation: same message, different DST
        assert B.hash_to_curve_g2(b"vote: block 9", B.DST_POP) != h

    def test_g1_non_subgroup_point_rejected(self):
        """Review finding (round 12): g1_in_subgroup must multiply by
        the UNREDUCED order — g1_mul reduces mod r, making the check
        0*P == infinity, vacuously true for every on-curve point (the
        small-subgroup hole: G1's cofactor is ~2^125). A curve point
        outside the r-torsion must fail the check, fail decompression,
        and fail signature verification as a pubkey."""
        x = None
        for cand in range(2, 50):
            y = B.fp_sqrt((cand**3 + B.B1) % B.P)
            if y is None:
                continue
            pt = (cand, y)
            if not B.g1_in_subgroup(pt):
                x = pt
                break
        assert x is not None, "no small non-subgroup point found"
        assert B.g1_on_curve(x)
        with pytest.raises(ValueError):
            B.g1_decompress(B.g1_compress(x))
        sk = B.keygen(b"\x66" * 32)
        sig = B.sign(sk, b"m")
        assert not B.verify(B.g1_compress(x), sig, b"m")
        # and the generator (a genuine subgroup member) still passes
        assert B.g1_in_subgroup(B.G1_GEN)

    def test_pre_clear_point_usually_outside_subgroup(self):
        # iso_map output before clear_cofactor is in E2(Fp2) but (with
        # overwhelming probability) NOT in the r-torsion — the subgroup
        # check must reject its compression (serialization safety)
        u0, _ = B.hash_to_field_fp2(b"raw point", B.DST_SIG, 2)
        raw = B._iso_map_g2(B._sswu_fp2(u0))
        assert B.g2_on_curve(raw)
        assert not B.g2_in_subgroup(raw)
        with pytest.raises(ValueError):
            B.g2_decompress(B.g2_compress(raw))


class TestSignatures:
    def test_sign_verify_roundtrip(self):
        sk = B.keygen(b"\x42" * 32)
        pk = B.sk_to_pk(sk)
        sig = B.sign(sk, b"committee vote payload")
        assert B.verify(pk, sig, b"committee vote payload")
        assert not B.verify(pk, sig, b"other payload")
        sk2 = B.keygen(b"\x43" * 32)
        assert not B.verify(B.sk_to_pk(sk2), sig, b"committee vote payload")

    def test_keygen_is_cfrg_shaped(self):
        # deterministic, nonzero, < r, and sensitive to IKM/key_info
        assert B.keygen(b"\x01" * 32) == B.keygen(b"\x01" * 32)
        assert 0 < B.keygen(b"\x01" * 32) < B.R
        assert B.keygen(b"\x01" * 32) != B.keygen(b"\x02" * 32)
        assert B.keygen(b"\x01" * 32) != B.keygen(b"\x01" * 32, b"info")
        with pytest.raises(ValueError):
            B.keygen(b"short")

    def test_malformed_signatures_rejected(self):
        sk = B.keygen(b"\x44" * 32)
        pk = B.sk_to_pk(sk)
        msg = b"m"
        sig = B.sign(sk, msg)
        assert not B.verify(pk, sig[:-1], msg)  # truncated
        assert not B.verify(pk, b"\x00" * 96, msg)  # not compressed-flagged
        infinity = bytes([0xC0]) + b"\x00" * 95
        assert not B.verify(pk, infinity, msg)  # infinity signature
        inf_pk = bytes([0xC0]) + b"\x00" * 47
        assert not B.verify(inf_pk, sig, msg)  # identity pubkey
        # flipped sign bit selects the other root -> verification fails
        flipped = bytes([sig[0] ^ 0x20]) + sig[1:]
        assert not B.verify(pk, flipped, msg)

    def test_serialization_roundtrips(self):
        sk = B.keygen(b"\x45" * 32)
        p1 = B.g1_mul(B.G1_GEN, sk)
        assert B.g1_decompress(B.g1_compress(p1)) == p1
        p2 = B.g2_mul(B.G2_GEN, sk)
        assert B.g2_decompress(B.g2_compress(p2)) == p2
        assert B.g1_decompress(B.g1_compress(None)) is None
        assert B.g2_decompress(B.g2_compress(None)) is None
        neg = B.g1_neg(p1)  # same x, other sign bit
        assert B.g1_decompress(B.g1_compress(neg)) == neg
        with pytest.raises(ValueError):
            B.g1_decompress((B.P).to_bytes(48, "big"))  # x >= p, no flag
        with pytest.raises(ValueError):
            B.g2_decompress(b"\x00" * 96)


class TestAggregation:
    def test_aggregate_verify(self):
        msg = b"commit block 77"
        sks = [B.keygen(bytes([i]) * 32) for i in range(1, 7)]
        pks = [B.sk_to_pk(sk) for sk in sks]
        sigs = [B.sign(sk, msg) for sk in sks]
        agg = B.aggregate(sigs)
        assert B.aggregate_verify(pks, msg, agg)
        assert not B.aggregate_verify(pks[:-1], msg, agg)  # missing member
        assert not B.aggregate_verify(pks, b"forged", agg)
        assert not B.aggregate_verify([], msg, agg)
        # partial aggregate of a subset verifies against that subset
        sub = B.aggregate(sigs[:3])
        assert B.aggregate_verify(pks[:3], msg, sub)

    def test_aggregate_verify_distinct_messages(self):
        sks = [B.keygen(bytes([i]) * 32) for i in range(1, 5)]
        pks = [B.sk_to_pk(sk) for sk in sks]
        msgs = [b"m%d" % i for i in range(4)]
        agg = B.aggregate([B.sign(sk, m) for sk, m in zip(sks, msgs)])
        assert B.aggregate_verify_distinct(pks, msgs, agg)
        assert not B.aggregate_verify_distinct(
            pks, [msgs[0]] * 4, agg
        )

    def test_rogue_key_attack_blocked_by_pop(self):
        """The attack the PoP registry exists for: the adversary
        registers pk_rogue = pk_evil - pk_victim, making the two-member
        aggregate equal its own key — it then forges the 'committee'
        signature ALONE. Without the PoP gate the forgery verifies;
        with it, the rogue key can never enter an accepted aggregate."""
        msg = b"steal the committee"
        sk_victim = B.keygen(b"\x51" * 32)
        pk_victim = B.sk_to_pk(sk_victim)
        sk_evil = B.keygen(b"\x52" * 32)
        rogue_pt = B.g1_add(
            B.g1_mul(B.G1_GEN, sk_evil),
            B.g1_neg(B.g1_decompress(pk_victim)),
        )
        pk_rogue = B.g1_compress(rogue_pt)
        forged = B.sign(sk_evil, msg)  # the adversary signs ALONE

        # the attack works at the raw math layer (victim never signed!)
        assert B.aggregate_verify([pk_victim, pk_rogue], msg, forged)

        # ... and is blocked at the SPI layer: the rogue key has no
        # known secret, so no valid proof of possession can exist
        assert not crypto.aggregate_verify(
            [pk_victim, pk_rogue], msg, forged
        )
        pop_victim = B.pop_prove(sk_victim)
        assert B.pop_verify(pk_victim, pop_victim)
        assert not B.pop_verify(pk_rogue, pop_victim)
        # an unrelated signature under the SIG DST is not a PoP either
        assert not B.pop_verify(pk_rogue, forged)
        assert crypto.bls_register_key(pk_victim, pop_victim)
        assert not crypto.bls_register_key(pk_rogue, forged)
        assert not crypto.aggregate_verify(
            [pk_victim, pk_rogue], msg, forged
        )


class TestCryptoSPI:
    def test_scheme_registered(self):
        assert crypto.find_signature_scheme(7) is BLS_BLS12381
        assert crypto.find_signature_scheme("BLS_BLS12381") is BLS_BLS12381
        assert crypto.is_operational(BLS_BLS12381)

    def test_generate_sign_verify(self):
        kp = crypto.generate_keypair(BLS_BLS12381)
        assert len(kp.public.encoded) == 48
        sig = crypto.do_sign(kp.private, b"spi payload")
        assert len(sig) == 96
        assert crypto.is_valid(kp.public, sig, b"spi payload")
        assert crypto.do_verify(kp.public, sig, b"spi payload")
        assert not crypto.is_valid(kp.public, sig, b"tampered")
        with pytest.raises(crypto.SignatureError):
            crypto.do_verify(kp.public, sig, b"tampered")
        assert crypto.public_key_on_curve(kp.public)

    def test_deterministic_derivation(self):
        a = crypto.derive_keypair_from_entropy(BLS_BLS12381, 999)
        b = crypto.derive_keypair_from_entropy(BLS_BLS12381, 999)
        c = crypto.derive_keypair_from_entropy(BLS_BLS12381, 1000)
        assert a.public.encoded == b.public.encoded
        assert a.public.encoded != c.public.encoded

    def test_spi_aggregate_requires_pop_registration(self):
        msg = b"spi committee"
        kps = [crypto.generate_keypair(BLS_BLS12381) for _ in range(3)]
        agg = crypto.aggregate(
            [crypto.do_sign(k.private, msg) for k in kps]
        )
        pubs = [k.public for k in kps]
        assert not crypto.aggregate_verify(pubs, msg, agg)  # unregistered
        assert crypto.aggregate_verify(
            pubs, msg, agg, require_pop=False
        )
        for k in kps:
            assert crypto.bls_register_key(
                k.public, crypto.bls_prove_possession(k.private)
            )
        assert crypto.aggregate_verify(pubs, msg, agg)
        assert not crypto.aggregate_verify(pubs, b"forged", agg)

    def test_aggregate_rejects_non_bls_keys(self):
        from corda_tpu.core.crypto.schemes import EDDSA_ED25519_SHA512

        ed = crypto.generate_keypair(EDDSA_ED25519_SHA512)
        with pytest.raises(crypto.UnsupportedSchemeError):
            crypto.aggregate_verify([ed.public], b"m", b"\x00" * 96)


# --- the aggregating BFT committee -------------------------------------------

class _DictMeta:
    def __init__(self):
        self._d = {}

    def get(self, k):
        return self._d.get(k)

    def put(self, k, v):
        self._d[k] = v


class _BLSCluster:
    """Deterministic in-memory PBFT committee with BLS vote keys (the
    test_bft harness shape, aggregating mode)."""

    def __init__(self, n=4, bls_members=None, tamper=()):
        from corda_tpu.core.serialization.codec import deserialize, serialize

        self._ser, self._deser = serialize, deserialize
        self.queue = deque()
        self.n = n
        self.uniqueness = {i: {} for i in range(n)}
        self.replicas = []
        self.client = BFTClient("client-0", n, self._client_send)
        sks, pubs, pops = dev_bls_committee(n)
        members = set(range(n) if bls_members is None else bls_members)
        pubs = {i: pubs[i] for i in members}
        pops = {i: pops[i] for i in members}
        for i in range(n):
            self.replicas.append(
                self._make_replica(i, sks, pubs, pops, i in members)
            )
        for i in tamper:
            # a Byzantine member signing under a WRONG secret: votes have
            # valid shape but fail the aggregate (and individual) check
            self.replicas[i]._bls_sk = 12345

    def _make_replica(self, idx, sks, pubs, pops, has_key):
        def apply(command):
            conflicts = {}
            umap = self.uniqueness[idx]
            for key, txid in command["entries"].items():
                if key in umap and umap[key] != txid:
                    conflicts[key] = umap[key]
            if not conflicts:
                umap.update(command["entries"])
            return {"conflicts": conflicts}

        def transport(dst, payload):
            self.queue.append(("replica", idx, dst, payload))

        def reply(client_id, request_id, result):
            self.queue.append(("reply", idx, request_id, result))

        return BFTReplica(
            idx, self.n, transport, apply, reply,
            meta_store=_DictMeta(),
            bls_signing_key=sks[idx] if has_key else None,
            replica_bls_pubs=pubs,
            replica_bls_pops=pops,
        )

    def _client_send(self, replica_id, request):
        self.queue.append(("request", None, replica_id, request))

    def pump(self, max_rounds=5000):
        rounds = 0
        while self.queue and rounds < max_rounds:
            kind, a, b, c = self.queue.popleft()
            rounds += 1
            if kind == "replica":
                self.replicas[b].on_message(a, c)
            elif kind == "request":
                self.replicas[b].on_request(c)
            elif kind == "reply":
                self.client.on_reply(a, b, c)

    def submit(self, entries):
        fut = self.client.submit({"kind": "putall", "entries": entries})
        self.pump()
        return fut.result(timeout=5)


class TestAggregatingCommittee:
    def test_commit_uses_one_aggregate_check_per_block(self):
        c = _BLSCluster(n=4)
        assert all(r.vote_scheme == "bls" for r in c.replicas)
        result = c.submit({"k1": "tx-1"})
        assert result == {"conflicts": {}}
        for r in c.replicas:
            assert r.agg_checks >= 1
            assert r.vote_verifies == 0, (
                "per-vote verifies ran in aggregate mode"
            )
        # every replica applied the entry
        assert all(c.uniqueness[i].get("k1") == "tx-1" for i in range(4))

    def test_byzantine_vote_falls_back_to_individual_and_commits(self):
        c = _BLSCluster(n=4, tamper=(1,))
        result = c.submit({"k2": "tx-2"})
        assert result == {"conflicts": {}}  # 3 honest of 4: quorum holds
        # at least one replica had to drop to per-vote verification
        assert sum(r.vote_verifies for r in c.replicas) > 0

    def test_missing_member_key_falls_back_to_ed25519(self):
        c = _BLSCluster(n=4, bls_members={0, 1, 2})  # member 3 lacks BLS
        assert all(r.vote_scheme == "ed25519" for r in c.replicas)
        result = c.submit({"k3": "tx-3"})
        assert result == {"conflicts": {}}
        assert all(r.agg_checks == 0 for r in c.replicas)

    def test_conflict_verdict_consistent_in_bls_mode(self):
        c = _BLSCluster(n=4)
        assert c.submit({"kx": "tx-a"}) == {"conflicts": {}}
        result = c.submit({"kx": "tx-b"})
        assert result["conflicts"] == {"kx": "tx-a"}

    def test_view_change_carries_aggregated_certificates(self):
        c = _BLSCluster(n=4)
        assert c.submit({"kv": "tx-v"}) == {"conflicts": {}}
        certs = c.replicas[1]._prepared_certificates()
        assert certs, "prepared entry missing after commit"
        for seq, d, request, view, cert in certs:
            assert cert[0] == "bls"
            voters, agg = cert[1], cert[2]
            assert len(voters) >= 3  # 2f+1
            # the aggregated certificate verifies as ONE check
            assert c.replicas[2]._cert_voters(view, seq, d, cert) == set(
                voters
            )
            # and a tampered aggregate yields NO voters
            bad = ["bls", voters, agg[:-1] + bytes([agg[-1] ^ 1])]
            assert c.replicas[2]._cert_voters(view, seq, d, bad) == set()


class TestMockNetworkBLSNotary:
    def test_bls_committee_notarises_and_reports_stats(self):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.core.transactions.builder import TransactionBuilder
        from corda_tpu.finance.cash import CashCommand, CashState
        from corda_tpu.node.notary import NotaryClientFlow
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members, bus = net.create_bft_notary_cluster(
            n_members=4, vote_scheme="bls"
        )
        bank = net.create_node("O=BLSBank,L=London,C=GB")
        try:
            token = Issued(bank.info.ref(1), "USD")
            b = TransactionBuilder(notary=cluster)
            b.add_output_state(
                CashState(amount=Amount(500, token), owner=bank.info)
            )
            b.add_command(CashCommand.Issue(), bank.info.owning_key)
            issue = bank.services.sign_initial_transaction(b)
            bank.services.record_transactions([issue])
            b2 = TransactionBuilder(notary=cluster)
            b2.add_input_state(issue.tx.out_ref(0))
            b2.add_output_state(
                CashState(amount=Amount(500, token), owner=bank.info)
            )
            b2.add_command(CashCommand.Move(), bank.info.owning_key)
            stx = bank.services.sign_initial_transaction(b2)
            h = bank.start_flow(
                NotaryClientFlow(stx, notary_validating=False), stx
            )
            net.run_network()
            sigs = h.result.result(timeout=30)
            assert len(sigs) >= 2  # f+1
            stats = members[0].notary_service.uniqueness_provider.vote_stats()
            assert stats["vote_scheme"] == "bls"
            assert stats["agg_checks"] >= 1
            assert stats["vote_verifies"] == 0
        finally:
            net.stop_nodes()


# --- batch dispatch grouping (see also tests/test_batch_dispatch.py) --------

class TestBenchStage:
    def test_bls_aggregate_stage_reports_speedup(self):
        import importlib.util
        import os
        import sys

        spec = importlib.util.spec_from_file_location(
            "bench_for_bls", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "bench.py",
            )
        )
        bench = importlib.util.module_from_spec(spec)
        saved = sys.argv
        sys.argv = ["bench.py"]
        try:
            spec.loader.exec_module(bench)
        finally:
            sys.argv = saved
        out = bench._bls_aggregate_stage(n=8)
        assert out["bls_committee_n"] == 8
        assert out["bls_aggregate_verify_ms"] > 0
        assert out["bls_naive_wall_ms"] > out["bls_aggregate_verify_ms"]
        # n=8 already shows a clear win; the bench's n=64 stage is the
        # acceptance measurement (>= 10x)
        assert out["bls_aggregate_speedup_x"] >= 2


# --- jax kernels -------------------------------------------------------------

class TestJaxTower:
    """Differential tests of the stacked-coefficient tower against the
    pure-Python mirror (small batches; the full pairing is @slow)."""

    def _rand_fp2(self, rng):
        return (rng.randrange(B.P), rng.randrange(B.P))

    def test_fp2_ops_match_mirror(self):
        import jax

        from corda_tpu.ops import field_bls12 as FB

        rng = random.Random(21)
        a2 = [self._rand_fp2(rng) for _ in range(4)]
        b2 = [self._rand_fp2(rng) for _ in range(4)]
        A = np.stack([FB.fp2_to_mont(v) for v in a2])
        Bb = np.stack([FB.fp2_to_mont(v) for v in b2])
        cases = [
            (FB.fp2_mul, B.fp2_mul, True),
            (FB.fp2_add, B.fp2_add, True),
            (FB.fp2_sub, B.fp2_sub, True),
            (FB.fp2_inv, B.fp2_inv, False),
            (FB.fp2_mul_xi, B.fp2_mul_xi, False),
        ]
        for jfn, rfn, binary in cases:
            out = np.asarray(
                jax.jit(jfn)(A, Bb) if binary else jax.jit(jfn)(A)
            )
            for i in range(4):
                want = rfn(a2[i], b2[i]) if binary else rfn(a2[i])
                assert FB.fp2_from_mont(out[i]) == want, (rfn.__name__, i)

    def test_fp2_edge_cases(self):
        import jax

        from corda_tpu.ops import field_bls12 as FB

        edges = [(0, 0), (B.P - 1, B.P - 1), (1, 0), (B.P - 1, 1)]
        E = np.stack([FB.fp2_to_mont(v) for v in edges])
        for jfn, rfn in [
            (FB.fp2_add, B.fp2_add), (FB.fp2_sub, B.fp2_sub),
            (FB.fp2_mul, B.fp2_mul),
        ]:
            out = np.asarray(jax.jit(jfn)(E, E))
            for i, e in enumerate(edges):
                assert FB.fp2_from_mont(out[i]) == rfn(e, e)

    def test_fp12_mul_and_frobenius_match_mirror(self):
        import jax

        from corda_tpu.ops import field_bls12 as FB

        rng = random.Random(22)

        def rand12():
            return tuple(
                tuple(self._rand_fp2(rng) for _ in range(3))
                for _ in range(2)
            )

        a12 = [rand12() for _ in range(2)]
        b12 = [rand12() for _ in range(2)]
        A = np.stack([FB.fp12_to_mont(v) for v in a12])
        Bb = np.stack([FB.fp12_to_mont(v) for v in b12])
        out = np.asarray(jax.jit(FB.fp12_mul)(A, Bb))
        for i in range(2):
            assert FB.fp12_from_mont(out[i]) == B.fp12_mul(a12[i], b12[i])
        out = np.asarray(jax.jit(FB.fp12_frob)(A))
        for i in range(2):
            assert FB.fp12_from_mont(out[i]) == B.fp12_frob(a12[i])
        one = FB.fp12_to_mont(B.FP12_ONE)
        arr = np.stack([one, FB.fp12_to_mont(a12[0])])
        assert list(np.asarray(jax.jit(FB.fp12_eq_one)(arr))) == [
            True, False,
        ]


@pytest.mark.slow
class TestJaxPairing:
    """Full batched pairing differential tests: expensive XLA compiles
    (minutes cold, persistent-cached after), excluded from tier-1."""

    def test_pairing_batch_matches_mirror(self):
        from corda_tpu.ops import bls12_batch as BB

        ps, qs = [], []
        for k in (7, 123456789):
            ps.append(B.g1_mul(B.G1_GEN, k))
            qs.append(B.g2_mul(B.G2_GEN, k + 3))
        got = BB.pairing_batch(ps, qs)
        for i in range(2):
            assert got[i] == B.pairing(ps[i], qs[i]), i

    def test_verify_pairs_batch_and_device_aggregate(self):
        from corda_tpu.ops import bls12_batch as BB

        msg = b"device committee block"
        sks = [B.keygen(bytes([40 + i]) * 32) for i in range(4)]
        pks = [B.sk_to_pk(sk) for sk in sks]
        sigs = [B.sign(sk, msg) for sk in sks]
        h = B.hash_to_curve_g2(msg)
        rows1, rows2 = [], []
        for pk, sig in zip(pks, sigs):
            rows1.append((B.g1_neg(B.G1_GEN), B.g2_decompress(sig)))
            rows2.append((B.g1_decompress(pk), h))
        # tamper the last row's signature point
        rows1[-1] = (rows1[-1][0], B.g2_mul(rows1[-1][1], 2))
        out = BB.verify_pairs_batch(rows1, rows2)
        assert out == [True, True, True, False]
        # the committee aggregate through the device kernel
        agg = B.aggregate(sigs)
        assert BB.aggregate_verify_device(pks, msg, agg)
        assert not BB.aggregate_verify_device(pks, b"forged", agg)

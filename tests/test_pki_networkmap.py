"""PKI hierarchy + network-map protocol unit tests.

Reference parity targets: `X509Utilities.kt:28-235` (3-level chain, CSR),
`NetworkMapService.kt:65-71` (signed registrations, serial ordering,
subscription push), `ArtemisMessagingServer.kt:299-412` (bridge
store-and-forward retry).
"""
import time

import pytest

from corda_tpu.core.crypto import crypto, pki
from corda_tpu.core.identity import Party
from corda_tpu.messaging import Broker
from corda_tpu.node.networkmap import (
    ADD,
    BridgeManager,
    NetworkMapClient,
    NetworkMapService,
    NodeRegistration,
    SignedRegistration,
    sign_registration,
)

pytestmark = pytest.mark.skipif(
    not pki.OPENSSL_AVAILABLE,
    reason="X.509 PKI requires the 'cryptography' package",
)

ALICE_KP = crypto.entropy_to_keypair(301)
BOB_KP = crypto.entropy_to_keypair(302)
ALICE = Party("O=Alice,L=London,C=GB", ALICE_KP.public)
BOB = Party("O=Bob,L=Paris,C=FR", BOB_KP.public)


def _reg(party, addr="127.0.0.1:1", serial=1, expires=None):
    return NodeRegistration(
        party, addr, (), serial,
        time.time() + 600 if expires is None else expires,
    )


class TestPKI:
    def test_three_level_chain_and_validation(self, tmp_path):
        entries = pki.dev_certificates(str(tmp_path), "O=Node,L=X,C=GB")
        assert pki.verify_chain(
            entries[pki.CORDA_TLS].cert,
            [entries[pki.CORDA_CLIENT_CA].cert,
             entries[pki.CORDA_INTERMEDIATE_CA].cert],
            entries[pki.CORDA_ROOT_CA].cert,
        )

    def test_wrong_root_rejected(self, tmp_path):
        entries = pki.dev_certificates(str(tmp_path / "a"), "O=Node,L=X,C=GB")
        other = pki.create_self_signed_ca("Other Root")
        assert not pki.verify_chain(
            entries[pki.CORDA_TLS].cert,
            [entries[pki.CORDA_CLIENT_CA].cert,
             entries[pki.CORDA_INTERMEDIATE_CA].cert],
            other.cert,
        )

    def test_shared_dir_shares_root_but_not_leaves(self, tmp_path):
        e1 = pki.dev_certificates(str(tmp_path), "O=A,L=X,C=GB")
        e2 = pki.dev_certificates(str(tmp_path), "O=B,L=Y,C=FR")
        assert e1[pki.CORDA_ROOT_CA].cert == e2[pki.CORDA_ROOT_CA].cert
        assert e1[pki.CORDA_TLS].cert != e2[pki.CORDA_TLS].cert

    def test_csr_flow(self, tmp_path):
        entries = pki.dev_certificates(str(tmp_path), "O=CA,L=X,C=GB")
        csr, _key = pki.create_csr("O=Applicant,L=Z,C=DE")
        cert = pki.sign_csr(entries[pki.CORDA_INTERMEDIATE_CA], csr, is_ca=True)
        assert pki.verify_chain(
            cert,
            [entries[pki.CORDA_INTERMEDIATE_CA].cert],
            entries[pki.CORDA_ROOT_CA].cert,
        )


class TestNetworkMapService:
    def setup_method(self):
        self.broker = Broker()
        self.svc = NetworkMapService(self.broker).start()

    def teardown_method(self):
        self.svc.stop()
        self.broker.close()

    def _register(self, signed):
        ok, reason = self.svc._process_registration(signed)
        return ok, reason

    def test_valid_registration_accepted(self):
        ok, _ = self._register(sign_registration(_reg(ALICE), ALICE_KP.private))
        assert ok
        assert len(self.svc.entries()) == 1

    def test_forged_signature_rejected(self):
        # Bob signs a registration claiming to be Alice.
        forged = sign_registration(_reg(ALICE), BOB_KP.private)
        ok, reason = self._register(forged)
        assert not ok and reason == "bad signature"

    def test_stale_serial_rejected(self):
        assert self._register(
            sign_registration(_reg(ALICE, serial=5), ALICE_KP.private)
        )[0]
        ok, reason = self._register(
            sign_registration(_reg(ALICE, addr="127.0.0.1:9", serial=4),
                              ALICE_KP.private)
        )
        assert not ok and reason == "stale serial"

    def test_expired_rejected(self):
        ok, reason = self._register(
            sign_registration(_reg(ALICE, expires=time.time() - 5),
                              ALICE_KP.private)
        )
        assert not ok and reason == "expired"

    def test_identical_reregistration_is_unchanged_no_persist(self):
        """Fast shared-identity refreshes re-register every few seconds as
        a liveness signal; an operationally identical entry far from
        expiry must be acked WITHOUT rewriting the map or re-pushing."""
        far = time.time() + 24 * 3600  # production TTL, far from expiry
        ok, reason = self._register(
            sign_registration(_reg(ALICE, serial=5, expires=far),
                              ALICE_KP.private)
        )
        assert ok and reason is None
        entry_before = self.svc.entries()[0]
        ok, reason = self._register(
            sign_registration(_reg(ALICE, serial=6, expires=far),
                              ALICE_KP.private)
        )
        assert ok and reason == "unchanged"
        # the stored entry (incl. serial) did not churn
        assert self.svc.entries()[0].registration.serial == (
            entry_before.registration.serial
        )
        # a CHANGED address still replaces the entry
        ok, reason = self._register(
            sign_registration(_reg(ALICE, addr="127.0.0.1:9999", serial=7,
                                   expires=far),
                              ALICE_KP.private)
        )
        assert ok and reason is None
        assert self.svc.entries()[0].registration.broker_address == (
            "127.0.0.1:9999"
        )

    def test_client_register_fetch_and_push(self):
        learned = []
        alice_client = NetworkMapClient(
            self.broker, ALICE, "127.0.0.1:1", (), ALICE_KP.private,
            on_entry=lambda reg: learned.append(reg.party.name),
        )
        assert alice_client.register_and_fetch() == 0  # alone so far
        bob_learned = []
        bob_client = NetworkMapClient(
            self.broker, BOB, "127.0.0.1:2", ("corda.notary",), BOB_KP.private,
            on_entry=lambda reg: bob_learned.append(reg.party.name),
        )
        assert bob_client.register_and_fetch() == 1  # sees alice
        assert bob_learned == [ALICE.name]
        # alice hears about bob via push
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not learned:
            time.sleep(0.05)
        assert learned == [BOB.name]
        alice_client.stop()
        bob_client.stop()


class TestBridgeManager:
    def test_store_and_forward_retry(self):
        """Messages queue while the peer is down and deliver on recovery."""
        from corda_tpu.messaging.net import BrokerServer, RemoteBroker

        local = Broker()
        bridges = BridgeManager(local)
        peer_broker = Broker()
        peer_broker.create_queue("p2p.inbound.O=Peer")
        # route points at a port with nothing listening yet
        import socket as _socket

        probe = _socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        bridges.set_route("O=Peer", f"127.0.0.1:{port}")
        local.create_queue(bridges.outbound_queue("O=Peer"))
        local.send(bridges.outbound_queue("O=Peer"), b"m1", {"topic": "t"})
        time.sleep(0.8)  # forwarder is failing + retrying
        server = BrokerServer(peer_broker, port=port).start()
        try:
            consumer = peer_broker.create_consumer("p2p.inbound.O=Peer")
            msg = consumer.receive(timeout=10)
            assert msg is not None and msg.payload == b"m1"
            assert msg.headers["topic"] == "t"
        finally:
            bridges.stop()
            server.stop()
            local.close()
            peer_broker.close()


class TestChainConstraints:
    def test_leaf_cannot_mint_certificates(self, tmp_path):
        """A TLS LEAF key must not be able to issue certs that validate —
        verify_chain enforces CA BasicConstraints on every issuer
        (round-2 review finding)."""
        entries = pki.dev_certificates(str(tmp_path), "O=Node,L=X,C=GB")
        leaf = entries[pki.CORDA_TLS]
        forged = pki._build_cert_from_public(
            pki._name("O=Mallory,L=X,C=GB"),
            pki._new_key().public_key(),
            leaf,  # leaf acting as a CA
            False,
        )
        assert not pki.verify_chain(
            forged,
            [leaf.cert, entries[pki.CORDA_CLIENT_CA].cert,
             entries[pki.CORDA_INTERMEDIATE_CA].cert],
            entries[pki.CORDA_ROOT_CA].cert,
        )

    def test_issuer_subject_mismatch_rejected(self, tmp_path):
        e1 = pki.dev_certificates(str(tmp_path / "a"), "O=A,L=X,C=GB")
        e2 = pki.dev_certificates(str(tmp_path / "b"), "O=B,L=X,C=GB")
        # splice another tree's intermediate into the path
        assert not pki.verify_chain(
            e1[pki.CORDA_TLS].cert,
            [e1[pki.CORDA_CLIENT_CA].cert, e2[pki.CORDA_INTERMEDIATE_CA].cert],
            e2[pki.CORDA_ROOT_CA].cert,
        )

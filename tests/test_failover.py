"""Verifier failover: deadlines, redispatch, circuit breaker, in-process
fallback, and the deterministic fault-injection seams that prove them
(docs/robustness.md).

The headline invariant (ISSUE 4 acceptance): with fault injection
crashing the SOLE verifier worker after ack, every in-flight
verify_signatures future still completes — zero hung futures — and the
health surface reflects the tripped (then recovered) breaker.
"""
import json
import time
import urllib.request

import pytest

from corda_tpu.core.crypto import crypto
from corda_tpu.messaging import Broker
from corda_tpu.messaging.broker import UnknownQueueError
from corda_tpu.testing import faults
from corda_tpu.utils import faultpoints
from corda_tpu.verifier import (
    CircuitBreaker,
    OutOfProcessTransactionVerifierService,
    VerificationError,
    VerificationTimeoutError,
    VerifierWorker,
    backoff_delay,
)


def _items(n, entropy0=7000):
    items = []
    for i in range(n):
        kp = crypto.entropy_to_keypair(entropy0 + i)
        content = b"failover-msg-%d" % i
        items.append((kp.public, crypto.do_sign(kp.private, content), content))
    return items


def _ltx():
    """A minimal valid LedgerTransaction (local contract/state types:
    importing another test module's helpers would re-register its codec
    adapters under a second module name in full-suite runs)."""
    from dataclasses import dataclass
    from typing import List

    from corda_tpu.core.contracts import (
        Contract, ContractState, TypeOnlyCommandData, contract,
    )
    from corda_tpu.core.identity import Party
    from corda_tpu.core.serialization.codec import corda_serializable
    from corda_tpu.core.transactions import TransactionBuilder

    global _FO_TYPES
    try:
        _FO_TYPES
    except NameError:
        @corda_serializable
        @dataclass(frozen=True)
        class FailoverState(ContractState):
            magic: int = 7
            contract_name = "FailoverContract"

            @property
            def participants(self) -> List:
                return []

        @contract(name="FailoverContract")
        class FailoverContract(Contract):
            def verify(self, tx) -> None:
                pass

        @corda_serializable
        @dataclass(frozen=True)
        class FailoverCommand(TypeOnlyCommandData):
            pass

        _FO_TYPES = (FailoverState, FailoverCommand)
    state_cls, cmd_cls = _FO_TYPES
    kp = crypto.entropy_to_keypair(88)
    notary_kp = crypto.entropy_to_keypair(89)
    notary = Party("O=FailoverNotary,L=Zurich,C=CH", notary_kp.public)
    b = TransactionBuilder(notary=notary)
    b.add_output_state(state_cls())
    b.add_command(cmd_cls(), kp.public)
    wtx = b.to_wire_transaction()
    return wtx.to_ledger_transaction(
        resolve_state=lambda ref: (_ for _ in ()).throw(AssertionError),
        resolve_attachment=lambda h: (_ for _ in ()).throw(AssertionError),
    )


# ---------------------------------------------------------------------------
# Fault injector mechanics
# ---------------------------------------------------------------------------

class TestFaultInjector:
    def test_rules_are_scoped_bounded_and_seeded(self):
        fi = faults.FaultInjector(seed=42)
        r1 = fi.rule("broker.send", "drop", match="verifier.", times=2)
        fi.rule("broker.send", "duplicate", times=None)
        # scoped: non-matching queue falls through to the unlimited rule
        assert fi("broker.send", queue="p2p.inbound") == "duplicate"
        # matching queue consumes the bounded rule first
        assert fi("broker.send", queue="verifier.requests") == "drop"
        assert fi("broker.send", queue="verifier.requests") == "drop"
        assert r1.fired == 2
        # exhausted: falls through
        assert fi("broker.send", queue="verifier.requests") == "duplicate"
        # same seed -> same probabilistic decisions
        a = faults.FaultInjector(seed=9)
        b = faults.FaultInjector(seed=9)
        a.rule("p", "x", times=None, probability=0.5)
        b.rule("p", "x", times=None, probability=0.5)
        seq_a = [a("p") for _ in range(32)]
        seq_b = [b("p") for _ in range(32)]
        assert seq_a == seq_b

    def test_inject_scopes_and_restores_the_hook(self):
        assert faultpoints.hook is None
        with faults.inject(seed=1) as fi:
            assert faultpoints.hook is fi
            with faults.inject(seed=2) as inner:
                assert faultpoints.hook is inner
            assert faultpoints.hook is fi
        assert faultpoints.hook is None

    def test_fire_raises_exception_actions(self):
        fi = faults.FaultInjector()
        fi.rule("custom.point", ValueError("boom"), times=1)
        with pytest.raises(ValueError):
            fi.fire("custom.point")
        assert fi.fire("custom.point") is None  # consumed


# ---------------------------------------------------------------------------
# Broker seams
# ---------------------------------------------------------------------------

class TestBrokerFaults:
    def test_send_drop_and_duplicate(self):
        broker = Broker()
        broker.create_queue("q")
        with faults.inject() as fi:
            fi.rule("broker.send", "drop", times=1)
            fi.rule("broker.send", "duplicate", times=1)
            broker.send("q", b"lost")       # dropped
            broker.send("q", b"twice")      # duplicated
        assert broker.message_count("q") == 2
        c = broker.create_consumer("q")
        m1, m2 = c.receive(timeout=1), c.receive(timeout=1)
        assert m1.payload == m2.payload == b"twice"
        assert m1.message_id != m2.message_id
        # dropped sends still honour the queue-must-exist contract
        with faults.inject() as fi:
            fi.rule("broker.send", "drop", times=1)
            with pytest.raises(UnknownQueueError):
                broker.send("nope", b"x")

    def test_send_delay_delivers_later(self):
        broker = Broker()
        broker.create_queue("q")
        with faults.inject() as fi:
            fi.rule("broker.send", ("delay", 0.15), times=1)
            broker.send("q", b"slow")
        assert broker.message_count("q") == 0
        c = broker.create_consumer("q")
        msg = c.receive(timeout=5)
        assert msg is not None and msg.payload == b"slow"

    def test_receive_drop_consumes_and_loses(self):
        broker = Broker()
        broker.create_queue("q")
        broker.send("q", b"a")
        broker.send("q", b"b")
        c = broker.create_consumer("q")
        with faults.inject() as fi:
            fi.rule("broker.receive", "drop", times=1)
            msg = c.receive(timeout=1)
        # the first message vanished; the second arrived normally
        assert msg.payload == b"b"
        assert broker.message_count("q") == 0

    def test_receive_many_honours_the_drop_seam(self):
        """The P2P pump prefers receive_many: the seam must cover it too,
        or pump-path loss injection would silently never fire."""
        broker = Broker()
        broker.create_queue("q")
        for i in range(4):
            broker.send("q", b"m%d" % i)
        c = broker.create_consumer("q")
        with faults.inject() as fi:
            rule = fi.rule("broker.receive", "drop", times=2)
            batch = c.receive_many(10, timeout=1)
        assert rule.fired == 2
        assert [m.payload for m in batch] == [b"m2", b"m3"]


# ---------------------------------------------------------------------------
# Failover primitives
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_trip_cooldown_halfopen_probe_cycle(self):
        now = [0.0]
        cb = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                            clock=lambda: now[0])
        assert cb.state == "closed" and cb.allow_request()
        cb.record_failure()
        assert cb.state == "closed"
        cb.record_failure()
        assert cb.state == "open"
        assert not cb.allow_request()  # failing fast
        now[0] = 1.5
        assert cb.state == "half-open"
        assert cb.allow_request()       # the single probe
        assert not cb.allow_request()   # concurrent requests keep failing over
        cb.record_failure()             # probe failed -> reopen
        assert cb.state == "open"
        now[0] = 3.0
        assert cb.allow_request()
        cb.record_success()
        assert cb.state == "closed"
        assert cb.trips == 2

    def test_direct_trip_and_backoff_shape(self):
        cb = CircuitBreaker(failure_threshold=99)
        cb.trip("worker pool empty")
        assert cb.state == "open"
        assert cb.last_trip_reason == "worker pool empty"
        import random

        rng = random.Random(3)
        delays = [backoff_delay(a, base_s=0.1, cap_s=1.0, rng=rng)
                  for a in range(1, 8)]
        assert all(0.05 <= d <= 1.0 for d in delays)
        # exponential up to the cap (jitter keeps them within [raw/2, raw])
        assert delays[6] <= 1.0


# ---------------------------------------------------------------------------
# The failover service itself
# ---------------------------------------------------------------------------

class TestVerifierFailover:
    def test_kill_sole_worker_after_ack_zero_hung_futures(self):
        """THE acceptance invariant: the nasty crash-after-ack mode (the
        broker believes the request was handled; the response is lost
        forever) on a one-worker pool. Every future must still complete
        within the deadline budget, and the breaker must show the trip."""
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeFailover", deadline_s=0.25, max_retries=1,
        )
        worker = VerifierWorker(broker, name="sole").start()
        try:
            with faults.inject(seed=7) as fi:
                rule = fi.rule("verifier.worker", "crash_after_ack", times=1)
                futures = svc.verify_signatures(_items(8))
                results = [f.result(timeout=10) for f in futures]
            assert rule.fired == 1
            assert worker.crashed
            assert results == [True] * 8
            assert svc.metrics.fallback_served.value >= 1
            hc = svc.healthcheck()
            assert hc["breaker"] in ("open", "half-open")
            assert hc["breaker_trips"] >= 1
            assert hc["fallback_active"] is True
            assert hc["workers"] == 0
            # nothing left supervised
            assert len(svc._inflight) == 0
        finally:
            worker.stop(graceful=False)
            svc.stop()

    def test_crash_before_ack_redelivers_to_survivor(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeRedeliver", deadline_s=5.0,
        )
        doomed = VerifierWorker(broker, name="doomed").start()
        survivor = VerifierWorker(broker, name="survivor").start()
        try:
            with faults.inject() as fi:
                fi.rule("verifier.worker", "crash_before_ack", times=1,
                        match="doomed")
                futures = svc.verify_signatures(_items(4, entropy0=7200))
                assert all(f.result(timeout=10) for f in futures)
            # broker-level redelivery, no deadline needed
            assert svc.metrics.redispatched.value == 0
            assert survivor.verified_count >= 1
        finally:
            doomed.stop(graceful=False)
            survivor.stop()
            svc.stop()

    def test_lost_response_redispatches_to_live_pool(self):
        """crash_after_ack with a SECOND worker alive: the deadline
        supervisor redispatches (same nonce) instead of falling back."""
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeRedispatch", deadline_s=0.25, max_retries=2,
        )
        w1 = VerifierWorker(broker, name="victim").start()
        w2 = VerifierWorker(broker, name="backup").start()
        try:
            with faults.inject() as fi:
                rule = fi.rule("verifier.worker", "crash_after_ack",
                               times=1, match="victim")
                futures = svc.verify_signatures(_items(4, entropy0=7300))
                assert all(f.result(timeout=15) for f in futures)
            assert rule.fired == 1
            assert svc.metrics.redispatched.value >= 1
            assert svc.metrics.fallback_served.value == 0
            assert svc.breaker.state == "closed"  # success closed it
        finally:
            w1.stop(graceful=False)
            w2.stop()
            svc.stop()

    def test_empty_pool_with_fallback_off_still_spends_retry_budget(self):
        """Without a fallback, a momentarily-empty pool must NOT skip
        straight to dead-letter: a worker respawning during the backoff
        window (the chaos worker_kill heal pattern) picks up the retry."""
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeRespawn", deadline_s=0.2, max_retries=3,
            fallback=False,
        )
        try:
            futures = svc.verify_signatures(_items(2, entropy0=7450))
            # wait for the first deadline to fire with zero workers
            deadline = time.monotonic() + 5
            while svc.metrics.redispatched.value == 0:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            worker = VerifierWorker(broker, name="respawned").start()
            assert all(f.result(timeout=15) for f in futures)
            assert svc.metrics.redispatched.value >= 1
            assert svc.metrics.dead_lettered.value == 0
            worker.stop()
        finally:
            svc.stop()

    def test_dead_letter_when_fallback_disabled(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeDeadLetter", deadline_s=0.1, max_retries=1,
            fallback=False,
        )
        try:
            # no workers at all: exhaust the budget, then dead-letter
            futures = svc.verify_signatures(_items(2, entropy0=7400))
            for fut in futures:
                with pytest.raises(VerificationTimeoutError):
                    fut.result(timeout=10)
            assert svc.metrics.dead_lettered.value == 1
            # tx verify: the future RESOLVES to the error (verify contract)
            err = svc.verify(_ltx()).result(timeout=10)
            assert isinstance(err, VerificationTimeoutError)
        finally:
            svc.stop()

    def test_breaker_open_routes_straight_to_fallback_then_recovers(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeRecover", deadline_s=0.2, max_retries=0,
        )
        svc.breaker.cooldown_s = 30.0  # hold open for the assertions below
        try:
            # trip via an empty pool
            futures = svc.verify_signatures(_items(2, entropy0=7500))
            assert all(f.result(timeout=10) for f in futures)
            assert svc.breaker.state == "open"
            served = svc.metrics.fallback_served.value
            # while open: no broker round trip, straight to fallback
            # (queue depth unchanged by the new request)
            qdepth = broker.message_count("verifier.requests")
            futures = svc.verify_signatures(_items(2, entropy0=7500))
            assert all(f.result(timeout=10) for f in futures)
            assert svc.metrics.fallback_served.value == served + 1
            assert broker.message_count("verifier.requests") == qdepth
            # recovery: a worker appears, the cooldown elapses, the next
            # request is the half-open probe and closes the breaker
            worker = VerifierWorker(broker, name="revived").start()
            svc.breaker.cooldown_s = 0.2
            time.sleep(0.25)
            futures = svc.verify_signatures(_items(2, entropy0=7500))
            assert all(f.result(timeout=10) for f in futures)
            assert svc.breaker.state == "closed"
            worker.stop()
        finally:
            svc.stop()

    def test_timed_out_halfopen_probe_reopens_breaker(self):
        """A half-open probe that never gets answered (consumers
        registered but stalled — the broker_partition shape) must
        RE-OPEN the breaker, not wedge it half-open with the probe slot
        consumed forever."""
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeProbe", deadline_s=0.15, max_retries=5,
        )
        svc.breaker.cooldown_s = 0.1
        # a consumer that never consumes: worker_count() > 0, queue stalls
        stalled = VerifierWorker(broker, name="stalled")  # never started
        try:
            svc.breaker.trip("test setup")
            time.sleep(0.12)  # cooldown elapses -> half-open
            assert svc.breaker.state == "half-open"
            futures = svc.verify_signatures(_items(2, entropy0=7650))
            # the probe times out; it must fail over AND count as a
            # breaker failure so the state machine keeps moving
            assert all(f.result(timeout=10) for f in futures)
            assert svc.breaker.state in ("open", "half-open")
            assert svc.breaker.trips >= 2  # the probe timeout re-tripped
            # recovery still possible: a real worker + the next probe
            worker = VerifierWorker(broker, name="real").start()
            time.sleep(0.12)
            futures = svc.verify_signatures(_items(2, entropy0=7650))
            assert all(f.result(timeout=10) for f in futures)
            assert svc.breaker.state == "closed"
            worker.stop()
        finally:
            stalled.stop(graceful=False)
            svc.stop()

    def test_corrupt_response_counted_not_fatal(self):
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeCorrupt", deadline_s=0.3, max_retries=2,
        )
        worker = VerifierWorker(broker, name="corruptor").start()
        try:
            with faults.inject() as fi:
                fi.rule("verifier.worker", "corrupt_response", times=1)
                futures = svc.verify_signatures(_items(3, entropy0=7600))
                # garbage reply is counted; the deadline redispatch (or
                # fallback) still completes the request
                assert all(f.result(timeout=15) for f in futures)
            assert svc.metrics.malformed.value == 1
        finally:
            worker.stop(graceful=False)
            svc.stop()

    def test_stop_drains_pending_futures(self):
        """Satellite: stop() must resolve every registered future so no
        caller blocks past shutdown."""
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeStop", deadline_s=30.0, fallback=False,
        )
        try:
            sig_futures = svc.verify_signatures(_items(2, entropy0=7700))
            tx_future = svc.verify(_ltx())
        finally:
            svc.stop()
        for fut in sig_futures:
            with pytest.raises(VerificationError, match="stopped"):
                fut.result(timeout=1)
        err = tx_future.result(timeout=1)
        assert isinstance(err, VerificationError)
        assert "stopped" in str(err)

    def test_late_duplicate_reply_is_ignored(self):
        """Redispatch reuses the nonce: when BOTH attempts eventually
        answer, the second reply must be dropped, not double-complete."""
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeDup", deadline_s=0.2, max_retries=2,
        )
        try:
            # no worker yet: first deadline fires and redispatches while
            # the request queue holds both copies; then a worker drains
            # both and sends two replies for one nonce
            futures = svc.verify_signatures(_items(2, entropy0=7800))
            time.sleep(0.45)  # one deadline + backoff window
            worker = VerifierWorker(broker, name="late").start()
            assert all(f.result(timeout=15) for f in futures)
            time.sleep(0.3)  # let any duplicate reply arrive
            assert svc.metrics.malformed.value == 0
            assert len(svc._inflight) == 0
            worker.stop()
        finally:
            svc.stop()


# ---------------------------------------------------------------------------
# Health surface end-to-end (node + ops endpoint)
# ---------------------------------------------------------------------------

class TestHealthzReflectsBreaker:
    def test_healthz_breaker_detail(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_VERIFY_DEADLINE", "0.25")
        monkeypatch.setenv("CORDA_TPU_VERIFY_RETRIES", "0")
        from corda_tpu.node.network import InMemoryMessagingNetwork
        from corda_tpu.node.node import AbstractNode, NodeConfiguration

        broker = Broker()
        net = InMemoryMessagingNetwork()
        node = AbstractNode(
            NodeConfiguration(
                my_legal_name="O=Failover,L=London,C=GB",
                verifier_type="OutOfProcess",
                identity_entropy=4242,
                ops_port=0,
            ),
            net.create_endpoint, broker=broker,
        )
        node.start()
        try:
            port = node.ops_server.port

            def healthz():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5
                ) as resp:
                    return json.loads(resp.read())

            body = healthz()
            assert body["checks"]["verifier"]["breaker"] == "closed"
            svc = node.services.transaction_verifier_service
            # sole worker dies after ack -> pool empty -> breaker trips,
            # futures complete via fallback
            worker = VerifierWorker(broker, name="node-sole").start()
            with faults.inject(seed=11) as fi:
                fi.rule("verifier.worker", "crash_after_ack", times=1)
                futures = svc.verify_signatures(_items(4, entropy0=7900))
                assert all(f.result(timeout=10) for f in futures)
            body = healthz()
            assert body["checks"]["verifier"]["breaker"] in (
                "open", "half-open"
            )
            assert body["checks"]["verifier"]["fallback_active"] is True
            # recovery: new worker + cooldown + probe -> closed again
            svc.breaker.cooldown_s = 0.2
            worker2 = VerifierWorker(broker, name="node-revived").start()
            time.sleep(0.25)
            futures = svc.verify_signatures(_items(2, entropy0=7900))
            assert all(f.result(timeout=10) for f in futures)
            assert healthz()["checks"]["verifier"]["breaker"] == "closed"
            worker.stop(graceful=False)
            worker2.stop()
        finally:
            node.stop()


# ---------------------------------------------------------------------------
# Loadtest catalog disruptions (in-process)
# ---------------------------------------------------------------------------

class TestDisruptionCatalog:
    def test_verifier_worker_kill_and_heal(self):
        import random

        from corda_tpu.loadtest.disruption import verifier_worker_kill

        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "nodeDisrupt", deadline_s=1.0,
        )
        workers = [VerifierWorker(broker, name="w0").start()]
        d = verifier_worker_kill(workers, broker, probability=1.0)
        rng = random.Random(0)
        try:
            d.maybe_fire(rng, None, 0)
            assert workers[0]._stop.is_set()
            d.maybe_heal(rng, None, 5)
            assert len(workers) == 2
            futures = svc.verify_signatures(_items(2, entropy0=8000))
            assert all(f.result(timeout=10) for f in futures)
        finally:
            for w in workers:
                w.stop(graceful=False)
            svc.stop()

    def test_broker_partition_drops_until_healed(self):
        import random

        from corda_tpu.loadtest.disruption import broker_partition

        broker = Broker()
        broker.create_queue("verifier.requests")
        d = broker_partition(match="verifier.", probability=1.0)
        rng = random.Random(0)
        d.maybe_fire(rng, None, 0)
        try:
            broker.send("verifier.requests", b"lost")
            assert broker.message_count("verifier.requests") == 0
        finally:
            d.maybe_heal(rng, None, 5)
        assert faultpoints.hook is None
        broker.send("verifier.requests", b"delivered")
        assert broker.message_count("verifier.requests") == 1

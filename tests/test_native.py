"""Native (C++) component tests: batched hashing vs hashlib, journal
format interop with the Python broker journal."""
import hashlib
import os
import struct

import pytest

from corda_tpu import native


def test_native_compiles_and_loads():
    # The image bakes g++; the native backend must actually be active so
    # the hot paths below exercise C++, not the fallback.
    assert native.available()


def test_sha256_many_matches_hashlib():
    msgs = [b"", b"a", b"abc" * 100, os.urandom(4096), b"x" * 55, b"y" * 56,
            b"z" * 63, b"w" * 64, b"v" * 65, os.urandom(119), os.urandom(128)]
    out = native.sha256_many(msgs)
    assert out == [hashlib.sha256(m).digest() for m in msgs]


def test_sha512_many_matches_hashlib():
    msgs = [b"", b"a", b"abc" * 100, os.urandom(4096), b"p" * 111, b"q" * 112,
            b"r" * 127, b"s" * 128, b"t" * 129, os.urandom(255)]
    out = native.sha512_many(msgs)
    assert out == [hashlib.sha512(m).digest() for m in msgs]


def test_sha512_wide_groups_match_hashlib():
    """Groups of >= 8 equal-length messages take the AVX-512 8-way path
    (where the CPU supports it); ragged batches and remainders take the
    scalar loop — every combination must match hashlib."""
    rng = os.urandom
    # 19 same-length (2 x8 groups + 3 scalar), then ragged interleave,
    # then tail-boundary lengths in runs of 8 (x8 with 1- and 2-block
    # shared padding), then an empty-message run
    msgs = [rng(128) for _ in range(19)]
    for i in range(10):
        msgs.append(rng(127 if i % 2 else 128))
    for ln in (111, 112, 120, 64):
        msgs += [rng(ln) for _ in range(8)]
    msgs += [b""] * 8
    out = native.sha512_many(msgs)
    assert out == [hashlib.sha512(m).digest() for m in msgs]


def test_sha512_mod_l_rows_matches_many():
    import numpy as np

    rows = np.frombuffer(os.urandom(24 * 128), np.uint8).reshape(24, 128)
    got = native.sha512_mod_l_rows(rows)
    want = native.sha512_mod_l_many([rows[i].tobytes() for i in range(24)])
    assert np.array_equal(got, want)


def test_sha256_pairs_matches_hashlib():
    nodes = os.urandom(64 * 9)
    out = native.sha256_pairs(nodes)
    for i in range(9):
        assert out[32 * i:32 * (i + 1)] == hashlib.sha256(
            nodes[64 * i:64 * (i + 1)]
        ).digest()


def test_merkle_tree_uses_native_and_matches():
    from corda_tpu.core.crypto import MerkleTree, SecureHash

    leaves = [SecureHash.sha256(b"leaf%d" % i) for i in range(5)]
    root = MerkleTree.get_merkle_tree(leaves)
    # manual recompute with hashlib
    import hashlib as hl

    padded = [l.bytes for l in leaves] + [bytes(32)] * 3
    lvl = padded
    while len(lvl) > 1:
        lvl = [
            hl.sha256(lvl[i] + lvl[i + 1]).digest()
            for i in range(0, len(lvl), 2)
        ]
    assert root.hash.bytes == lvl[0]


class TestNativeJournal:
    def test_append_scan_roundtrip(self, tmp_path):
        path = str(tmp_path / "native.journal")
        j = native.NativeJournal(path)
        j.append(1, b"enqueue-body-1")
        j.append(2, b"ack-1")
        j.append(1, b"enqueue-body-2")
        j.close()
        records = native.NativeJournal.scan(path)
        assert records == [
            (1, b"enqueue-body-1"), (2, b"ack-1"), (1, b"enqueue-body-2"),
        ]

    def test_torn_tail_ignored(self, tmp_path):
        path = str(tmp_path / "torn.journal")
        j = native.NativeJournal(path)
        j.append(1, b"good")
        j.close()
        with open(path, "ab") as fh:
            fh.write(struct.pack(">BI", 1, 9999) + b"partial")
        assert native.NativeJournal.scan(path) == [(1, b"good")]

    def test_python_journal_reads_native_writes(self, tmp_path):
        """The two implementations share one record format."""
        from corda_tpu.messaging.broker import _Journal, _encode_headers

        path = str(tmp_path / "interop.journal")
        j = native.NativeJournal(path)
        mid = "0" * 36
        body = mid.encode() + struct.pack(">I", len(_encode_headers({}))) + \
            _encode_headers({}) + b"payload"
        j.append(1, body)
        j.close()
        msgs = _Journal.replay(path)
        assert len(msgs) == 1
        assert msgs[0].payload == b"payload"
        assert msgs[0].message_id == mid


def test_sha512_mod_l_matches_bigint():
    """Fused prehash: SHA-512 reduced exactly mod the ed25519 group order.

    The C reduction (Horner + 2^252 == -c fold, native/src/sha2_batch.cpp)
    must agree with Python bigint arithmetic on every row — this is
    consensus-critical (reference parity: i2p sc_reduce semantics used by
    Crypto.isValid, Crypto.kt:535-541)."""
    import hashlib

    import numpy as np

    from corda_tpu import native

    L = 2**252 + 27742317777372353535851937790883648493
    rng = np.random.default_rng(11)
    msgs = [rng.bytes(int(rng.integers(0, 300))) for _ in range(512)]
    msgs += [b"", b"\x00" * 128, b"\xff" * 127]
    out = native.sha512_mod_l_many(msgs)
    for i, m in enumerate(msgs):
        h = int.from_bytes(hashlib.sha512(m).digest(), "little") % L
        expect = np.frombuffer(h.to_bytes(32, "little"), np.uint32)
        assert (out[i] == expect).all(), i

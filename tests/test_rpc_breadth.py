"""Widened RPC surface tests: registered flows, tracked flow progress,
chunked attachment streaming (reference CordaRPCOps.kt:61-259 breadth +
Artemis large-message streaming)."""
import urllib.request

import pytest

from corda_tpu.core.flows import FlowLogic, startable_by_rpc
from corda_tpu.core.flows.api import ProgressTracker
from corda_tpu.messaging import Broker
from corda_tpu.rpc import (
    CordaRPCClient,
    CordaRPCOps,
    RPCServer,
    RPCUser,
)
from corda_tpu.testing import MockNetwork


@startable_by_rpc
class TrackedFlow(FlowLogic):
    STEP_A = ProgressTracker.Step("FIRST")
    STEP_B = ProgressTracker.Step("SECOND")

    def __init__(self):
        self.progress_tracker = ProgressTracker(self.STEP_A, self.STEP_B)

    def call(self):
        self.progress_tracker.set_current_step(self.STEP_A)
        self.progress_tracker.set_current_step(self.STEP_B)
        return "tracked-done"
        yield  # pragma: no cover


from corda_tpu.core.flows.api import initiated_by, initiating_flow  # noqa: E402


@initiating_flow
@startable_by_rpc
class TrackedEchoFlow(FlowLogic):
    """Suspends between steps so the second one streams asynchronously."""

    STEP_A = ProgressTracker.Step("ASK")
    STEP_B = ProgressTracker.Step("GOT")

    def __init__(self, peer):
        self.peer = peer
        self.progress_tracker = ProgressTracker(self.STEP_A, self.STEP_B)

    def call(self):
        self.progress_tracker.set_current_step(self.STEP_A)
        reply = yield self.send_and_receive(self.peer, "ping", str)
        self.progress_tracker.set_current_step(self.STEP_B)
        return reply


@initiated_by(TrackedEchoFlow)
class TrackedEchoResponder(FlowLogic):
    def __init__(self, counterparty):
        self.counterparty = counterparty

    def call(self):
        msg = yield self.receive(self.counterparty, str)
        yield self.send(self.counterparty, msg + "-pong")


class TestOverRpcClient:
    """Everything through the real client/server marshal path."""

    def setup_method(self):
        self.net = MockNetwork()
        self.node = self.net.create_node("O=Breadth,L=London,C=GB")
        self.broker = Broker()
        self.ops = CordaRPCOps(self.node.services, self.node.smm)
        self.server = RPCServer(
            self.broker, self.ops, users=[RPCUser("admin", "secret")]
        )
        self.client = CordaRPCClient(self.broker)
        self.conn = self.client.start("admin", "secret")
        self.proxy = self.conn.proxy

    def teardown_method(self):
        self.conn.close()
        self.client.close()
        self.server.stop()
        self.net.stop_nodes()

    def test_registered_flows(self):
        flows = self.proxy.registered_flows()
        assert any(f.endswith("TrackedFlow") for f in flows)
        assert all(isinstance(f, str) for f in flows)

    def test_synchronous_steps_ride_the_snapshot(self):
        flow_id, feed = self.proxy.start_tracked_flow_dynamic("TrackedFlow")
        assert feed.snapshot == ["FIRST", "SECOND"]
        assert self.proxy.flow_result(flow_id, 10) == "tracked-done"

    def test_post_suspension_steps_stream(self):
        peer = self.net.create_node("O=EchoPeer,L=Paris,C=FR")
        self.node.register_peer(peer.info)
        peer.register_peer(self.node.info)
        flow_id, feed = self.proxy.start_tracked_flow_dynamic(
            "TrackedEchoFlow", peer.info
        )
        steps = []
        feed.updates.subscribe(steps.append)
        assert feed.snapshot == ["ASK"]  # fired before suspension
        self.net.run_network()
        assert self.proxy.flow_result(flow_id, 10) == "ping-pong"
        import time

        deadline = time.monotonic() + 5
        while not steps and time.monotonic() < deadline:
            time.sleep(0.02)
        assert steps == ["GOT"]  # streamed over the observable channel

    def test_chunked_attachment_round_trip(self):
        blob = bytes(range(256)) * 8192  # 2 MiB, > one chunk
        upload_id = self.proxy.upload_attachment_begin()
        chunk = 512 * 1024
        for off in range(0, len(blob), chunk):
            n = self.proxy.upload_attachment_chunk(
                upload_id, blob[off : off + chunk]
            )
        assert n == len(blob)
        att_id = self.proxy.upload_attachment_end(upload_id)
        assert self.proxy.attachment_size(att_id) == len(blob)
        out = bytearray()
        offset = 0
        while offset < len(blob):
            part = self.proxy.attachment_chunk(att_id, offset)
            assert len(part) <= CordaRPCOps.ATTACHMENT_CHUNK
            out.extend(part)
            offset += len(part)
        assert bytes(out) == blob

    def test_unknown_upload_rejected(self):
        with pytest.raises(Exception, match="unknown upload"):
            self.proxy.upload_attachment_chunk("nope", b"x")


class TestSizeCap:
    def setup_method(self):
        self.net = MockNetwork()
        self.node = self.net.create_node("O=Cap,L=London,C=GB")
        self.ops = CordaRPCOps(self.node.services, self.node.smm)

    def teardown_method(self):
        self.net.stop_nodes()

    def test_oversize_single_shot_rejected(self, monkeypatch):
        monkeypatch.setattr(CordaRPCOps, "MAX_ATTACHMENT_SIZE", 1024)
        with pytest.raises(ValueError, match="exceeds"):
            self.ops.upload_attachment(b"x" * 2048)

    def test_oversize_chunked_rejected_and_cleaned(self, monkeypatch):
        monkeypatch.setattr(CordaRPCOps, "MAX_ATTACHMENT_SIZE", 1024)
        upload_id = self.ops.upload_attachment_begin()
        self.ops.upload_attachment_chunk(upload_id, b"x" * 1000)
        with pytest.raises(ValueError, match="exceeds"):
            self.ops.upload_attachment_chunk(upload_id, b"x" * 1000)
        # the aborted upload is gone
        with pytest.raises(ValueError, match="unknown upload"):
            self.ops.upload_attachment_end(upload_id)


class TestWebserverStreaming:
    def test_large_attachment_streams(self):
        from corda_tpu.webserver import WebServer

        net = MockNetwork()
        node = net.create_node("O=Stream,L=London,C=GB")
        ops = CordaRPCOps(node.services, node.smm)
        web = WebServer(ops, port=0)
        try:
            blob = b"\xab" * (1_500_000)  # > 2 chunks
            req = urllib.request.Request(
                f"http://127.0.0.1:{web.port}/api/attachments",
                data=blob, method="POST",
            )
            with urllib.request.urlopen(req, timeout=15) as resp:
                assert resp.status == 200
            from corda_tpu.core.crypto.secure_hash import SecureHash

            att_id = SecureHash.sha256(blob)
            assert ops.attachment_exists(att_id)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{web.port}/api/attachments/"
                + att_id.bytes.hex(),
                timeout=15,
            ) as resp:
                assert resp.read() == blob
        finally:
            web.stop()
            net.stop_nodes()


class TestWebserverStreamFailure:
    def test_mid_stream_failure_drops_connection(self):
        """If a chunk read fails after headers are sent, the server must
        kill the connection rather than emit a JSON 500 into the body
        (which would corrupt the download)."""
        import urllib.request

        from corda_tpu.webserver import WebServer

        net = MockNetwork()
        node = net.create_node("O=StreamFail,L=London,C=GB")
        ops = CordaRPCOps(node.services, node.smm)
        blob = b"\xcd" * (1_200_000)
        att_id = ops.upload_attachment(blob)

        class FlakyOps:
            """Proxy that serves one chunk then breaks."""

            def __init__(self, inner):
                self._inner = inner
                self._served = 0

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def attachment_chunk(self, att_id, offset, length=None):
                self._served += 1
                if self._served > 1:
                    raise IOError("simulated broker failure")
                return self._inner.attachment_chunk(att_id, offset, length)

        web = WebServer(FlakyOps(ops), port=0)
        try:
            url = (
                f"http://127.0.0.1:{web.port}/api/attachments/"
                + att_id.bytes.hex()
            )
            got = None
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    got = resp.read()
            except Exception:
                pass  # connection died mid-body: correct behavior
            # if the read "succeeded" it must NOT be a corrupted short body
            # with an embedded JSON error
            if got is not None:
                assert b'{"error"' not in got
                assert len(got) < len(blob)
        finally:
            web.stop()
            net.stop_nodes()


class TestWebServerPlugins:
    """WebServerPluginRegistry analogue: CorDapp-contributed REST routes
    and static dirs mount next to the built-in API (reference
    webserver/services/WebServerPluginRegistry.kt)."""

    def test_plugin_api_and_static_mounts(self, tmp_path):
        import json as _json

        from corda_tpu.webserver import WebServer
        from corda_tpu.webserver.plugins import (
            WebServerPlugin,
            clear_web_plugins,
            register_web_plugin,
        )

        (tmp_path / "index.html").write_text("<h1>cordapp ui</h1>")

        class DemoPlugin(WebServerPlugin):
            def web_apis(self):
                def rates(ops, method, subpath, params, body):
                    if method == "POST":
                        return 200, {"posted": body.decode()}
                    return 200, {"pair": subpath, "rate": 1.25,
                                 "who": ops.node_info().name}

                return {"demo": rates}

            def static_serve_dirs(self):
                return {"demoui": str(tmp_path)}

        clear_web_plugins()
        register_web_plugin(DemoPlugin())
        net = MockNetwork()
        node = net.create_node("O=Plug,L=London,C=GB")
        ops = CordaRPCOps(node.services, node.smm)
        web = WebServer(ops, port=0)
        try:
            base = f"http://127.0.0.1:{web.port}"
            with urllib.request.urlopen(f"{base}/api/demo/USDGBP",
                                        timeout=10) as r:
                body = _json.loads(r.read())
            assert body["pair"] == "USDGBP" and body["rate"] == 1.25
            assert "O=Plug" in body["who"]

            req = urllib.request.Request(
                f"{base}/api/demo", data=b"hello", method="POST"
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                assert _json.loads(r.read())["posted"] == "hello"

            with urllib.request.urlopen(
                f"{base}/web/demoui/index.html", timeout=10
            ) as r:
                assert b"cordapp ui" in r.read()
                assert r.headers["Content-Type"].startswith("text/html")

            # traversal must be refused
            from urllib.error import HTTPError

            with pytest.raises(HTTPError) as exc:
                urllib.request.urlopen(
                    f"{base}/web/demoui/..%2f..%2fetc%2fpasswd", timeout=10
                )
            assert exc.value.code in (403, 404)

            # unknown routes still 404
            with pytest.raises(HTTPError) as exc:
                urllib.request.urlopen(f"{base}/api/nope", timeout=10)
            assert exc.value.code == 404
        finally:
            web.stop()
            net.stop_nodes()
            clear_web_plugins()


class TestDashboard:
    """The web GUI tier (reference explorer / network-visualiser JavaFX
    shells): a self-contained dashboard page served at /, consuming the
    gateway's own JSON API."""

    def test_dashboard_served_and_api_shapes_match(self):
        from corda_tpu.webserver import WebServer

        net = MockNetwork()
        node = net.create_node("O=Dash,L=London,C=GB")
        ops = CordaRPCOps(node.services, node.smm)
        web = WebServer(ops, port=0)
        try:
            base = f"http://127.0.0.1:{web.port}"
            with urllib.request.urlopen(base + "/", timeout=10) as resp:
                assert resp.headers["Content-Type"].startswith("text/html")
                page = resp.read().decode()
            assert "corda-tpu node dashboard" in page
            # every endpoint the page polls must exist and return the
            # shape its JS destructures
            import json as _json

            def get(path):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    assert r.status == 200, path
                    return _json.loads(r.read())

            for path in ("/api/info", "/api/network", "/api/notaries",
                         "/api/vault?page_size=25", "/api/metrics",
                         "/api/transactions?limit=15", "/api/statemachines"):
                assert f'j("{path}")' in page, f"page no longer polls {path}"
            info = get("/api/info")
            assert {"name", "key", "scheme"} <= set(info)
            vault = get("/api/vault?page_size=25")
            assert {"total", "states"} <= set(vault)
            assert isinstance(get("/api/network"), list)
            assert isinstance(get("/api/notaries"), list)
            assert isinstance(get("/api/metrics"), dict)
            assert isinstance(get("/api/transactions?limit=15"), list)
            assert isinstance(get("/api/statemachines"), list)
            # limit abuse must stay bounded (clamped to [1, 500]),
            # never returning the whole store via -0/negative slicing
            assert len(get("/api/transactions?limit=0")) <= 1
            assert len(get("/api/transactions?limit=-5")) <= 1
            assert len(get("/api/transactions?limit=999999")) <= 500
        finally:
            web.stop()
            net.stop_nodes()

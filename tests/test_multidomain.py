"""Multi-domain notary federation (docs/robustness.md §6).

Covers the federation's four load-bearing claims:

  * segmentation — domain-scoped network maps (directory rule, mock
    fan-out, gateways) with the single-domain kill switch intact;
  * pinning — mixed-notary input sets and unresolvable notaries are
    typed `WrongNotaryError`, hospital-FATAL (retry cannot re-route);
  * atomicity — the journaled 2PC notary change survives an injected
    coordinator crash at EVERY seam, recovery lands the state on
    exactly one notary, double-spend probed on BOTH sides;
  * observability — the new soak metrics carry the right gate
    directions and the soak-gate goodput floor breaches on missing
    data.
"""
from dataclasses import dataclass
from typing import List

import pytest

from corda_tpu.core.contracts import (
    Contract,
    ContractState,
    StateAndRef,
    TypeOnlyCommandData,
    contract,
)
from corda_tpu.core.flows import FinalityFlow, NotaryChangeFlow
from corda_tpu.core.serialization.codec import corda_serializable
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.node.notary import NotaryException, WrongNotaryError
from corda_tpu.node.notary_change import (
    CRASH_POINTS,
    NotaryChangeRecoveryFlow,
    change_journal,
    pending_notary_changes,
)
from corda_tpu.testing.mocknetwork import MockNetwork
from corda_tpu.utils import faultpoints


@corda_serializable
@dataclass(frozen=True)
class FedState(ContractState):
    parties: tuple = ()
    tag: int = 1
    contract_name = "FedContract"

    @property
    def participants(self) -> List:
        return list(self.parties)


@corda_serializable
@dataclass(frozen=True)
class FedCommand(TypeOnlyCommandData):
    pass


@contract(name="FedContract")
class FedContract(Contract):
    def verify(self, tx) -> None:
        pass


def _issue(node, notary) -> StateAndRef:
    builder = TransactionBuilder(notary=notary.info)
    builder.add_output_state(FedState(parties=(node.info,)))
    builder.add_command(FedCommand(), node.info.owning_key)
    stx = node.services.sign_initial_transaction(builder)
    node.services.record_transactions([stx])
    return stx.tx.out_ref(0)


def _spend(node, ref: StateAndRef, notary):
    """Start a FinalityFlow consuming `ref` at `notary`; returns the
    flow handle (the caller runs the network and asserts the verdict)."""
    builder = TransactionBuilder(notary=notary.info)
    builder.add_input_state(ref)
    builder.add_output_state(
        FedState(parties=(node.info,), tag=2), notary.info
    )
    builder.add_command(FedCommand(), node.info.owning_key)
    stx = node.services.sign_initial_transaction(builder)
    return node.start_flow(FinalityFlow(stx))


def _spend_forced(node, ref: StateAndRef, notary):
    """Like _spend, but bypasses TransactionBuilder's local pinning check
    by appending the input ref directly — a client that lies about the
    governing notary, so the typed flow-layer enforcement is what trips."""
    builder = TransactionBuilder(notary=notary.info)
    builder.add_output_state(
        FedState(parties=(node.info,), tag=2), notary.info
    )
    builder.add_command(FedCommand(), node.info.owning_key)
    builder._inputs.append(ref.ref)
    stx = node.services.sign_initial_transaction(builder)
    return node.start_flow(FinalityFlow(stx))


# ---------------------------------------------------------------------------
# Segmentation: domain-scoped maps


class TestDomainScoping:
    def setup_method(self):
        self.net = MockNetwork()

    def teardown_method(self):
        self.net.stop_nodes()
        faultpoints.set_hook(None)

    def test_domain_scoped_visibility(self):
        """A domain member sees its own segment + gateways, not the
        foreign segment's members; a domainless observer sees all."""
        notary_a, (alice,) = self.net.create_domain("alpha")
        notary_b, (bob,) = self.net.create_domain("beta")
        observer = self.net.create_node("O=Observer,L=Oslo,C=NO")

        alice_names = {
            p.name for p in alice.services.network_map_cache.all_nodes
        }
        assert notary_a.info.name in alice_names
        assert notary_b.info.name in alice_names  # gateway notary
        assert bob.info.name not in alice_names   # foreign member
        assert observer.info.name in alice_names  # domainless entry

        observer_names = {
            p.name for p in observer.services.network_map_cache.all_nodes
        }
        assert {alice.info.name, bob.info.name} <= observer_names

    def test_notaries_in_domain_and_gateway_helpers(self):
        notary_a, (alice,) = self.net.create_domain("alpha")
        notary_b, _ = self.net.create_domain("beta")
        cache = alice.services.network_map_cache
        assert cache.notaries_in_domain("alpha") == [notary_a.info]
        assert cache.node_domain(notary_b.info) == "beta"
        assert cache.is_gateway(notary_b.info)
        assert not cache.is_gateway(alice.info)
        assert cache.get_notary(domain="beta") == notary_b.info
        assert "alpha" in cache.domains and "beta" in cache.domains

    def test_gateway_view_is_global(self):
        """A GATEWAY sees foreign-domain MEMBERS: it anchors
        cross-domain protocol legs (the notary-change ASSUME resolves
        its back-chain from a foreign-domain client), so a scoped view
        would strand the sessions it must serve — found live by the
        tier-1 real-process kill test."""
        notary_a, (alice,) = self.net.create_domain("alpha")
        notary_b, (bob,) = self.net.create_domain("beta")
        b_view = {
            p.name for p in notary_b.services.network_map_cache.all_nodes
        }
        assert alice.info.name in b_view   # foreign member, visible
        assert notary_a.info.name in b_view
        # the gateway's reach is one-way trust plumbing: alice still
        # does NOT see the foreign member bob
        a_view = {
            p.name for p in alice.services.network_map_cache.all_nodes
        }
        assert bob.info.name not in a_view

    def test_kill_switch_unconfigured_network_unchanged(self):
        """No domain config -> no pseudo-services advertised, full
        mutual visibility — the pre-federation wire format exactly."""
        notary = self.net.create_notary_node()
        alice = self.net.create_node("O=Alice,L=London,C=GB")
        bob = self.net.create_node("O=Bob,L=Paris,C=FR")
        for node in (notary, alice, bob):
            for svc in node.config.advertised_services:
                assert not svc.startswith("corda.domain.")
                assert svc != "corda.gateway"
        names = {p.name for p in alice.services.network_map_cache.all_nodes}
        assert {notary.info.name, bob.info.name} <= names

    def test_cordform_kill_switch_omits_domain_keys(self, tmp_path):
        from corda_tpu.tools.cordform import deploy_nodes

        resolved = deploy_nodes({"nodes": [
            {"name": "O=N,L=Zurich,C=CH", "notary": "validating"},
            {"name": "O=A,L=London,C=GB"},
        ]}, str(tmp_path))
        for conf in resolved:
            assert "domain" not in conf
            assert "gateway" not in conf

    def test_cordform_propagates_domain_and_gateway(self, tmp_path):
        from corda_tpu.tools.cordform import deploy_nodes

        resolved = deploy_nodes({"nodes": [
            {"name": "O=N,L=Zurich,C=CH", "notary": "validating",
             "domain": "alpha", "gateway": True},
            {"name": "O=A,L=London,C=GB", "domain": "alpha"},
        ]}, str(tmp_path))
        assert resolved[0]["domain"] == "alpha"
        assert resolved[0]["gateway"] is True
        assert resolved[1]["domain"] == "alpha"
        assert "gateway" not in resolved[1]

    def test_networkmap_entry_visibility_rule(self):
        from corda_tpu.node.networkmap import _entry_visible

        assert _entry_visible(None, ["corda.domain.alpha"])
        assert _entry_visible("alpha", ["corda.domain.alpha"])
        assert _entry_visible("alpha", [])  # domainless entry
        assert not _entry_visible("alpha", ["corda.domain.beta"])
        assert _entry_visible(
            "alpha", ["corda.domain.beta", "corda.gateway"]
        )


# ---------------------------------------------------------------------------
# Pinning: typed WrongNotaryError, hospital-fatal


class TestNotaryPinning:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary_a = self.net.create_notary_node(
            "O=Notary A,L=Zurich,C=CH"
        )
        self.notary_b = self.net.create_notary_node(
            "O=Notary B,L=Geneva,C=CH"
        )
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")

    def teardown_method(self):
        self.net.stop_nodes()

    def test_mixed_notary_input_set_rejected(self):
        """Inputs pinned to A committed through B are refused at BOTH
        layers: the builder refuses to assemble the set, and a client
        that bypasses the builder gets a typed refusal carrying the
        governing notary before anything reaches notary B's ledger."""
        ref_a = _issue(self.alice, self.notary_a)
        with pytest.raises(ValueError, match="requires notary"):
            _spend(self.alice, ref_a, self.notary_b)
        h = _spend_forced(self.alice, ref_a, self.notary_b)
        self.net.run_network()
        with pytest.raises(WrongNotaryError, match="pinned to notary"):
            h.result.result(timeout=5)

    def test_wrong_notary_error_carries_pinned_notary(self):
        ref_a = _issue(self.alice, self.notary_a)
        h = _spend_forced(self.alice, ref_a, self.notary_b)
        self.net.run_network()
        try:
            h.result.result(timeout=5)
            raise AssertionError("mixed-notary spend was accepted")
        except WrongNotaryError as exc:
            assert exc.pinned_notary == self.notary_a.info

    def test_wrong_notary_is_hospital_fatal(self):
        """The hospital must ward a pinning violation, not retry it —
        and keep treating genuine unavailability as transient."""
        hospital = self.alice.smm.hospital
        assert hospital.classify(
            WrongNotaryError("input pinned to another notary")
        ) == "fatal"
        assert hospital.classify(
            NotaryException("notary request timed out")
        ) == "transient"

    def test_spend_with_matching_notary_still_works(self):
        ref_a = _issue(self.alice, self.notary_a)
        h = _spend(self.alice, ref_a, self.notary_a)
        self.net.run_network()
        h.result.result(timeout=5)

    def test_coin_selection_skips_foreign_pinned_states(self):
        """generate_spend must not gather states pinned to another
        notary into a builder already pinned (multi-domain vaults): the
        only cash is under notary A, so a builder pinned to B sees an
        empty eligible set."""
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.finance.flows import CashIssueFlow, generate_spend

        h = self.alice.start_flow(CashIssueFlow(
            Amount(100, "USD"), b"\x01", self.alice.info,
            self.notary_a.info,
        ))
        self.net.run_network()
        h.result.result(timeout=5)
        token = Issued(self.alice.info.ref(1), "USD")
        with pytest.raises(Exception, match="[Ii]nsufficient"):
            generate_spend(
                self.alice.services,
                TransactionBuilder(notary=self.notary_b.info),
                Amount(100, token), self.alice.info,
            )
        # sanity: the same spend against the PINNED notary selects fine
        _, selected = generate_spend(
            self.alice.services,
            TransactionBuilder(notary=self.notary_a.info),
            Amount(100, token), self.alice.info,
        )
        assert selected


# ---------------------------------------------------------------------------
# Atomicity: crash matrix over the 2PC seams


class TestNotaryChangeCrashMatrix:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary_a = self.net.create_notary_node(
            "O=Notary A,L=Zurich,C=CH"
        )
        self.notary_b = self.net.create_notary_node(
            "O=Notary B,L=Geneva,C=CH"
        )
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")

    def teardown_method(self):
        faultpoints.set_hook(None)
        self.net.stop_nodes()

    def _crash_at(self, point):
        def hook(p, **detail):
            if p == point:
                return "crash"
            return None

        faultpoints.set_hook(hook)

    def _run_change(self, ref):
        h = self.alice.start_flow(
            NotaryChangeFlow(ref, self.notary_b.info)
        )
        self.net.run_network()
        return h

    @pytest.mark.parametrize("point", CRASH_POINTS)
    def test_crash_then_recover_lands_exactly_once(self, point):
        """Kill the coordinator at every protocol seam; recovery must
        land the state on EXACTLY one notary — probed for double-spend
        on both domains afterwards."""
        original = _issue(self.alice, self.notary_a)
        self._crash_at(point)
        h = self._run_change(original)
        with pytest.raises(Exception, match="injected coordinator crash"):
            h.result.result(timeout=5)
        faultpoints.set_hook(None)

        pending = pending_notary_changes(self.alice.services)
        if point == "notary_change.before_prepare":
            # nothing durable happened: no journal entry, state still
            # governed by the old notary; a fresh change completes
            assert pending == []
            ts = self.alice.services.load_state(original.ref)
            assert ts.notary == self.notary_a.info
            h2 = self._run_change(original)
            new_ref = h2.result.result(timeout=5)
        else:
            expected_phase = (
                "prepare" if point == "notary_change.after_prepare"
                else "assume"
            )
            assert [rec["phase"] for _, rec in pending] == [expected_phase]
            change_stx = pending[0][1]["stx"]
            rh = self.alice.start_flow(NotaryChangeRecoveryFlow())
            self.net.run_network()
            recovered = rh.result.result(timeout=5)
            assert recovered == [change_stx.id.bytes.hex()]
            from corda_tpu.core.contracts import StateRef

            outputs = change_stx.tx.resolve_outputs(
                self.alice.services.load_state
            )
            new_ref = StateAndRef(
                outputs[0], StateRef(change_stx.id, 0)
            )
        assert pending_notary_changes(self.alice.services) == []
        assert new_ref.state.notary == self.notary_b.info

        # double-spend probe, OLD domain: the original ref must be dead
        # at notary A (consumed by the recorded change)
        h3 = _spend(self.alice, original, self.notary_a)
        self.net.run_network()
        with pytest.raises(Exception, match="[Cc]onflict|consumed"):
            h3.result.result(timeout=5)
        # double-spend probe, NEW domain: the original ref cannot be
        # smuggled through notary B either (pinning), while the migrated
        # state spends exactly once there
        h4 = _spend_forced(self.alice, original, self.notary_b)
        self.net.run_network()
        with pytest.raises(
            Exception, match="pinned to|[Cc]onflict|consumed"
        ):
            h4.result.result(timeout=5)
        h5 = _spend(self.alice, new_ref, self.notary_b)
        self.net.run_network()
        h5.result.result(timeout=5)

    def test_journal_survives_and_is_listed_at_start(self):
        """A crash-interrupted change is visible via
        pending_notary_changes — what AbstractNode.start() warns on."""
        original = _issue(self.alice, self.notary_a)
        self._crash_at("notary_change.between_consume_and_assume")
        h = self._run_change(original)
        with pytest.raises(Exception):
            h.result.result(timeout=5)
        faultpoints.set_hook(None)
        pending = pending_notary_changes(self.alice.services)
        assert len(pending) == 1
        tx_hex, rec = pending[0]
        assert rec["phase"] == "assume"
        assert rec["old"] == self.notary_a.info.name
        assert rec["new"] == self.notary_b.info.name

    def test_happy_path_leaves_journal_empty(self):
        """A completed cross-domain change clears its journal entry —
        the durable intent must not outlive the landed protocol."""
        original = _issue(self.alice, self.notary_a)
        h = self.alice.start_flow(
            NotaryChangeFlow(original, self.notary_b.info)
        )
        self.net.run_network()
        new_ref = h.result.result(timeout=5)
        assert new_ref.state.notary == self.notary_b.info
        assert pending_notary_changes(self.alice.services) == []

    def test_journal_phase_mapping_round_trips(self):
        """The decision phase ("assume") borrows the base journal's
        raised-durability "committing" write but reads back untranslated."""
        journal = change_journal(self.alice.services)
        journal.put("aa" * 32, {"phase": "prepare", "n": 1})
        assert journal.get("aa" * 32)["phase"] == "prepare"
        journal.put("aa" * 32, {"phase": "assume", "n": 2})
        assert journal.get("aa" * 32)["phase"] == "assume"
        assert [r["phase"] for _, r in journal.items()] == ["assume"]
        journal.remove("aa" * 32)
        assert journal.items() == []


# ---------------------------------------------------------------------------
# Disruption catalog entries (deterministic, fakes)


class _FakeVictim:
    def __init__(self):
        self.suspended = False
        self.log = []

    def suspend(self):
        self.suspended = True
        self.log.append("suspend")

    def resume(self):
        self.suspended = False
        self.log.append("resume")


class TestDomainDisruptions:
    def test_domain_partition_asserts_foreign_progress_while_dark(self):
        from corda_tpu.loadtest.disruption import domain_partition

        victim = _FakeVictim()
        foreign = {"n": 0}
        dark = {"n": 0}
        seen_suspended_at_assert = []

        def foreign_probe():
            # record whether the victim was still dark when the heal
            # sampled foreign progress — the ordering IS the claim
            seen_suspended_at_assert.append(victim.suspended)
            foreign["n"] += 2
            return foreign["n"]

        def dark_probe():
            dark["n"] += 2
            return dark["n"]

        d = domain_partition(
            [victim], foreign_probe, dark_probe,
            recovery_deadline_s=5.0,
        )
        import random

        rng = random.Random(1)
        d.fire(rng)
        assert victim.suspended
        d.heal(rng)
        assert not victim.suspended
        # the foreign-progress samples inside heal happened BEFORE resume
        assert any(seen_suspended_at_assert)
        assert victim.log[0] == "suspend" and victim.log[-1] == "resume"

    def test_domain_partition_no_foreign_progress_fails_heal(self):
        from corda_tpu.loadtest.disruption import domain_partition

        victim = _FakeVictim()
        d = domain_partition(
            [victim], lambda: 0, None, recovery_deadline_s=0.5,
        )
        import random

        rng = random.Random(1)
        d.fire(rng)
        with pytest.raises(AssertionError, match="foreign traffic"):
            d.heal(rng)

    def test_notary_change_storm_drains_waiters(self):
        from corda_tpu.loadtest.disruption import notary_change_storm

        drained = []
        progress = {"n": 0}

        def probe():
            progress["n"] += 1
            return progress["n"]

        def launch(rng):
            return lambda: drained.append(1)

        d = notary_change_storm(
            launch, probe, changes=3, recovery_deadline_s=5.0,
        )
        import random

        rng = random.Random(1)
        d.fire(rng)
        d.heal(rng)
        assert len(drained) == 3

    def test_notary_change_storm_failed_change_fails_heal(self):
        from corda_tpu.loadtest.disruption import notary_change_storm

        def launch(rng):
            def waiter():
                raise RuntimeError("change did not land")

            return waiter

        d = notary_change_storm(
            launch, lambda: 99, changes=2, recovery_deadline_s=5.0,
        )
        import random

        rng = random.Random(1)
        d.fire(rng)
        with pytest.raises(AssertionError, match="failed to\\s+land"):
            d.heal(rng)


# ---------------------------------------------------------------------------
# Soak record + gate plumbing


class TestSoakGatePlumbing:
    def test_gate_directions_for_new_metrics(self):
        from corda_tpu.loadtest import gate

        assert gate.direction("multi_domain_pairs_s") == "higher"
        assert gate.direction("mttr_ms{kind=domain_partition}") == "lower"

    def test_soak_gate_domain_goodput_floor(self, capsys):
        import json

        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "soak_gate", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools", "soak_gate.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        def gate_run(record, *extra):
            import tempfile

            with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False
            ) as fh:
                json.dump(record, fh)
                path = fh.name
            try:
                return mod.main(["--current", path, *extra])
            finally:
                os.unlink(path)

        base = {
            "pairs": 10, "hard_error_rate": 0.0, "consistent": True,
            "domain_goodput_pct": 83.0,
        }
        assert gate_run(base, "--domain-goodput", "50") == 0
        low = dict(base, domain_goodput_pct=12.5)
        assert gate_run(low, "--domain-goodput", "50") == 1
        missing = {k: v for k, v in base.items()
                   if k != "domain_goodput_pct"}
        assert gate_run(missing, "--domain-goodput", "50") == 1
        # without the flag the same record passes (opt-in floor)
        assert gate_run(missing) == 0

    def test_disruption_mttr_labels_domain_partition(self):
        from corda_tpu.loadtest.observatory import disruption_mttr

        events = [
            (10.0, "domain_partition", "fired"),
            (22.5, "domain_partition", "recovered+8"),
            (30.0, "notary_change_storm", "fired"),
            (31.0, "notary_change_storm", "recovered+2"),
        ]
        mttr = disruption_mttr(events)
        assert mttr["mttr_ms{kind=domain_partition}"] == 12500.0
        assert mttr["mttr_ms{kind=notary_change_storm}"] == 1000.0

    def test_domains_soak_helpers(self):
        from corda_tpu.loadtest import domains

        spec = domains.domain_spec()
        assert len(spec["nodes"]) == 3 * len(domains.DOMAINS)
        notaries = [n for n in spec["nodes"] if n.get("notary")]
        assert all(n["gateway"] for n in notaries)
        assert sum(
            1 for n in spec["nodes"] if n.get("network_map_service")
        ) == 1
        doms = {n["domain"] for n in spec["nodes"]}
        assert doms == set(domains.DOMAINS)

        assert domains.is_typed_transient_shed(
            "NotaryException: notary request timed out"
        )
        assert domains.is_typed_transient_shed(
            "TransientFlowError: shed"
        )
        assert not domains.is_typed_transient_shed(
            "ValueError: bad amount"
        )

    def test_dark_window_floor(self, monkeypatch):
        from corda_tpu.loadtest import domains

        monkeypatch.setenv("CORDA_TPU_DOMAIN_DARK_S", "3")
        assert domains.default_dark_window_s() == 10.0
        monkeypatch.setenv("CORDA_TPU_DOMAIN_DARK_S", "25")
        assert domains.default_dark_window_s() == 25.0
        monkeypatch.setenv("CORDA_TPU_DOMAIN_DARK_S", "junk")
        assert domains.default_dark_window_s() == 12.0
        monkeypatch.delenv("CORDA_TPU_DOMAIN_DARK_S")
        assert domains.default_dark_window_s() == 12.0


# ---------------------------------------------------------------------------
# Bounded PJRT backend probe (satellite)


class TestBackendProbe:
    def test_probe_status_shape(self):
        from corda_tpu.core.crypto import batch

        status = batch.backend_probe_status()
        assert set(status) >= {
            "classification", "attempts", "backend", "elapsed_s"
        }
        # a copy, not the live dict: callers must not mutate probe state
        status["classification"] = "tampered"
        assert batch._probe_status["classification"] != "tampered"

    def test_probe_timeout_classified_and_budgeted(self, monkeypatch):
        """Every attempt times out -> budgeted retries (alternate init
        scripts), classified skip to cpu — never an unbounded hang."""
        import subprocess as sp

        from corda_tpu.core.crypto import batch

        calls = []

        def fake_run(cmd, **kw):
            calls.append(cmd)
            raise sp.TimeoutExpired(cmd, kw.get("timeout", 0))

        monkeypatch.setenv("CORDA_TPU_BACKEND_PROBE_RETRIES", "2")
        monkeypatch.setenv("CORDA_TPU_BACKEND_PROBE_TIMEOUT", "1")
        monkeypatch.setenv("CORDA_TPU_BACKEND_PROBE_BUDGET_S", "30")
        monkeypatch.setattr(batch.subprocess, "run", fake_run)
        monkeypatch.setattr(batch._time, "sleep", lambda s: None)
        result = batch._probe_backend_subprocess({})
        assert result == "cpu"
        assert len(calls) == 2
        # alternate init scripts rotate across attempts
        scripts = [c[-1] for c in calls]
        assert scripts[0] != scripts[1]
        status = batch.backend_probe_status()
        assert status["classification"] == "timeout"
        assert status["attempts"] == 2
        assert status["backend"] == "cpu"

    def test_probe_success_classified_ok(self, monkeypatch):
        from corda_tpu.core.crypto import batch

        class _Out:
            returncode = 0
            stdout = "tpu\n"
            stderr = ""

        monkeypatch.setenv("CORDA_TPU_BACKEND_PROBE_RETRIES", "2")
        monkeypatch.setattr(
            batch.subprocess, "run", lambda *a, **k: _Out()
        )
        assert batch._probe_backend_subprocess({}) == "tpu"
        status = batch.backend_probe_status()
        assert status["classification"] == "ok"
        assert status["backend"] == "tpu"

    def test_probe_budget_exhaustion(self, monkeypatch):
        """A zero budget skips straight to the classified cpu fallback
        without ever spawning a probe process."""
        from corda_tpu.core.crypto import batch

        spawned = []
        monkeypatch.setenv("CORDA_TPU_BACKEND_PROBE_BUDGET_S", "0")
        monkeypatch.setattr(
            batch.subprocess, "run",
            lambda *a, **k: spawned.append(a) or None,
        )
        assert batch._probe_backend_subprocess({}) == "cpu"
        assert spawned == []
        assert batch.backend_probe_status()[
            "classification"
        ] == "budget-exhausted"

    def test_probe_knobs_registered(self):
        from corda_tpu.analysis import envknobs

        for name in (
            "CORDA_TPU_BACKEND_PROBE_TIMEOUT",
            "CORDA_TPU_BACKEND_PROBE_RETRIES",
            "CORDA_TPU_BACKEND_PROBE_BUDGET_S",
            "CORDA_TPU_DOMAIN_DARK_S",
        ):
            assert name in envknobs.KNOBS

"""RPC layer tests (reference `client/rpc` round-trip + observable tests,
RPCServer permission checks)."""
import time

import pytest

from corda_tpu.core.contracts import Amount
from corda_tpu.core.flows import FlowLogic, startable_by_rpc
from corda_tpu.messaging import Broker
from corda_tpu.rpc import (
    CordaRPCClient,
    CordaRPCOps,
    RPCException,
    RPCPermissionError,
    RPCServer,
    RPCUser,
)
from corda_tpu.testing import MockNetwork


@startable_by_rpc
class AddFlow(FlowLogic):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def call(self):
        return self.a + self.b
        yield  # pragma: no cover


class TestRPC:
    def setup_method(self):
        self.net = MockNetwork()
        self.node = self.net.create_node("O=RpcNode,L=London,C=GB")
        self.broker = Broker()
        self.ops = CordaRPCOps(self.node.services, self.node.smm)
        self.server = RPCServer(
            self.broker, self.ops,
            users=[
                RPCUser("admin", "secret"),
                RPCUser("limited", "pw", {"node_info", "vault_query"}),
            ],
        )
        self.client = CordaRPCClient(self.broker)

    def teardown_method(self):
        self.client.close()
        self.server.stop()
        self.net.stop_nodes()

    def test_login_and_node_info(self):
        conn = self.client.start("admin", "secret")
        info = conn.proxy.node_info()
        assert info == self.node.info
        assert conn.proxy.party_from_name("O=RpcNode,L=London,C=GB") == self.node.info
        conn.close()

    def test_bad_credentials(self):
        with pytest.raises(RPCException, match="invalid credentials"):
            self.client.start("admin", "wrong")

    def test_start_flow_and_result(self):
        conn = self.client.start("admin", "secret")
        flow_id = conn.proxy.start_flow_dynamic("AddFlow", 20, 22)
        assert self.ops.flow_result(flow_id, timeout=5) == 42
        conn.close()

    def test_permissions(self):
        conn = self.client.start("limited", "pw")
        assert conn.proxy.node_info() == self.node.info
        with pytest.raises(RPCPermissionError):
            conn.proxy.start_flow_dynamic("AddFlow", 1, 2)
        with pytest.raises(RPCPermissionError):
            conn.proxy.network_map_snapshot()
        conn.close()

    def test_state_machine_feed_streams(self):
        conn = self.client.start("admin", "secret")
        feed = conn.proxy.state_machines_feed()
        events = []
        feed.updates.subscribe(events.append)
        conn.proxy.start_flow_dynamic("AddFlow", 1, 2)
        deadline = time.time() + 5
        while len(events) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert any(e.done for e in events)       # finished event arrived
        assert any(not e.done for e in events)   # started event arrived
        conn.close()

    def test_attachments_roundtrip(self):
        conn = self.client.start("admin", "secret")
        att_id = conn.proxy.upload_attachment(b"jar bytes here")
        assert conn.proxy.attachment_exists(att_id)
        assert conn.proxy.open_attachment(att_id) == b"jar bytes here"
        conn.close()

    def test_unknown_method(self):
        conn = self.client.start("admin", "secret")
        with pytest.raises(RPCException, match="unknown method"):
            conn.proxy.does_not_exist()
        conn.close()

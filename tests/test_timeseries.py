"""Metric time-series ring (corda_tpu/utils/timeseries.py).

Covers: per-type derivation (counters/meters to windowed rates, gauges
to last numeric readings, timers to window rate/mean + reservoir
quantiles), the strictly-after `since()` cursor contract, the bounded
ring, the quiesce-registered poller lifecycle, and the
CORDA_TPU_METRICS_HISTORY kill switch.
"""
import time

from corda_tpu.utils import quiesce
from corda_tpu.utils.metrics import MetricRegistry
from corda_tpu.utils.timeseries import (
    MetricsHistory,
    history_enabled,
    latest_rates,
)


def _history(registry=None, **kw):
    kw.setdefault("interval_s", 60.0)  # ticks driven manually by tests
    return MetricsHistory(registry or MetricRegistry(), **kw)


class TestDerivation:
    def test_counter_becomes_windowed_rate(self):
        registry = MetricRegistry()
        history = _history(registry)
        counter = registry.counter("Pay.Count")
        counter.inc(4)
        first = history.sample_once(now=10.0)
        # no previous sample -> no window to rate over
        assert first["metrics"]["Pay.Count"] == {"count": 4.0, "rate": None}
        counter.inc(10)
        second = history.sample_once(now=12.0)
        assert second["metrics"]["Pay.Count"] == {"count": 14.0, "rate": 5.0}
        assert second["dt_s"] == 2.0
        # a counter that went quiet rates 0.0, not None (the inflection
        # detector needs "stopped" to be a reading, not a gap)
        third = history.sample_once(now=13.0)
        assert third["metrics"]["Pay.Count"]["rate"] == 0.0

    def test_gauge_keeps_last_numeric_reading_and_skips_dead(self):
        registry = MetricRegistry()
        history = _history(registry)
        registry.gauge("Live.Depth", lambda: 7)
        registry.gauge("Live.Flag", lambda: True)
        registry.gauge("Dead.Gauge", lambda: 1 / 0)
        sample = history.sample_once(now=1.0)
        assert sample["metrics"]["Live.Depth"] == {"value": 7}
        assert sample["metrics"]["Live.Flag"] == {"value": 1}
        assert "Dead.Gauge" not in sample["metrics"]

    def test_timer_window_mean_and_quantiles(self):
        registry = MetricRegistry()
        history = _history(registry)
        timer = registry.timer("Verify.Wall")
        timer.update(0.2)
        history.sample_once(now=1.0)
        timer.update(0.4)
        timer.update(0.6)
        sample = history.sample_once(now=2.0)
        derived = sample["metrics"]["Verify.Wall"]
        assert derived["count"] == 3.0
        assert derived["rate"] == 2.0
        assert abs(derived["window_mean"] - 0.5) < 1e-9
        assert "p50" in derived and "p95" in derived

    def test_latest_rates_helper(self):
        registry = MetricRegistry()
        history = _history(registry)
        counter = registry.counter("C")
        counter.inc()
        history.sample_once(now=1.0)
        counter.inc(3)
        history.sample_once(now=2.0)
        samples = history.since()["samples"]
        series = latest_rates(samples, "C")
        assert len(series) == 1 and series[0][1] == 3.0


class TestCursorAndBounds:
    def test_since_is_strictly_after_and_resumable(self):
        history = _history()
        for i in range(5):
            history.sample_once(now=float(i))
        page = history.since(cursor=0, limit=3)
        assert [s["seq"] for s in page["samples"]] == [1, 2, 3]
        assert page["next"] == 3 and page["newest"] == 5
        page2 = history.since(cursor=page["next"])
        assert [s["seq"] for s in page2["samples"]] == [4, 5]
        # drained: next holds position instead of rewinding
        assert history.since(cursor=5)["samples"] == []
        assert history.since(cursor=5)["next"] == 5

    def test_ring_is_bounded_but_seq_is_global(self):
        history = _history(maxlen=3)
        for i in range(10):
            history.sample_once(now=float(i))
        page = history.since()
        assert [s["seq"] for s in page["samples"]] == [8, 9, 10]
        assert history.stats()["sampled"] == 10


class TestPollerLifecycle:
    def test_start_registers_quiesce_and_pause_skips_sampling(self):
        history = _history(name="t-lifecycle", interval_s=0.02)
        try:
            history.start()
            assert history.start() is history  # idempotent
            assert any(
                name == history._quiesce_name
                for name, _, _ in quiesce._registry
            )
            deadline = time.monotonic() + 5
            while history.stats()["sampled"] == 0:
                assert time.monotonic() < deadline, "poller never sampled"
                time.sleep(0.01)
            history.pause()
            time.sleep(0.06)
            frozen = history.stats()["sampled"]
            time.sleep(0.06)
            assert history.stats()["sampled"] == frozen
        finally:
            history.stop()
        assert not any(
            name == history._quiesce_name
            for name, _, _ in quiesce._registry
        )
        assert history.stats()["running"] is False

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_METRICS_HISTORY", "0")
        assert history_enabled() is False
        monkeypatch.delenv("CORDA_TPU_METRICS_HISTORY")
        assert history_enabled() is True

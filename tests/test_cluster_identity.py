"""Cluster service identity tests (reference ServiceIdentityGenerator +
distributed notary composite keys)."""
import pytest

from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto.signing import sign_bytes
from corda_tpu.node.cluster_identity import (
    generate_service_identity,
    load_service_identity,
    write_service_identity,
)


def _members(n):
    return [crypto.entropy_to_keypair(900 + i) for i in range(n)]


class TestGenerator:
    def test_composite_identity_thresholds(self):
        kps = _members(3)
        pub_keys = [kp.public for kp in kps]
        cluster = generate_service_identity(
            "O=NotaryCluster,L=Zurich,C=CH", pub_keys, threshold=2
        )
        # one member is not enough, two distinct members are
        assert not cluster.owning_key.is_fulfilled_by({pub_keys[0]})
        assert cluster.owning_key.is_fulfilled_by({pub_keys[0], pub_keys[2]})

    def test_default_threshold_is_one(self):
        kps = _members(3)
        cluster = generate_service_identity(
            "O=Raft,L=Z,C=CH", [kp.public for kp in kps]
        )
        assert cluster.owning_key.is_fulfilled_by({kps[1].public})

    def test_invalid_inputs(self):
        with pytest.raises(ValueError):
            generate_service_identity("O=X,L=Y,C=ZZ", [])
        kps = _members(2)
        with pytest.raises(ValueError):
            generate_service_identity(
                "O=X,L=Y,C=ZZ", [k.public for k in kps], threshold=3
            )

    def test_round_trips_disk(self, tmp_path):
        kps = _members(3)
        cluster = generate_service_identity(
            "O=C,L=Z,C=CH", [kp.public for kp in kps], threshold=2
        )
        path = write_service_identity(cluster, str(tmp_path))
        loaded = load_service_identity(path)
        assert loaded.name == cluster.name
        assert loaded.owning_key.encoded == cluster.owning_key.encoded


class TestClientValidation:
    """NotaryClientFlow's collective-fulfillment check, unit-level."""

    def _sigs(self, kps, content):
        return [
            sign_bytes(kp.private, kp.public, content) for kp in kps
        ]

    def test_bft_style_threshold_met(self):
        kps = _members(4)  # f=1 cluster: threshold f+1 = 2
        cluster = generate_service_identity(
            "O=BFT,L=Z,C=CH", [kp.public for kp in kps], threshold=2
        )
        content = b"tx-id-bytes-0123456789abcdef0123"
        sigs = self._sigs(kps[:2], content)
        assert cluster.owning_key.is_fulfilled_by({s.by for s in sigs})
        assert all(s.is_valid(content) for s in sigs)

    def test_single_replica_cannot_fulfil_bft_identity(self):
        kps = _members(4)
        cluster = generate_service_identity(
            "O=BFT,L=Z,C=CH", [kp.public for kp in kps], threshold=2
        )
        content = b"tx-id-bytes-0123456789abcdef0123"
        sigs = self._sigs(kps[:1], content)
        # even repeated signatures from ONE replica don't reach threshold
        assert not cluster.owning_key.is_fulfilled_by(
            {s.by for s in sigs + sigs}
        )

    def test_outsider_not_a_leaf(self):
        kps = _members(3)
        outsider = crypto.entropy_to_keypair(999)
        cluster = generate_service_identity(
            "O=C,L=Z,C=CH", [kp.public for kp in kps], threshold=1
        )
        assert outsider.public not in cluster.owning_key.keys


class TestNotaryClusterIntegration:
    """End-to-end: a client notarises against the composite cluster
    identity; any member serves; killing one mid-sequence fails over
    (reference VerifierTests-style elasticity + RaftNotaryService client
    failover via sendAndReceiveWithRetry)."""

    def _issue_and_move(self, net, bank, cluster, n=1):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.finance.flows import CashIssueFlow, CashPaymentFlow

        results = []
        for _ in range(n):
            h = bank.start_flow(CashIssueFlow(
                Amount(100, "USD"), b"\x01", bank.info, cluster
            ))
            net.run_network()
            h.result.result(timeout=15)
            token = Issued(bank.info.ref(1), "USD")
            h = bank.start_flow(CashPaymentFlow(
                Amount(100, token), bank.info, cluster
            ))
            net.run_network()
            results.append(h.result.result(timeout=15))
        return results

    def test_cluster_notarises_and_rotates_members(self):
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members = net.create_notary_cluster(n_members=3)
        bank = net.create_node("O=ClusterBank,L=London,C=GB")
        try:
            self._issue_and_move(net, bank, cluster, n=3)
            # the committed states name the cluster as notary
            states = bank.services.vault_service.unconsumed_states()
            assert all(
                s.state.notary.name == cluster.name for s in states
            )
            # audit shows more than one member served commits (round robin)
            served = {
                m.info.name for m in members
                if m.services.audit_service.events("notary.commit")
            }
            assert len(served) >= 2
        finally:
            net.stop_nodes()

    def test_failover_after_member_death(self):
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members = net.create_notary_cluster(n_members=3)
        bank = net.create_node("O=FailoverBank,L=London,C=GB")
        try:
            self._issue_and_move(net, bank, cluster, n=1)
            # kill one member; the cluster keeps serving
            victim = members[1]
            victim.stop()
            net.nodes.remove(victim)
            self._issue_and_move(net, bank, cluster, n=2)
            states = bank.services.vault_service.unconsumed_states()
            assert states  # everything settled without the dead member
        finally:
            net.stop_nodes()

    def test_double_spend_rejected_across_members(self):
        """The shared commit log makes a double spend conflict no matter
        which member sees the second attempt."""
        import pytest as _pytest

        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.structures import StateRef, StateAndRef
        from corda_tpu.core.transactions.builder import TransactionBuilder
        from corda_tpu.finance.cash import CashCommand, CashState
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.node.notary import NotaryClientFlow
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members = net.create_notary_cluster(n_members=2)
        bank = net.create_node("O=DoubleBank,L=London,C=GB")
        try:
            token = Issued(bank.info.ref(1), "USD")
            builder = TransactionBuilder(notary=cluster)
            builder.add_output_state(
                CashState(amount=Amount(100, token), owner=bank.info)
            )
            builder.add_command(CashCommand.Issue(), bank.info.owning_key)
            issue = bank.services.sign_initial_transaction(builder)
            bank.services.record_transactions([issue])

            def spend():
                ref = StateRef(issue.id, 0)
                ts = bank.services.load_state(ref)
                b = TransactionBuilder(notary=cluster)
                b.add_input_state(StateAndRef(ts, ref))
                b.add_output_state(
                    CashState(amount=Amount(100, token), owner=bank.info)
                )
                b.add_command(CashCommand.Move(), bank.info.owning_key)
                return bank.services.sign_initial_transaction(b)

            stx1, stx2 = spend(), spend()
            h = bank.start_flow(NotaryClientFlow(stx1), stx1)
            net.run_network()
            assert h.result.result(timeout=15)
            h = bank.start_flow(NotaryClientFlow(stx2), stx2)
            net.run_network()
            with _pytest.raises(Exception, match="[Cc]onflict"):
                h.result.result(timeout=15)
        finally:
            net.stop_nodes()


class TestBFTNotaryCluster:
    """The BFT cluster returns f+1 REPLICA signatures which collectively
    fulfil the f+1-threshold composite identity (reference
    BFTNonValidatingNotaryService + response extractor)."""

    def _spend_pair(self, net, bank, cluster):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.core.contracts.structures import StateAndRef, StateRef
        from corda_tpu.core.transactions.builder import TransactionBuilder
        from corda_tpu.finance.cash import CashCommand, CashState

        token = Issued(bank.info.ref(1), "USD")
        builder = TransactionBuilder(notary=cluster)
        builder.add_output_state(
            CashState(amount=Amount(100, token), owner=bank.info)
        )
        builder.add_command(CashCommand.Issue(), bank.info.owning_key)
        issue = bank.services.sign_initial_transaction(builder)
        bank.services.record_transactions([issue])

        def spend():
            ref = StateRef(issue.id, 0)
            ts = bank.services.load_state(ref)
            b = TransactionBuilder(notary=cluster)
            b.add_input_state(StateAndRef(ts, ref))
            b.add_output_state(
                CashState(amount=Amount(100, token), owner=bank.info)
            )
            b.add_command(CashCommand.Move(), bank.info.owning_key)
            return bank.services.sign_initial_transaction(b)

        return spend(), spend()

    def test_bft_notarisation_aggregates_replica_signatures(self):
        from corda_tpu.node.notary import NotaryClientFlow
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members, bus = net.create_bft_notary_cluster(n_members=4)
        bank = net.create_node("O=BFTBank,L=London,C=GB")
        try:
            stx1, _ = self._spend_pair(net, bank, cluster)
            h = bank.start_flow(
                NotaryClientFlow(stx1, notary_validating=False), stx1
            )
            net.run_network()
            sigs = h.result.result(timeout=30)
            f = (4 - 1) // 3
            assert len(sigs) >= f + 1
            # distinct replica keys, all leaves of the composite identity
            signers = {s.by.encoded for s in sigs}
            assert len(signers) >= f + 1
            leaf_keys = {k.encoded for k in cluster.owning_key.keys}
            assert signers <= leaf_keys
        finally:
            net.stop_nodes()

    def test_bft_double_spend_conflicts(self):
        import pytest as _pytest

        from corda_tpu.node.notary import NotaryClientFlow
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members, bus = net.create_bft_notary_cluster(n_members=4)
        bank = net.create_node("O=BFTBank2,L=London,C=GB")
        try:
            stx1, stx2 = self._spend_pair(net, bank, cluster)
            h = bank.start_flow(
                NotaryClientFlow(stx1, notary_validating=False), stx1
            )
            net.run_network()
            assert h.result.result(timeout=30)
            h = bank.start_flow(
                NotaryClientFlow(stx2, notary_validating=False), stx2
            )
            net.run_network()
            with _pytest.raises(Exception, match="[Cc]onflict"):
                h.result.result(timeout=30)
        finally:
            net.stop_nodes()

    def test_dead_replica_does_not_block_quorum(self):
        """n=4 tolerates f=1: with one replica dead the remaining three
        still commit and return >= f+1 valid signatures."""
        from corda_tpu.node.notary import NotaryClientFlow
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members, bus = net.create_bft_notary_cluster(n_members=4)
        bank = net.create_node("O=BFTBank3,L=London,C=GB")
        try:
            bus.dead.add(3)  # crash a replica before any request
            stx1, _ = self._spend_pair(net, bank, cluster)
            h = bank.start_flow(
                NotaryClientFlow(stx1, notary_validating=False), stx1
            )
            net.run_network()
            sigs = h.result.result(timeout=30)
            assert len({s.by.encoded for s in sigs}) >= 2  # f+1
        finally:
            net.stop_nodes()

    def test_signature_withholding_replica_cannot_starve_quorum(self):
        """A Byzantine replica echoing the agreed verdict WITHOUT its
        signature must not count toward the quorum (round-2 review
        finding): honest replicas still deliver f+1 valid signatures."""
        from corda_tpu.node.notary import NotaryClientFlow
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members, bus = net.create_bft_notary_cluster(n_members=4)
        bank = net.create_node("O=BFTBank4,L=London,C=GB")
        try:
            # replica 0 (the primary) turns Byzantine: strips its tx_sig
            evil = bus.replicas[0]
            original_reply = evil.reply_fn

            def stripping_reply(client_id, request_id, result):
                if isinstance(result, dict):
                    result = {
                        k: v for k, v in result.items() if k != "tx_sig"
                    }
                original_reply(client_id, request_id, result)

            evil.reply_fn = stripping_reply
            stx1, _ = self._spend_pair(net, bank, cluster)
            h = bank.start_flow(
                NotaryClientFlow(stx1, notary_validating=False), stx1
            )
            net.run_network()
            sigs = h.result.result(timeout=30)
            signers = {s.by.encoded for s in sigs}
            assert len(signers) >= 2
            # every returned signature is a valid leaf signature
            leaf = {k.encoded for k in cluster.owning_key.keys}
            assert signers <= leaf
            assert all(s.is_valid(stx1.id.bytes) for s in sigs)
        finally:
            net.stop_nodes()


class TestRaftNotaryCluster:
    """CFT cluster: commits replicate through Raft; any member serves and
    a leader crash fails over (reference RaftValidatingNotaryService)."""

    def _issue_and_pay(self, net, bank, cluster):
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.finance.flows import CashIssueFlow, CashPaymentFlow

        h = bank.start_flow(CashIssueFlow(
            Amount(100, "USD"), b"\x01", bank.info, cluster
        ))
        net.run_network()
        h.result.result(timeout=20)
        token = Issued(bank.info.ref(1), "USD")
        h = bank.start_flow(CashPaymentFlow(
            Amount(100, token), bank.info, cluster
        ))
        net.run_network()
        return h.result.result(timeout=20)

    def test_raft_cluster_notarises(self):
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members, bus = net.create_raft_notary_cluster(n_members=3)
        bank = net.create_node("O=RaftBank,L=London,C=GB")
        try:
            self._issue_and_pay(net, bank, cluster)
            states = bank.services.vault_service.unconsumed_states()
            assert states and all(
                s.state.notary.name == cluster.name for s in states
            )
        finally:
            net.stop_nodes()

    def test_leader_crash_fails_over(self):
        from corda_tpu.testing import MockNetwork

        net = MockNetwork()
        cluster, members, bus = net.create_raft_notary_cluster(n_members=3)
        bank = net.create_node("O=RaftBank2,L=London,C=GB")
        try:
            self._issue_and_pay(net, bank, cluster)
            leader = bus.leader()
            bus.kill(leader.node_id)
            # a new leader is elected and the cluster keeps notarising
            self._issue_and_pay(net, bank, cluster)
            new_leader = bus.leader()
            assert new_leader is not None
            assert new_leader.node_id != leader.node_id
        finally:
            net.stop_nodes()


class TestGeneratedLedgerThroughClusters:
    """Property test: a generated always-valid transaction DAG commits
    in order through a BFT cluster's replicated log; every commit yields
    f+1 replica signatures fulfilling the composite identity, and any
    replayed input conflicts (reference GeneratedLedger + VerifierTests
    style property coverage, applied to the consensus tier)."""

    def test_dag_commits_and_replays_conflict(self):
        import random

        from corda_tpu.node.notary import NotaryException
        from corda_tpu.testing import MockNetwork
        from corda_tpu.testing.generated_ledger import generate_ledger

        gl = generate_ledger(
            random.Random(77), n_parties=3, n_transactions=25,
            entropy_base=60_000,
        )
        net = MockNetwork()
        cluster, members, bus = net.create_bft_notary_cluster(n_members=4)
        svc = members[0].notary_service
        try:
            committed = []
            for stx in gl.transactions:
                inputs = list(stx.tx.inputs)
                if not inputs:
                    continue
                sigs = svc.commit_input_states(inputs, stx.id)
                assert sigs, "BFT commit must return replica signatures"
                assert cluster.owning_key.is_fulfilled_by(
                    {s.by for s in sigs}
                )
                assert all(s.is_valid(stx.id.bytes) for s in sigs)
                committed.append((inputs, stx.id))
            assert committed, "generated ledger had no spends"
            # replaying ANY consumed input under a different tx conflicts,
            # no matter which member serves it
            from corda_tpu.core.crypto.secure_hash import SecureHash

            for i, (inputs, _tx_id) in enumerate(committed[:5]):
                other = members[(i + 1) % len(members)].notary_service
                with pytest.raises(NotaryException):
                    other.commit_input_states(
                        inputs[:1], SecureHash.sha256(f"evil{i}".encode())
                    )
        finally:
            net.stop_nodes()

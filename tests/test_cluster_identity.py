"""Cluster service identity tests (reference ServiceIdentityGenerator +
distributed notary composite keys)."""
import pytest

from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto.signing import sign_bytes
from corda_tpu.node.cluster_identity import (
    generate_service_identity,
    load_service_identity,
    write_service_identity,
)


def _members(n):
    return [crypto.entropy_to_keypair(900 + i) for i in range(n)]


class TestGenerator:
    def test_composite_identity_thresholds(self):
        kps = _members(3)
        pub_keys = [kp.public for kp in kps]
        cluster = generate_service_identity(
            "O=NotaryCluster,L=Zurich,C=CH", pub_keys, threshold=2
        )
        # one member is not enough, two distinct members are
        assert not cluster.owning_key.is_fulfilled_by({pub_keys[0]})
        assert cluster.owning_key.is_fulfilled_by({pub_keys[0], pub_keys[2]})

    def test_default_threshold_is_one(self):
        kps = _members(3)
        cluster = generate_service_identity(
            "O=Raft,L=Z,C=CH", [kp.public for kp in kps]
        )
        assert cluster.owning_key.is_fulfilled_by({kps[1].public})

    def test_invalid_inputs(self):
        with pytest.raises(ValueError):
            generate_service_identity("O=X,L=Y,C=ZZ", [])
        kps = _members(2)
        with pytest.raises(ValueError):
            generate_service_identity(
                "O=X,L=Y,C=ZZ", [k.public for k in kps], threshold=3
            )

    def test_round_trips_disk(self, tmp_path):
        kps = _members(3)
        cluster = generate_service_identity(
            "O=C,L=Z,C=CH", [kp.public for kp in kps], threshold=2
        )
        path = write_service_identity(cluster, str(tmp_path))
        loaded = load_service_identity(path)
        assert loaded.name == cluster.name
        assert loaded.owning_key.encoded == cluster.owning_key.encoded


class TestClientValidation:
    """NotaryClientFlow's collective-fulfillment check, unit-level."""

    def _sigs(self, kps, content):
        return [
            sign_bytes(kp.private, kp.public, content) for kp in kps
        ]

    def test_bft_style_threshold_met(self):
        kps = _members(4)  # f=1 cluster: threshold f+1 = 2
        cluster = generate_service_identity(
            "O=BFT,L=Z,C=CH", [kp.public for kp in kps], threshold=2
        )
        content = b"tx-id-bytes-0123456789abcdef0123"
        sigs = self._sigs(kps[:2], content)
        assert cluster.owning_key.is_fulfilled_by({s.by for s in sigs})
        assert all(s.is_valid(content) for s in sigs)

    def test_single_replica_cannot_fulfil_bft_identity(self):
        kps = _members(4)
        cluster = generate_service_identity(
            "O=BFT,L=Z,C=CH", [kp.public for kp in kps], threshold=2
        )
        content = b"tx-id-bytes-0123456789abcdef0123"
        sigs = self._sigs(kps[:1], content)
        # even repeated signatures from ONE replica don't reach threshold
        assert not cluster.owning_key.is_fulfilled_by(
            {s.by for s in sigs + sigs}
        )

    def test_outsider_not_a_leaf(self):
        kps = _members(3)
        outsider = crypto.entropy_to_keypair(999)
        cluster = generate_service_identity(
            "O=C,L=Z,C=CH", [kp.public for kp in kps], threshold=1
        )
        assert outsider.public not in cluster.owning_key.keys

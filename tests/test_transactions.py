"""Transaction model tests.

Layer parity: reference `core/src/test/kotlin/net/corda/core/transactions/`
(WireTransaction/SignedTransaction tests) + `PartialMerkleTreeTest.kt`'s
FilteredTransaction cases + TransactionSignature batch-check semantics.
"""
from dataclasses import dataclass
from typing import List

import pytest

from corda_tpu.core.contracts import (
    Amount,
    Command,
    Contract,
    ContractState,
    StateAndRef,
    StateRef,
    TimeWindow,
    TransactionState,
    TransactionVerificationError,
    TypeOnlyCommandData,
    contract,
)
from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto.composite import CompositeKey
from corda_tpu.core.crypto.signing import DigitalSignatureWithKey
from corda_tpu.core.identity import Party
from corda_tpu.core.serialization.codec import corda_serializable, deserialize, serialize
from corda_tpu.core.transactions import (
    FilteredTransaction,
    FilteredTransactionVerificationError,
    SignaturesMissingError,
    SignedTransaction,
    TransactionBuilder,
    WireTransaction,
)
from corda_tpu.core.transactions.signed import SignatureError

ALICE_KP = crypto.entropy_to_keypair(70)
BOB_KP = crypto.entropy_to_keypair(71)
NOTARY_KP = crypto.entropy_to_keypair(72)
ALICE = Party("O=Alice,L=London,C=GB", ALICE_KP.public)
BOB = Party("O=Bob,L=New York,C=US", BOB_KP.public)
NOTARY = Party("O=Notary,L=Zurich,C=CH", NOTARY_KP.public)


@corda_serializable
@dataclass(frozen=True)
class DummyState(ContractState):
    magic: int = 42
    contract_name = "DummyContract"

    @property
    def participants(self) -> List:
        return []


@contract(name="DummyContract")
class DummyContract(Contract):
    def verify(self, tx) -> None:
        for s in tx.outputs_of_type(DummyState):
            if s.magic != 42:
                raise TransactionVerificationError(tx.id, "bad magic")


@corda_serializable
@dataclass(frozen=True)
class DummyCommand(TypeOnlyCommandData):
    pass


def _issue_builder():
    b = TransactionBuilder(notary=NOTARY)
    b.add_output_state(DummyState())
    b.add_command(DummyCommand(), ALICE_KP.public)
    return b


class TestWireTransaction:
    def test_id_is_merkle_root_and_stable(self):
        wtx = _issue_builder().to_wire_transaction()
        assert wtx.id == wtx.merkle_tree.hash
        # deserialized copy has the same id (byte-stable components)
        wtx2 = deserialize(serialize(wtx))
        assert wtx2.id == wtx.id

    def test_id_changes_with_content(self):
        b = _issue_builder()
        wtx1 = b.to_wire_transaction()
        b.add_output_state(DummyState())
        assert b.to_wire_transaction().id != wtx1.id

    def test_required_signing_keys(self):
        wtx = _issue_builder().to_wire_transaction()
        # issue tx: no inputs, no time window -> notary key not required
        assert wtx.required_signing_keys == frozenset({ALICE_KP.public})
        b = _issue_builder()
        b.set_time_window(TimeWindow.from_only(10))
        assert NOTARY_KP.public in b.to_wire_transaction().required_signing_keys

    def test_empty_transaction_rejected(self):
        with pytest.raises(ValueError):
            WireTransaction(notary=NOTARY)

    def test_time_window_requires_notary(self):
        with pytest.raises(ValueError):
            WireTransaction(
                outputs=(TransactionState(DummyState(), NOTARY),),
                time_window=TimeWindow.from_only(1),
                notary=None,
            )


class TestSignedTransaction:
    def test_sign_and_verify(self):
        stx = _issue_builder().sign_with(ALICE_KP).to_signed_transaction()
        stx.verify_required_signatures()

    def test_missing_signature_detected(self):
        stx = _issue_builder().sign_with(BOB_KP).to_signed_transaction(
            check_sufficient_signatures=False
        )
        with pytest.raises(SignaturesMissingError) as e:
            stx.verify_required_signatures()
        assert ALICE_KP.public in e.value.missing

    def test_verify_signatures_except(self):
        stx = _issue_builder().sign_with(BOB_KP).to_signed_transaction(
            check_sufficient_signatures=False
        )
        stx.verify_signatures_except(ALICE_KP.public)

    def test_tampered_signature_rejected(self):
        stx = _issue_builder().sign_with(ALICE_KP).to_signed_transaction()
        good = stx.sigs[0]
        bad = DigitalSignatureWithKey(
            good.bytes[:-1] + bytes([good.bytes[-1] ^ 1]), good.by
        )
        tampered = SignedTransaction(stx.tx_bits, (bad,))
        with pytest.raises(SignatureError):
            tampered.verify_required_signatures()

    def test_wrong_key_signature_rejected(self):
        stx = _issue_builder().sign_with(ALICE_KP).to_signed_transaction()
        forged = DigitalSignatureWithKey(stx.sigs[0].bytes, BOB_KP.public)
        with pytest.raises(SignatureError):
            SignedTransaction(stx.tx_bits, (forged,)).verify_required_signatures()

    def test_composite_key_threshold_fulfilment(self):
        composite = CompositeKey.Builder().add_keys(
            ALICE_KP.public, BOB_KP.public
        ).build(threshold=1)
        b = TransactionBuilder(notary=NOTARY)
        b.add_output_state(DummyState())
        b.add_command(DummyCommand(), composite)
        stx = b.sign_with(ALICE_KP).to_signed_transaction(
            check_sufficient_signatures=False
        )
        # 1-of-2 composite requirement satisfied by Alice's leaf signature
        stx.verify_required_signatures()

    def test_composite_wrapping_cannot_impersonate_leaf_signer(self):
        # Attack: Bob wraps Alice's required key in a 1-of-2 composite he can
        # satisfy alone, then signs with the composite. Alice's required
        # signature must still be reported missing.
        from corda_tpu.core.crypto.composite import CompositeSignaturesWithKeys

        composite = CompositeKey.Builder().add_keys(
            BOB_KP.public, ALICE_KP.public
        ).build(threshold=1)
        stx = _issue_builder().sign_with(BOB_KP).to_signed_transaction(
            check_sufficient_signatures=False
        )
        leaf_sig = crypto.do_sign(BOB_KP.private, stx.id.bytes)
        comp_sig = DigitalSignatureWithKey(
            CompositeSignaturesWithKeys(((BOB_KP.public, leaf_sig),)).serialize(),
            composite,
        )
        attacked = SignedTransaction(stx.tx_bits, (comp_sig,))
        with pytest.raises(SignaturesMissingError) as e:
            attacked.verify_required_signatures()
        assert ALICE_KP.public in e.value.missing

    def test_with_additional_signature(self):
        stx = _issue_builder().sign_with(BOB_KP).to_signed_transaction(
            check_sufficient_signatures=False
        )
        sig = DigitalSignatureWithKey(
            crypto.do_sign(ALICE_KP.private, stx.id.bytes), ALICE_KP.public
        )
        (stx + sig).verify_required_signatures()

    def test_serialization_roundtrip(self):
        stx = _issue_builder().sign_with(ALICE_KP).to_signed_transaction()
        stx2 = deserialize(serialize(stx))
        assert stx2.id == stx.id
        stx2.verify_required_signatures()


class TestLedgerTransaction:
    def _ledger_tx(self, wtx: WireTransaction, input_states=None):
        input_states = input_states or {}
        return wtx.to_ledger_transaction(
            resolve_state=lambda ref: input_states[ref],
            resolve_attachment=lambda h: (_ for _ in ()).throw(KeyError(h)),
        )

    def test_contract_verify_passes(self):
        ltx = self._ledger_tx(_issue_builder().to_wire_transaction())
        ltx.verify()

    def test_contract_verify_rejects(self):
        b = TransactionBuilder(notary=NOTARY)
        b.add_output_state(DummyState(magic=13))
        b.add_command(DummyCommand(), ALICE_KP.public)
        ltx = self._ledger_tx(b.to_wire_transaction())
        with pytest.raises(TransactionVerificationError):
            ltx.verify()

    def test_notary_consistency(self):
        issue = _issue_builder().to_wire_transaction()
        ref = StateRef(issue.id, 0)
        other_notary = Party("O=Other,L=Paris,C=FR", crypto.entropy_to_keypair(99).public)
        b = TransactionBuilder(notary=other_notary)
        b._inputs.append(ref)  # bypass builder's own notary check
        b.add_output_state(DummyState())
        b.add_command(DummyCommand(), ALICE_KP.public)
        ltx = self._ledger_tx(
            b.to_wire_transaction(), {ref: TransactionState(DummyState(), NOTARY)}
        )
        with pytest.raises(TransactionVerificationError, match="notary"):
            ltx.verify()

    def test_duplicate_inputs_rejected(self):
        issue = _issue_builder().to_wire_transaction()
        ref = StateRef(issue.id, 0)
        with pytest.raises(ValueError, match="duplicate"):
            WireTransaction(
                inputs=(ref, ref),
                outputs=(TransactionState(DummyState(), NOTARY),),
                commands=(Command(DummyCommand(), (ALICE_KP.public,)),),
                notary=NOTARY,
            )

    def test_ledger_transaction_duplicate_inputs_rejected(self):
        issue = _issue_builder().to_wire_transaction()
        snr = StateAndRef(
            TransactionState(DummyState(), NOTARY), StateRef(issue.id, 0)
        )
        from corda_tpu.core.transactions import LedgerTransaction

        ltx = LedgerTransaction(
            inputs=(snr, snr),
            outputs=(),
            commands=(),
            attachments=(),
            id=issue.id,
            notary=NOTARY,
            time_window=None,
        )
        with pytest.raises(TransactionVerificationError, match="[Dd]uplicate"):
            ltx.verify()

    def test_group_states(self):
        b = TransactionBuilder(notary=NOTARY)
        b.add_output_state(DummyState(magic=42))
        b.add_output_state(DummyState(magic=42))
        b.add_command(DummyCommand(), ALICE_KP.public)
        ltx = self._ledger_tx(b.to_wire_transaction())
        groups = ltx.group_states(DummyState, lambda s: s.magic)
        assert len(groups) == 1 and len(groups[0].outputs) == 2


class TestFilteredTransaction:
    def _wtx(self):
        b = _issue_builder()
        b.set_time_window(TimeWindow.between(100, 200))
        return b.to_wire_transaction()

    def test_build_and_verify(self):
        wtx = self._wtx()
        ftx = wtx.build_filtered_transaction(
            lambda c: isinstance(c, (TimeWindow, Command))
        )
        assert ftx.id == wtx.id
        ftx.verify()
        assert ftx.time_window == wtx.time_window
        assert len(ftx.commands) == 1
        assert ftx.outputs == []  # hidden

    def test_tampered_component_rejected(self):
        wtx = self._wtx()
        ftx = wtx.build_filtered_transaction(lambda c: isinstance(c, TimeWindow))
        from corda_tpu.core.transactions.filtered import FilteredComponent

        fake = FilteredComponent(
            ftx.filtered_components[0].group,
            ftx.filtered_components[0].index,
            TimeWindow.between(1, 2),  # altered content
            ftx.filtered_components[0].nonce,
        )
        tampered = FilteredTransaction(ftx.id, (fake,), ftx.partial_tree)
        with pytest.raises(FilteredTransactionVerificationError):
            tampered.verify()

    def test_relabelled_position_rejected(self):
        # A genuine leaf presented under a different (group, index) must fail:
        # the leaf preimage binds the position.
        b = _issue_builder()
        b.add_output_state(DummyState(magic=42))
        wtx = b.to_wire_transaction()
        ftx = wtx.build_filtered_transaction(
            lambda c: isinstance(c, TransactionState)
        )
        from corda_tpu.core.transactions.filtered import FilteredComponent
        from corda_tpu.core.transactions.wire import ComponentGroup

        fc0, fc1 = [
            fc for fc in ftx.filtered_components
            if fc.group != ComponentGroup.GROUP_SIZES
        ]
        swapped = (
            FilteredComponent(fc0.group, fc1.index, fc0.component, fc0.nonce),
            FilteredComponent(fc1.group, fc0.index, fc1.component, fc1.nonce),
        )
        tampered = FilteredTransaction(ftx.id, swapped, ftx.partial_tree)
        with pytest.raises(FilteredTransactionVerificationError):
            tampered.verify()

    def test_roundtrip(self):
        wtx = self._wtx()
        ftx = wtx.build_filtered_transaction(lambda c: True)
        ftx2 = deserialize(serialize(ftx))
        ftx2.verify()
        assert ftx2.id == wtx.id

    def test_check_with_fun(self):
        wtx = self._wtx()
        ftx = wtx.build_filtered_transaction(lambda c: isinstance(c, TimeWindow))
        assert ftx.check_with_fun(lambda c: isinstance(c, TimeWindow))
        assert not ftx.check_with_fun(lambda c: False)


class TestAmountAndTimeWindow:
    def test_amount_math(self):
        a = Amount(100, "USD")
        b = Amount(50, "USD")
        assert (a + b).quantity == 150
        assert (a - b).quantity == 50
        with pytest.raises(ValueError):
            a + Amount(1, "GBP")
        with pytest.raises(ValueError):
            Amount(-1, "USD")

    def test_amount_from_decimal(self):
        assert Amount.from_decimal(1.25, "USD").quantity == 125
        with pytest.raises(ValueError, match="minor unit"):
            Amount.from_decimal(1.005, "USD")  # half a cent: lossy
        assert Amount.from_decimal(1.005, "USD", rounding="floor").quantity == 100
        assert Amount.from_decimal(1.005, "USD", rounding="round").quantity == 101
        assert repr(Amount(1, "JPY")) == "1 JPY"
        assert repr(Amount(1, "BHD")) == "0.001 BHD"

    def test_time_window(self):
        tw = TimeWindow.between(100, 200)
        assert tw.contains(100) and tw.contains(199)
        assert not tw.contains(200) and not tw.contains(99)
        assert tw.midpoint == 150
        with pytest.raises(ValueError):
            TimeWindow(None, None)
        with pytest.raises(ValueError):
            TimeWindow.between(200, 100)

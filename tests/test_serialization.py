"""Codec tests: determinism, round-trips, whitelist enforcement.

Mirrors the role of reference `core/src/test/.../serialization/` round-trip
suites, adapted to the single canonical format.
"""
from dataclasses import dataclass

import pytest

from corda_tpu.core import crypto as c
from corda_tpu.core.serialization import (
    SerializationError,
    corda_serializable,
    deserialize,
    serialize,
)


@corda_serializable
@dataclass(frozen=True)
class Payment:
    amount: int
    currency: str
    memo: bytes


@pytest.mark.parametrize(
    "value",
    [
        None, True, False, 0, 1, -1, 2**70, -(2**70),
        b"", b"\x00\xff", "", "hello é世界",
        [1, [2, 3], "x"], {"b": 1, "a": 2}, {1: "one", (1, 2): "tup"},
        3.14159, [None, True, {"k": b"v"}],
    ],
)
def test_primitive_roundtrip(value):
    assert deserialize(serialize(value)) == value


def test_tuple_decodes_as_list():
    assert deserialize(serialize((1, 2))) == [1, 2]


def test_map_key_order_is_canonical():
    a = serialize({"x": 1, "y": 2, "z": 3})
    b = serialize({"z": 3, "y": 2, "x": 1})
    assert a == b


def test_set_is_canonical():
    assert serialize({3, 1, 2}) == serialize({2, 3, 1})
    assert sorted(deserialize(serialize({3, 1, 2}))) == [1, 2, 3]


def test_registered_dataclass_roundtrip():
    p = Payment(100, "USD", b"invoice-42")
    out = deserialize(serialize(p))
    assert out == p
    assert isinstance(out, Payment)


def test_object_field_order_is_canonical():
    # same object serialized twice is byte-identical
    p = Payment(1, "GBP", b"")
    assert serialize(p) == serialize(p)


def test_unregistered_type_rejected():
    class Rogue:
        pass

    with pytest.raises(SerializationError):
        serialize(Rogue())


def test_unknown_type_name_rejected_on_decode():
    raw = bytearray(serialize(Payment(1, "EUR", b"")))
    # corrupt the embedded type name
    idx = bytes(raw).find(b"Payment")
    raw[idx : idx + 7] = b"Evil!!!"
    with pytest.raises(SerializationError):
        deserialize(bytes(raw))


def test_truncation_and_trailing_rejected():
    raw = serialize([1, 2, 3])
    with pytest.raises(SerializationError):
        deserialize(raw[:-1])
    with pytest.raises(SerializationError):
        deserialize(raw + b"\x00")
    with pytest.raises(SerializationError):
        deserialize(b"XX" + raw)


def test_nan_rejected():
    with pytest.raises(SerializationError):
        serialize(float("nan"))


def test_crypto_types_roundtrip():
    kp = c.generate_keypair()
    h = c.SecureHash.sha256(b"tx")
    sig = c.sign_bytes(kp.private, kp.public, h.bytes)
    out = deserialize(serialize({"id": h, "sig": sig, "key": kp.public}))
    assert out["id"] == h
    assert out["key"] == kp.public
    assert out["sig"].verify(h.bytes)


def test_composite_key_roundtrip():
    kps = [c.derive_keypair_from_entropy(c.EDDSA_ED25519_SHA512, 7000 + i) for i in range(3)]
    ck = c.CompositeKey.Builder().add_keys(*[k.public for k in kps]).build(threshold=2)
    out = deserialize(serialize(ck))
    assert out == ck
    assert out.is_fulfilled_by([kps[0].public, kps[2].public])


def test_signed_data_verified():
    kp = c.generate_keypair()
    payload = serialize({"role": "notary", "seq": 1})
    sd = c.SignedData(payload, c.sign_bytes(kp.private, kp.public, payload))
    assert sd.verified() == {"role": "notary", "seq": 1}
    # tampered payload fails signature check
    bad = c.SignedData(payload + b" ", sd.sig)
    with pytest.raises(c.SignatureError):
        bad.verified()


def test_leaf_index_with_collapsed_subtrees():
    from corda_tpu.core.crypto.merkle import MerkleTree, PartialMerkleTree
    from corda_tpu.core.crypto.secure_hash import SecureHash

    ls = [SecureHash.sha256(bytes([i])) for i in range(8)]
    tree = MerkleTree.get_merkle_tree(ls)
    pmt = PartialMerkleTree.build(tree, [ls[7]])
    assert pmt.leaf_index(ls[7]) == 7
    pmt2 = PartialMerkleTree.build(tree, [ls[0], ls[7]])
    assert pmt2.leaf_index(ls[0]) == 0
    assert pmt2.leaf_index(ls[7]) == 7
    pmt3 = PartialMerkleTree.build(tree, [ls[3], ls[5]])
    assert pmt3.leaf_index(ls[3]) == 3
    assert pmt3.leaf_index(ls[5]) == 5


class TestAttachmentContractLoading:
    """Attachment-delivered contract code (reference
    AttachmentsClassLoader.kt:23-40): load, resolve by name, reject
    overlapping paths."""

    CONTRACT_SRC = b"""
from dataclasses import dataclass
from typing import List

from corda_tpu.core.contracts import Contract, ContractState, contract
from corda_tpu.core.serialization.codec import corda_serializable


@corda_serializable
@dataclass(frozen=True)
class ShippedState(ContractState):
    n: int = 1
    contract_name = "shipped.Demo"

    @property
    def participants(self) -> List:
        return []


@contract(name="shipped.Demo")
class ShippedContract(Contract):
    def verify(self, tx) -> None:
        pass
"""

    @staticmethod
    def _zip(entries):
        import io
        import zipfile

        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as zf:
            for name, content in entries.items():
                zf.writestr(name, content)
        return buf.getvalue()

    def test_load_and_resolve(self):
        from corda_tpu.core.contracts.structures import resolve_contract
        from corda_tpu.core.serialization.attachments_loader import (
            load_contracts_from_attachments,
        )

        blob = self._zip({"contracts/demo.py": self.CONTRACT_SRC})
        new = load_contracts_from_attachments([blob])
        assert "shipped.Demo" in new
        assert resolve_contract("shipped.Demo") is not None
        # identical re-load is a no-op
        assert load_contracts_from_attachments([blob]) == []

    def test_overlap_rejected(self):
        from corda_tpu.core.serialization.attachments_loader import (
            OverlappingAttachments,
            load_contracts_from_attachments,
        )

        a = self._zip({"contracts/overlap_case.py": b"X = 1\n"})
        b = self._zip({"contracts/overlap_case.py": b"X = 2\n"})
        with pytest.raises(OverlappingAttachments):
            load_contracts_from_attachments([a, b])

    def test_bad_zip_rejected(self):
        from corda_tpu.core.serialization.attachments_loader import (
            AttachmentLoadError,
            load_contracts_from_attachments,
        )

        with pytest.raises(AttachmentLoadError):
            load_contracts_from_attachments([b"not a zip"])

    def test_partial_load_rolls_back(self):
        from corda_tpu.core.contracts.structures import _CONTRACT_REGISTRY
        from corda_tpu.core.serialization.attachments_loader import (
            AttachmentLoadError,
            load_contracts_from_attachments,
        )

        good = (
            b"from corda_tpu.core.contracts import Contract, contract\n"
            b"@contract(name='rollback.Demo')\n"
            b"class C(Contract):\n"
            b"    def verify(self, tx): pass\n"
        )
        bad = b"raise RuntimeError('boom')\n"
        blob = self._zip({
            "a/ok_module.py": good,
            "b/explodes.py": bad,
        })
        with pytest.raises(AttachmentLoadError):
            load_contracts_from_attachments([blob])
        assert "rollback.Demo" not in _CONTRACT_REGISTRY

    def test_same_path_different_txs_allowed(self):
        from corda_tpu.core.serialization.attachments_loader import (
            load_contracts_from_attachments,
        )

        a = self._zip({"contracts/contract.py": b"A1 = 1\n"})
        b = self._zip({"contracts/contract.py": b"A2 = 2\n"})
        # separate calls = separate transactions: both load fine
        load_contracts_from_attachments([a])
        load_contracts_from_attachments([b])


class TestNativeCodecParity:
    """The C codec extension must be byte-for-byte identical to the
    pure-Python encoder and round-trip identically — tx ids are Merkle
    roots over these bytes, so parity is a consensus property."""

    def _python_serialize(self, value):
        from corda_tpu.core.serialization import codec

        out = bytearray(codec._MAGIC)
        codec._encode(out, value)
        return bytes(out)

    def _python_deserialize(self, data):
        from corda_tpu.core.serialization import codec

        value, pos = codec._decode(data, len(codec._MAGIC))
        assert pos == len(data)
        return value

    def test_extension_is_active(self):
        from corda_tpu.core.serialization import codec

        assert codec._native_codec is not None, (
            "native codec failed to build — the toolchain is in the image"
        )

    def test_fuzz_differential(self):
        import random

        from corda_tpu.core.crypto.secure_hash import SecureHash
        from corda_tpu.core.serialization.codec import deserialize, serialize

        rng = random.Random(1234)

        def gen(depth=0):
            kinds = ["int", "bigint", "str", "bytes", "bool", "none",
                     "float"]
            if depth < 4:
                kinds += ["list", "dict", "set", "obj"] * 2
            k = rng.choice(kinds)
            if k == "int":
                return rng.randint(-2**62, 2**62)
            if k == "bigint":
                return rng.randint(-2**300, 2**300)
            if k == "str":
                return "".join(
                    rng.choice("abcXYZ漢字🎉 _:") for _ in range(rng.randint(0, 20))
                )
            if k == "bytes":
                return rng.randbytes(rng.randint(0, 40))
            if k == "bool":
                return rng.choice([True, False])
            if k == "none":
                return None
            if k == "float":
                return rng.choice([0.0, 1.5, -2.25, 1e300, 123.456])
            if k == "list":
                return [gen(depth + 1) for _ in range(rng.randint(0, 5))]
            if k == "dict":
                return {
                    rng.choice(["a", "bb", "z", "k1", "漢"]) + str(i): gen(depth + 1)
                    for i in range(rng.randint(0, 5))
                }
            if k == "set":
                return frozenset(
                    rng.randint(0, 1000) for _ in range(rng.randint(0, 5))
                )
            return SecureHash(rng.randbytes(32))  # registered OBJ type

        for _ in range(300):
            value = gen()
            nb = serialize(value)
            pb = self._python_serialize(value)
            assert nb == pb, (value, nb.hex(), pb.hex())
            assert deserialize(nb) == self._python_deserialize(pb)

    def test_error_parity(self):
        import math

        from corda_tpu.core.serialization.codec import (
            SerializationError,
            deserialize,
            serialize,
        )

        for bad in (float("nan"), -0.0, object()):
            with pytest.raises(SerializationError):
                serialize(bad)
        with pytest.raises(SerializationError):
            deserialize(b"XX\x01\x00")  # bad magic
        with pytest.raises(SerializationError):
            deserialize(serialize([1, 2]) + b"\x00")  # trailing bytes
        with pytest.raises(SerializationError):
            deserialize(b"CT\x01\x08\x03abc")  # unknown OBJ type 'abc', 0 fields... truncated
        assert serialize(math.inf)  # inf is allowed, like the python path

    def test_padded_varint_parity(self):
        """Non-canonical zero-padded length varints (hostile or buggy
        peers) must decode IDENTICALLY on the native and Python paths —
        a split here is a consensus fork (round-3 review finding)."""
        from corda_tpu.core.serialization.codec import deserialize

        # TAG_BYTES with length 2 encoded in 10 varint bytes
        padded = b"CT\x01" + bytes([4]) + b"\x82" + b"\x80" * 8 + b"\x00" + b"ab"
        assert deserialize(padded) == b"ab"
        assert self._python_deserialize(padded) == b"ab"

    def test_hostile_length_rejected(self):
        """A 2^63-1 length varint must reject cleanly on both paths —
        the C bounds check previously overflowed Py_ssize_t (round-3
        review finding: remotely-triggerable OOB read)."""
        from corda_tpu.core.serialization.codec import (
            SerializationError,
            deserialize,
        )

        for tag in (4, 5):  # TAG_BYTES, TAG_STR
            hostile = b"CT\x01" + bytes([tag]) + b"\xff" * 8 + b"\x7f"
            with pytest.raises(SerializationError):
                deserialize(hostile)
            with pytest.raises(SerializationError):
                self._python_deserialize(hostile)

    def test_deep_nesting_capped(self):
        from corda_tpu.core.serialization.codec import (
            SerializationError,
            serialize,
        )

        v = []
        for _ in range(150):
            v = [v]
        with pytest.raises(SerializationError, match="nesting"):
            serialize(v)

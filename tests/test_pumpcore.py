"""GIL-escaped message plane (ISSUE 12): differential parity + overlap.

Pins the consensus-critical contracts of the native batch codec and the
native pump core against their pure-Python fallbacks:

  * serialize_many / deserialize_many are byte-identical to the
    single-shot codec on randomized whitelisted object graphs, and
    malformed frames raise the same SerializationError taxonomy on both
    paths;
  * the wire framing primitives (frame_msgs / frame_send_many /
    parse_msgs / parse_send_many / parse_headers_many) are
    byte-identical to the messaging/net.py code they replace, in both
    directions (native-framed -> python-parsed and vice versa);
  * route_hints_many agrees with shardhost.route_session_hint on every
    hint shape — a retransmit must land on the same worker either way;
  * one wire drain cycle makes O(1) native calls for an N-message
    batch, payloads arrive as zero-copy views over the per-drain arena,
    and ack/redelivery/journal semantics survive the view payloads;
  * the no-native run (kill switches AND a no-compiler build) exercises
    the fallback path with identical bytes, and the native loader
    reports WHY a build was skipped (classified reason + eventlog +
    Native.Available gauges);
  * on a >=2-core box, a pump-heavy burst under utils/sampler.py shows
    the pump thread's runnable share rising once the framing releases
    the GIL (skipped with a named reason on 1-core boxes).
"""
import os
import random
import struct
import subprocess
import sys
import threading
import time

import pytest

from corda_tpu.core.serialization import codec
from corda_tpu.core.serialization.codec import SerializationError
from corda_tpu.messaging import pumpcore
from corda_tpu.messaging.broker import Broker, _encode_headers
from corda_tpu.messaging.net import OP_SEND_MANY, RE_MSG, BrokerServer, RemoteBroker

HAVE_NATIVE = pumpcore.native_active()


def _gen_value(rng, depth=0):
    from corda_tpu.core.crypto.secure_hash import SecureHash

    kinds = ["int", "bigint", "str", "bytes", "bool", "none", "float"]
    if depth < 4:
        kinds += ["list", "dict", "set", "obj"] * 2
    k = rng.choice(kinds)
    if k == "int":
        return rng.randint(-2**62, 2**62)
    if k == "bigint":
        return rng.randint(-2**300, 2**300)
    if k == "str":
        return "".join(
            rng.choice("abcXYZ漢字🎉 _:") for _ in range(rng.randint(0, 20))
        )
    if k == "bytes":
        return rng.randbytes(rng.randint(0, 40))
    if k == "bool":
        return rng.choice([True, False])
    if k == "none":
        return None
    if k == "float":
        return rng.choice([0.0, 1.5, -2.25, 1e300, 123.456])
    if k == "list":
        return [_gen_value(rng, depth + 1) for _ in range(rng.randint(0, 5))]
    if k == "dict":
        return {
            rng.choice(["a", "bb", "z", "k1", "漢"]) + str(i):
                _gen_value(rng, depth + 1)
            for i in range(rng.randint(0, 5))
        }
    if k == "set":
        return frozenset(
            rng.randint(0, 1000) for _ in range(rng.randint(0, 5))
        )
    return SecureHash(rng.randbytes(32))


def _python_serialize(value):
    out = bytearray(codec._MAGIC)
    codec._encode(out, value)
    return bytes(out)


def _python_deserialize(data):
    value, pos = codec._decode(bytes(data), len(codec._MAGIC))
    assert pos == len(data)
    return value


class TestCodecBatchParity:
    def test_batch_entry_points_active(self):
        assert codec._native_codec is not None, (
            "native codec failed to build — the toolchain is in the image"
        )
        assert hasattr(codec._native_codec, "encode_many")
        assert HAVE_NATIVE, "native pump core failed to build"

    def test_fuzz_differential(self):
        rng = random.Random(4321)
        values = [_gen_value(rng) for _ in range(300)]
        frames = codec.serialize_many(values)
        assert len(frames) == len(values)
        for v, frame in zip(values, frames):
            assert bytes(frame) == _python_serialize(v), v
        decoded = codec.deserialize_many([bytes(f) for f in frames])
        singles = [_python_deserialize(bytes(f)) for f in frames]
        assert decoded == singles

    def test_serialize_many_shares_one_arena(self):
        frames = codec.serialize_many([1, "two", b"three"])
        assert all(isinstance(f, memoryview) for f in frames)
        owners = {id(f.obj) for f in frames}
        assert len(owners) == 1, "batch encode must write ONE arena"

    def test_decode_many_accepts_views(self):
        values = [{"k": [1, 2]}, b"payload", "s"]
        frames = [memoryview(codec.serialize(v)) for v in values]
        assert codec.deserialize_many(frames) == values

    def test_deserialize_coerces_views_on_python_path(self, monkeypatch):
        frame = codec.serialize({"k": b"v"})
        monkeypatch.setattr(codec, "_native_codec", None)
        assert codec.deserialize(memoryview(frame)) == {"k": b"v"}
        assert codec.deserialize_many([memoryview(frame)]) == [{"k": b"v"}]

    #: malformed frames, each a distinct failure mode of the grammar
    MALFORMED = [
        b"XX\x01\x00",                                   # bad magic
        b"CT\x01",                                       # empty value
        b"CT\x01\x63",                                   # unknown tag
        b"CT\x01\x04\x05abc",                            # truncated bytes
        b"CT\x01\x05\x03ab",                             # truncated string
        b"CT\x01\x09\x04",                               # truncated float
        b"CT\x01\x03" + b"\x80" * 95,                    # truncated varint
        b"CT\x01\x03" + b"\x80" * 95 + b"\x01",          # varint too long
        b"CT\x01\x04" + b"\xff" * 8 + b"\x7f",           # hostile length
        b"CT\x01\x08\x03abc",                            # truncated OBJ
        b"CT\x01\x06\x02\x00",                           # truncated list
    ]

    def test_malformed_taxonomy_parity(self, monkeypatch):
        good = codec.serialize([1, "x"])
        for bad in self.MALFORMED + [good + b"\x00"]:  # + trailing bytes
            with pytest.raises(SerializationError):
                codec.deserialize_many([good, bad])
            with pytest.raises(SerializationError):
                codec.deserialize(bad)
            with monkeypatch.context() as m:
                m.setattr(codec, "_native_codec", None)
                with pytest.raises(SerializationError):
                    codec.deserialize_many([good, bad])

    def test_unknown_type_rejected(self):
        frame = b"CT\x01\x08\x05NoSuc\x00"
        with pytest.raises(SerializationError, match="whitelist"):
            codec.deserialize_many([frame])

    def test_deep_nesting_capped_both_paths(self, monkeypatch):
        deep = b"CT\x01" + bytes([6, 1]) * 150 + b"\x00"
        with pytest.raises(SerializationError, match="nesting"):
            codec.deserialize_many([deep])
        with monkeypatch.context() as m:
            m.setattr(codec, "_native_codec", None)
            with pytest.raises(SerializationError, match="nesting"):
                codec.deserialize_many([deep])
        v = []
        for _ in range(150):
            v = [v]
        with pytest.raises(SerializationError, match="nesting"):
            codec.serialize_many([v])

    def test_padded_varint_parity(self):
        padded = (
            b"CT\x01" + bytes([4]) + b"\x82" + b"\x80" * 8 + b"\x00" + b"ab"
        )
        assert codec.deserialize_many([padded]) == [b"ab"]
        assert _python_deserialize(padded) == b"ab"

    def test_bigint_roundtrip(self):
        values = [2**64, -2**64, 2**300, -2**300 + 7, 2**63, -2**63]
        frames = codec.serialize_many(values)
        for v, f in zip(values, frames):
            assert bytes(f) == _python_serialize(v)
        assert codec.deserialize_many(frames) == values

    def test_fallback_counters(self, monkeypatch):
        before = codec.batch_stats()
        codec.serialize_many([1])
        codec.deserialize_many([codec.serialize(1)])
        mid = codec.batch_stats()
        assert mid["encode_many_native"] == before["encode_many_native"] + 1
        assert mid["decode_many_native"] == before["decode_many_native"] + 1
        monkeypatch.setattr(codec, "_native_codec", None)
        codec.serialize_many([1])
        codec.deserialize_many([codec.serialize(1)])
        after = codec.batch_stats()
        assert after["encode_many_fallback"] == (
            mid["encode_many_fallback"] + 1
        )
        assert after["decode_many_fallback"] == (
            mid["decode_many_fallback"] + 1
        )


CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus", "decode")


class TestCorpusReplay:
    """Committed malformed-frame regression corpus (tests/corpus/decode)
    replayed against BOTH codec paths with error-taxonomy parity: the
    native and pure-Python decoders must raise the SAME
    SerializationError message — or accept with the same value (the
    padded-varint consensus-compatibility case)."""

    def _corpus(self):
        assert os.path.isdir(CORPUS_DIR), "corpus directory missing"
        out = []
        for fn in sorted(os.listdir(CORPUS_DIR)):
            if fn.endswith(".bin"):
                with open(os.path.join(CORPUS_DIR, fn), "rb") as fh:
                    out.append((fn, fh.read()))
        assert len(out) >= 15, "corpus shrank"
        return out

    @staticmethod
    def _outcome(frame):
        """(value, None) on accept, (None, error message) on reject."""
        try:
            return codec.deserialize(frame), None
        except SerializationError as exc:
            return None, str(exc)

    def test_corpus_taxonomy_parity_both_paths(self, monkeypatch):
        assert codec._native_codec is not None
        for fn, frame in self._corpus():
            native = self._outcome(frame)
            with monkeypatch.context() as m:
                m.setattr(codec, "_native_codec", None)
                python = self._outcome(frame)
            assert native == python, (
                f"{fn}: native={native!r} python={python!r}"
            )

    def test_corpus_through_decode_many(self, monkeypatch):
        """The batch scan path classifies each corpus frame identically
        to the single-shot path, on both codec planes."""
        good = codec.serialize([1, "x"])
        for fn, frame in self._corpus():
            single_value, single_err = self._outcome(frame)
            for use_native in (True, False):
                with monkeypatch.context() as m:
                    if not use_native:
                        m.setattr(codec, "_native_codec", None)
                    try:
                        many_value = codec.deserialize_many(
                            [good, frame]
                        )[1]
                        many_err = None
                    except SerializationError as exc:
                        many_value, many_err = None, str(exc)
                assert (many_err is None) == (single_err is None), (
                    fn, use_native,
                )
                if single_err is not None:
                    assert many_err == single_err, (fn, use_native)
                else:
                    # accept parity includes the VALUE, not just
                    # no-error (the padded-varint case)
                    assert many_value == single_value, (fn, use_native)

    def test_corpus_has_an_accept_case(self):
        """At least one corpus file is the WELL-FORMED non-canonical
        shape (padded varint): parity must hold for accepts too, or the
        corpus only ever proves the reject half."""
        accepted = [fn for fn, frame in self._corpus()
                    if self._outcome(frame)[1] is None]
        assert any("padded" in fn for fn in accepted), accepted


class TestWireParity:
    def _rand_msgs(self, rng, n=16):
        out = []
        for i in range(n):
            headers = {
                rng.choice(["topic", "x-dest", "x-session-route",
                            "traceparent", "k%d" % i, "漢字"]):
                    "".join(rng.choice("abz0-:漢") for _ in range(
                        rng.randint(0, 12)))
                for _ in range(rng.randint(0, 5))
            }
            out.append((
                f"prefix-{i:019d}",
                rng.randint(1, 5),
                headers,
                rng.randbytes(rng.randint(0, 200)),
            ))
        return out

    def test_frame_and_parse_msgs_differential(self, monkeypatch):
        rng = random.Random(99)
        for _ in range(10):
            msgs = self._rand_msgs(rng)
            native = pumpcore.frame_msgs(msgs, RE_MSG)
            with monkeypatch.context() as m:
                m.setattr(pumpcore, "_native", None)
                fallback = pumpcore.frame_msgs(msgs, RE_MSG)
                parsed_py = pumpcore.parse_msgs(native)
            assert native == fallback
            parsed_native = pumpcore.parse_msgs(fallback)
            norm = lambda rows: [
                (mid, dc, h, bytes(p)) for mid, dc, h, p in rows
            ]
            assert norm(parsed_native) == msgs
            assert norm(parsed_py) == msgs

    def test_frame_and_parse_send_many_differential(self, monkeypatch):
        rng = random.Random(7)
        for _ in range(10):
            items = [
                (f"queue.{i}.漢", rng.randbytes(rng.randint(0, 100)),
                 {"h%d" % j: str(j) for j in range(rng.randint(0, 4))})
                for i in range(rng.randint(0, 12))
            ]
            native = pumpcore.frame_send_many(items, OP_SEND_MANY)
            with monkeypatch.context() as m:
                m.setattr(pumpcore, "_native", None)
                fallback = pumpcore.frame_send_many(items, OP_SEND_MANY)
                parsed_py = pumpcore.parse_send_many(native)
            assert native == fallback
            parsed_native = pumpcore.parse_send_many(fallback)
            norm = lambda rows: [(q, bytes(p), h) for q, p, h in rows]
            assert norm(parsed_native) == items
            assert norm(parsed_py) == items

    def test_parse_msgs_payloads_are_arena_views(self):
        if not HAVE_NATIVE:
            pytest.skip("native pump core unavailable")
        msgs = [("m-%019d" % i, 1, {"topic": "t"}, bytes([i]) * 50)
                for i in range(8)]
        reply = pumpcore.frame_msgs(msgs, RE_MSG)
        parsed = pumpcore.parse_msgs(reply)
        for _, _, _, payload in parsed:
            assert isinstance(payload, memoryview)
            assert payload.obj is reply  # zero-copy: views over ONE arena

    def test_parse_headers_many(self, monkeypatch):
        blobs = [
            _encode_headers({"x-session-route": "h:abc", "topic": "s",
                             "x-dest": "Bank A"}),
            _encode_headers({}),
            _encode_headers({"traceparent": "00-ab-cd-01"}),
        ]
        wanted = ("x-session-route", "x-dest", "traceparent")
        expected = [
            ("h:abc", "Bank A", None),
            (None, None, None),
            (None, None, "00-ab-cd-01"),
        ]
        assert pumpcore.parse_headers_many(blobs, wanted) == expected
        with monkeypatch.context() as m:
            m.setattr(pumpcore, "_native", None)
            assert pumpcore.parse_headers_many(blobs, wanted) == expected

    def test_malformed_batch_frame_rejected(self):
        if not HAVE_NATIVE:
            pytest.skip("native pump core unavailable")
        good = pumpcore.frame_msgs(
            [("m-%019d" % 0, 1, {}, b"x")], RE_MSG
        )
        for bad in (b"", b"\x81\x00\x00", good[:-1],
                    b"\x81" + struct.pack(">I", 3) + b"\x00" * 4):
            with pytest.raises(ValueError):
                pumpcore.parse_msgs(bad)
        with pytest.raises(ValueError):
            pumpcore.parse_headers_many([b"\x00\x00\x00\x09"], ("x",))


class TestRouteHints:
    def _hint_corpus(self):
        rng = random.Random(17)
        hints = ["h:w2-abc:1", "t:w3-xyz:9", "t:w9-x", "t:wx-", "bogus",
                 "", None, "h:", "t:w0-a", "x:abc", "t:w12345678901234-a",
                 "h:漢字-session",
                 # Unicode decimal digits must NOT parse as a tag on
                 # either path (\d would have accepted them in Python
                 # while the native parser is ASCII-only — a divergence
                 # that splits a session across workers)
                 "t:w٣-abc", "t:w１-abc"]
        hints += ["h:" + "".join(rng.choice("abcdef0123456789:-w")
                                 for _ in range(rng.randint(0, 80)))
                  for _ in range(150)]
        hints += ["t:" + "".join(rng.choice("w0123456789-x:")
                                 for _ in range(rng.randint(0, 20)))
                  for _ in range(150)]
        return hints

    def test_differential_vs_route_session_hint(self, monkeypatch):
        from corda_tpu.node.shardhost import _NO_HINT, route_session_hint

        hints = self._hint_corpus()
        for n_workers in (1, 2, 4, 7):
            native = pumpcore.route_hints_many(hints, n_workers)
            with monkeypatch.context() as m:
                m.setattr(pumpcore, "_native", None)
                fallback = pumpcore.route_hints_many(hints, n_workers)
            assert native == fallback
            for hint, code in zip(hints, native):
                py = route_session_hint(hint, n_workers)
                expect = (
                    pumpcore.NO_HINT if py is _NO_HINT
                    else pumpcore.SUPERVISOR if py is None
                    else py
                )
                assert code == expect, (hint, n_workers)

    def test_router_targets_of_agrees_with_target_of(self):
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.messaging.broker import Message
        from corda_tpu.node.session import ROUTE_HINT_HEADER, SESSION_TOPIC
        from corda_tpu.node.shardhost import ShardRouter

        broker = Broker()
        broker.create_queue("p2p.inbound.RouteNode")
        router = ShardRouter(broker, "RouteNode", 3)  # never start()ed
        rng = random.Random(5)
        batch = []
        for hint in self._hint_corpus()[:60]:
            headers = {"topic": rng.choice([SESSION_TOPIC, "other"])}
            if hint is not None and rng.random() < 0.8:
                headers[ROUTE_HINT_HEADER] = hint
            batch.append(Message(
                payload=serialize({"junk": True}), headers=headers,
                message_id="m%d" % len(batch),
            ))
        assert router.targets_of(batch) == [
            router.target_of(m) for m in batch
        ]
        router._consumer.close()
        broker.close()


class TestDrainSemantics:
    """End-to-end over the real wire layer: O(1) native calls per drain
    cycle, zero-copy arena payloads, ack/redelivery/journal discipline
    intact."""

    def test_one_drain_is_o1_native_calls(self):
        if not HAVE_NATIVE:
            pytest.skip("native pump core unavailable")
        broker = Broker()
        broker.create_queue("drain.test")
        server = BrokerServer(broker).start()
        n_msgs, batch = 256, 64
        try:
            remote = RemoteBroker("127.0.0.1", server.port)
            consumer = remote.create_consumer("drain.test", prefetch=batch)
            before = pumpcore.stats()
            for start in range(0, n_msgs, batch):
                remote.send_many([
                    ("drain.test", b"p%d" % i, {"seq": str(i)})
                    for i in range(start, start + batch)
                ])
            got = []
            while len(got) < n_msgs:
                msg = consumer.receive(timeout=5)
                assert msg is not None
                got.append(msg)
                consumer.ack(msg)
            after = pumpcore.stats()
            # contents survived the native plane
            assert [bytes(m.payload) for m in got] == [
                b"p%d" % i for i in range(n_msgs)
            ]
            assert [m.headers["seq"] for m in got] == [
                str(i) for i in range(n_msgs)
            ]
            # zero-copy arena views on the client side
            assert all(isinstance(m.payload, memoryview) for m in got)
            # O(1) calls per drain cycle: 4 send batches cost 4 frame +
            # 4 parse calls; receives cost one frame+parse per wire
            # drain — far below one call per MESSAGE. Bound generously
            # (scheduling can split wire drains) but well under n_msgs.
            delta = sum(
                after.get(k, 0) - before.get(k, 0)
                for k in after if k.endswith("_native")
            )
            assert delta <= n_msgs // 2, delta
            fallback_delta = sum(
                after.get(k, 0) - before.get(k, 0)
                for k in after if k.endswith("_fallback")
            )
            assert fallback_delta == 0
            consumer.close()
            remote.close()
        finally:
            server.stop()
            broker.close()

    def test_redelivery_preserves_view_payloads(self):
        broker = Broker()
        broker.create_queue("redeliver.test")
        server = BrokerServer(broker).start()
        try:
            remote = RemoteBroker("127.0.0.1", server.port)
            remote.send_many([
                ("redeliver.test", b"keep-me", {"k": "v"}),
            ])
            # consumer takes the message and dies without acking
            c1 = remote.create_consumer("redeliver.test")
            msg = c1.receive(timeout=5)
            assert bytes(msg.payload) == b"keep-me"
            c1.close()
            c2 = remote.create_consumer("redeliver.test")
            again = c2.receive(timeout=5)
            assert again is not None
            assert bytes(again.payload) == b"keep-me"
            assert again.delivery_count == 2
            assert again.headers["k"] == "v"
            c2.ack(again)
            c2.close()
            remote.close()
        finally:
            server.stop()
            broker.close()

    def test_durable_journal_snapshots_arena_views(self, tmp_path):
        # the durability boundary: messages enqueued as views over a
        # wire arena must journal as REAL bytes — a restart replays
        # them intact long after the arena died. The wire server
        # snapshots at enqueue already (arena-retention rule), so ALSO
        # enqueue view payloads locally to pin the journal's own
        # coercion.
        jdir = str(tmp_path / "journal")
        broker = Broker(journal_dir=jdir)
        broker.create_queue("durable.q", durable=True)
        server = BrokerServer(broker).start()
        try:
            remote = RemoteBroker("127.0.0.1", server.port)
            remote.send_many([
                ("durable.q", bytes([i]) * 64, {"i": str(i)})
                for i in range(3)
            ])
            arena = bytes([3]) * 64 + bytes([4]) * 64
            mv = memoryview(arena)
            broker.send("durable.q", mv[:64], {"i": "3"})
            broker.send_many([("durable.q", mv[64:], {"i": "4"})])
            del mv, arena  # the journal record must have its own bytes
            remote.close()
        finally:
            server.stop()
            broker.close()
        revived = Broker(journal_dir=jdir)
        try:
            consumer = revived.create_consumer("durable.q")
            for i in range(5):
                msg = consumer.receive(timeout=2)
                assert msg is not None
                assert bytes(msg.payload) == bytes([i]) * 64
                assert msg.headers["i"] == str(i)
                assert msg.delivery_count == 2  # journal replay
                consumer.ack(msg)
        finally:
            revived.close()


_FALLBACK_SNIPPET = r"""
import os, sys
from corda_tpu.core.serialization import codec
from corda_tpu.messaging import pumpcore
from corda_tpu.messaging.net import RE_MSG

assert codec._native_codec is None, "kill switch ignored by codec"
assert not pumpcore.native_active(), "kill switch ignored by pumpcore"

values = [1, "two", b"three", {"k": [None, True]}, 2**100]
frames = codec.serialize_many(values)
assert [f.hex() for f in frames] == sys.argv[1].split(","), "frame bytes diverged"
assert codec.deserialize_many(frames) == values
stats = codec.batch_stats()
assert stats["encode_many_fallback"] >= 1 and stats["encode_many_native"] == 0
assert stats["decode_many_fallback"] >= 1 and stats["decode_many_native"] == 0

msgs = [("m-%019d" % i, 1, {"topic": "t"}, b"x" * i) for i in range(4)]
body = pumpcore.frame_msgs(msgs, RE_MSG)
assert body.hex() == sys.argv[2], "wire bytes diverged"
parsed = [(m, d, h, bytes(p)) for m, d, h, p in pumpcore.parse_msgs(body)]
assert parsed == msgs
pstats = pumpcore.stats()
assert all(k.endswith("_fallback") for k, v in pstats.items() if v), pstats
print("FALLBACK-OK")
"""


class TestFallbackPath:
    def test_kill_switches_reproduce_native_bytes(self):
        """CORDA_TPU_NATIVE_CODEC=0 / CORDA_TPU_PUMP_NATIVE=0 must
        reproduce the native plane byte-identically — proven by handing
        the fallback subprocess the NATIVE-path bytes to match."""
        values = [1, "two", b"three", {"k": [None, True]}, 2**100]
        native_frames = ",".join(
            bytes(f).hex() for f in codec.serialize_many(values)
        )
        msgs = [("m-%019d" % i, 1, {"topic": "t"}, b"x" * i)
                for i in range(4)]
        native_body = pumpcore.frame_msgs(msgs, RE_MSG).hex()
        env = dict(
            os.environ, CORDA_TPU_NATIVE_CODEC="0",
            CORDA_TPU_PUMP_NATIVE="0", JAX_PLATFORMS="cpu",
        )
        proc = subprocess.run(
            [sys.executable, "-c", _FALLBACK_SNIPPET,
             native_frames, native_body],
            capture_output=True, text=True, env=env, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        assert "FALLBACK-OK" in proc.stdout

    def test_no_compiler_build_classified_and_reported(self, tmp_path):
        """A box without a compiler must fall back with a CLASSIFIED
        reason (no_compiler), an eventlog record, and a working pure-
        Python plane — the no-native tier-1 story in one subprocess."""
        snippet = r"""
import os, sys
import corda_tpu.native as native
native._BUILD = sys.argv[1]  # fresh build dir: force a compile attempt
from corda_tpu.core.serialization import codec
assert codec._native_codec is None, "built without a compiler?"
status = native.availability()
assert status["codec_ext"]["available"] is False
assert status["codec_ext"]["reason"] == "no_compiler", status
assert native._get_lib() is None
assert status != native.availability() or True
for ext in ("sha2_batch", "journal", "ed25519_msm", "ecdsa_host"):
    entry = native.availability()[ext]
    assert entry["available"] is False and entry["reason"] == "no_compiler"
from corda_tpu.utils import eventlog
recs = eventlog.get_event_log().records(component="native")
assert any(r.get("reason") == "no_compiler" for r in recs), recs
assert codec.deserialize(codec.serialize({"x": 1})) == {"x": 1}
print("NOCOMPILER-OK")
"""
        # a PATH with python but no gcc/g++ (symlink the interpreter in)
        bindir = tmp_path / "bin"
        bindir.mkdir()
        (bindir / "python").symlink_to(sys.executable)
        env = dict(os.environ, PATH=str(bindir), JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", snippet, str(tmp_path / "build")],
            capture_output=True, text=True, env=env, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        assert "NOCOMPILER-OK" in proc.stdout


class TestNativeStatusAndCLI:
    def test_availability_reports_all_five(self):
        import corda_tpu.native as native

        native._get_lib()
        native.codec_extension()
        status = native.availability()
        for ext in native.EXTENSIONS:
            assert status[ext]["available"] is True, status

    def test_build_cli_ok(self):
        proc = subprocess.run(
            [sys.executable, "-m", "corda_tpu.native", "--build"],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        for ext in ("sha2_batch", "journal", "ed25519_msm", "ecdsa_host",
                    "codec_ext"):
            assert f"{ext}: OK" in proc.stdout, proc.stdout

    def test_build_cli_fails_on_compile_error_with_compiler(self, tmp_path):
        """CI contract: when a compiler IS present and a source is
        broken, the CLI exits non-zero naming the extension."""
        snippet = r"""
import os, shutil, sys
import corda_tpu.native as native
srcdir, builddir = sys.argv[1], sys.argv[2]
os.makedirs(srcdir, exist_ok=True)
for fname in os.listdir(native._SRC):
    shutil.copy(os.path.join(native._SRC, fname), srcdir)
with open(os.path.join(srcdir, "codec_ext.c"), "a") as fh:
    fh.write("\n#error deliberately broken\n")
native._SRC = srcdir
native._BUILD = builddir
from corda_tpu.native.__main__ import main
rc = main(["--build"])
status = native.availability()
assert status["codec_ext"]["available"] is False
assert status["codec_ext"]["reason"].startswith("compile_error"), status
assert status["sha2_batch"]["available"] is True  # the C++ lib still builds
print("RC=%d" % rc)
"""
        proc = subprocess.run(
            [sys.executable, "-c", snippet, str(tmp_path / "src"),
             str(tmp_path / "build")],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        assert "RC=1" in proc.stdout, proc.stdout

    def test_native_available_gauges_on_node(self):
        from corda_tpu.testing.mocknetwork import MockNetwork

        net = MockNetwork()
        node = net.create_node("O=NativeGauge,L=London,C=GB")
        try:
            snap = node.metrics.snapshot()
            import corda_tpu.native as native

            for ext in native.EXTENSIONS:
                entry = snap.get(f"Native.Available{{ext={ext}}}")
                assert entry is not None, sorted(snap)[:5]
                assert entry["value"] in (-1.0, 0.0, 1.0)
            # this container HAS the toolchain and the codec loaded at
            # import time, so at least codec_ext must read 1
            assert snap["Native.Available{ext=codec_ext}"]["value"] == 1.0
        finally:
            net.stop_nodes()


class TestRetentionAndResilience:
    def test_server_enqueue_snapshots_payloads(self):
        """Arena-retention rule: broker-RESIDENT payloads must be real
        bytes — a queued view would pin its whole multi-message request
        arena for the (unbounded) queue residence."""
        broker = Broker()
        broker.create_queue("resident.q")
        server = BrokerServer(broker).start()
        try:
            remote = RemoteBroker("127.0.0.1", server.port)
            remote.send_many([
                ("resident.q", b"x" * 32, {"i": str(i)}) for i in range(8)
            ])
            with broker._lock:
                payloads = [
                    m.payload for m in broker._queues["resident.q"].messages
                ]
            assert len(payloads) == 8
            assert all(isinstance(p, bytes) for p in payloads)
            remote.close()
        finally:
            server.stop()
            broker.close()

    def test_egress_pump_survives_non_broker_error(self):
        """A non-BrokerError from the batch send (journal OSError, …)
        must fall back to per-message forwarding, not kill the pump
        thread — the old per-message loop never died on one."""
        from corda_tpu.node.shardhost import EGRESS_QUEUE, EgressPump

        broker = Broker()
        broker.create_queue("p2p.inbound.EgressDest")
        fails = [0]
        real_send_many = broker.send_many

        def flaky_send_many(items):
            if fails[0] == 0:
                fails[0] += 1
                raise OSError("disk full mid-batch")
            return real_send_many(items)

        broker.send_many = flaky_send_many
        pump = EgressPump(broker).start()
        try:
            broker.send(EGRESS_QUEUE, b"hello",
                        {"x-dest": "EgressDest", "topic": "t"})
            deadline = time.monotonic() + 5
            while (broker.message_count("p2p.inbound.EgressDest") < 1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert broker.message_count("p2p.inbound.EgressDest") == 1
            assert fails[0] == 1  # the batch path DID fail first
            assert pump._thread.is_alive()
            assert pump.forwarded == 1 and pump.dropped == 0
        finally:
            pump.stop()
            broker.close()


class TestBenchStage:
    def test_gate_directions_for_new_keys(self):
        from corda_tpu.loadtest.gate import direction

        assert direction("pump_drain_msgs_s") == "higher"
        assert direction("codec_batch_speedup_x") == "higher"
        assert direction("codec_batch_native_us_per_obj") == "lower"
        assert direction("codec_batch_python_us_per_obj") == "lower"
        assert direction("codec_batch_decode_us_per_obj") == "lower"
        # provenance keys must NOT gate
        assert direction("pump_drain_native_calls") is None
        assert direction("codec_batch_n") is None

    def test_measure_codec_batch_meets_acceptance(self):
        from corda_tpu.loadtest.latency import measure_codec_batch

        out = measure_codec_batch(n=400)
        assert out["codec_batch_native"] is True
        # parity is asserted INSIDE the helper; the >=3x acceptance
        # line is enforced by bench on the build box — here we pin a
        # lenient floor so a silent fallback can't pass as a win
        assert out["codec_batch_speedup_x"] >= 2.0, out

    def test_measure_pump_drain_smoke(self):
        from corda_tpu.loadtest.latency import measure_pump_drain

        out = measure_pump_drain(n_msgs=200, payload_len=256, batch=32)
        assert out["pump_drain_msgs_s"] > 0
        assert out["pump_drain_native"] is HAVE_NATIVE
        if HAVE_NATIVE:
            # O(1) native calls per drain cycle, not per message
            assert 0 < out["pump_drain_native_calls"] <= 200 // 2


class TestSamplerOverlap:
    """Satellite: a pump-heavy burst under the sampling profiler shows
    the pump thread overlapping a busy Python thread once the framing
    releases the GIL."""

    @pytest.mark.skipif(
        (os.cpu_count() or 1) < 2,
        reason="pump off-GIL overlap needs >=2 cores (1-core box: the "
               "GIL release cannot buy parallelism to observe)",
    )
    @pytest.mark.skipif(not HAVE_NATIVE, reason="native pump core missing")
    def test_pump_thread_runnable_share_rises_off_gil(self, monkeypatch):
        from corda_tpu.utils import sampler

        payload = bytes(256 * 1024)
        msgs = [("m-%019d" % i, 1, {"topic": "x"}, payload)
                for i in range(32)]

        def measure(native_on):
            with monkeypatch.context() as m:
                if not native_on:
                    m.setattr(pumpcore, "_native", None)
                stop = threading.Event()
                spins = [0]

                def busy():
                    # pure-Python GIL-holding competitor
                    x = 0
                    while not stop.is_set():
                        x += 1
                    spins[0] = x

                frames = [0]

                def pump():
                    while not stop.is_set():
                        pumpcore.frame_msgs(msgs, RE_MSG)
                        frames[0] += 1

                tb = threading.Thread(target=busy, name="overlap-busy",
                                      daemon=True)
                tp = threading.Thread(target=pump, name="overlap-pump",
                                      daemon=True)
                tb.start()
                tp.start()
                time.sleep(0.1)  # settle
                cap = sampler.capture(seconds=0.6, interval=0.005)
                stop.set()
                tb.join(timeout=5)
                tp.join(timeout=5)
            row = next(
                t for t in cap["threads"] if t["name"] == "overlap-pump"
            )
            share = row["running"] / max(1, row["running"] + row["waiting"])
            return cap["meta"]["total_cpu_s"], share, frames[0]

        gil_cpu, gil_share, gil_frames = measure(native_on=False)
        nat_cpu, nat_share, nat_frames = measure(native_on=True)
        assert nat_frames > 0 and gil_frames > 0
        # off-GIL framing lets BOTH threads burn a core: total CPU in
        # the window rises, and the pump thread is runnable more often
        assert nat_cpu > gil_cpu * 1.15, (nat_cpu, gil_cpu)
        assert nat_share > gil_share, (nat_share, gil_share)

"""PBFT notary consensus tests (coverage parity with the reference's
BFTNotaryServiceTests): normal-case commit, replica-down progress,
uniqueness conflicts, duplicate-request dedup, primary-failure view change.
Deterministic pumping, no wall clock."""
from collections import deque

import pytest

from corda_tpu.node.bft import BFTClient, BFTReplica


class BFTCluster:
    def __init__(self, n=4):
        self.queue = deque()
        self.partitioned = set()
        self.n = n
        self.applied = {i: [] for i in range(n)}
        self.uniqueness = {i: {} for i in range(n)}
        self.replicas = []
        self.client = BFTClient("client-0", n, self._client_send)

        def make_apply(idx):
            def apply(command):
                self.applied[idx].append(command)
                conflicts = {}
                umap = self.uniqueness[idx]
                for key, txid in command["entries"].items():
                    if key in umap and umap[key] != txid:
                        conflicts[key] = umap[key]
                if not conflicts:
                    umap.update(command["entries"])
                return {"conflicts": conflicts}
            return apply

        def make_transport(src):
            def transport(dst, payload):
                self.queue.append(("replica", src, dst, payload))
            return transport

        def make_reply(idx):
            def reply(client_id, request_id, result):
                self.queue.append(("reply", idx, request_id, result))
            return reply

        for i in range(n):
            self.replicas.append(
                BFTReplica(i, n, make_transport(i), make_apply(i), make_reply(i))
            )

    def _client_send(self, replica_id, request):
        self.queue.append(("request", None, replica_id, request))

    def pump(self, max_rounds=5000):
        rounds = 0
        while self.queue and rounds < max_rounds:
            item = self.queue.popleft()
            rounds += 1
            kind = item[0]
            if kind == "replica":
                _, src, dst, payload = item
                if src in self.partitioned or dst in self.partitioned:
                    continue
                self.replicas[dst].on_message(src, payload)
            elif kind == "request":
                _, _, dst, request = item
                if dst in self.partitioned:
                    continue
                self.replicas[dst].on_request(request)
            elif kind == "reply":
                _, idx, request_id, result = item
                if idx in self.partitioned:
                    continue
                self.client.on_reply(idx, request_id, result)

    def tick_all(self, now):
        for i, r in enumerate(self.replicas):
            if i not in self.partitioned:
                r.tick(now)
        self.pump()


class TestBFT:
    def test_normal_commit(self):
        c = BFTCluster(4)
        fut = c.client.submit({"entries": {"s1": "tx1"}})
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}
        # every replica executed it exactly once
        assert all(len(c.applied[i]) == 1 for i in range(4))

    def test_conflict_detected_consistently(self):
        c = BFTCluster(4)
        f1 = c.client.submit({"entries": {"s1": "tx1"}})
        c.pump()
        f1.result(timeout=0)
        f2 = c.client.submit({"entries": {"s1": "tx2"}})
        c.pump()
        assert f2.result(timeout=0) == {"conflicts": {"s1": "tx1"}}
        # idempotent re-commit of the original is clean
        f3 = c.client.submit({"entries": {"s1": "tx1"}, "nonce": 1})
        c.pump()
        assert f3.result(timeout=0) == {"conflicts": {}}

    def test_progress_with_one_replica_down(self):
        c = BFTCluster(4)
        c.partitioned.add(3)  # f = 1 tolerated
        fut = c.client.submit({"entries": {"k": "t"}})
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}

    def test_no_progress_with_two_down_f1(self):
        c = BFTCluster(4)
        c.partitioned.update({2, 3})
        fut = c.client.submit({"entries": {"k": "t"}})
        c.pump()
        assert not fut.done()

    def test_repeated_reply_from_one_replica_cannot_forge_quorum(self):
        # A single Byzantine replica repeating a fabricated verdict f+1
        # times must not resolve the future (advisor finding, round 1).
        c = BFTCluster(4)
        fut = c.client.submit({"entries": {"s1": "tx1"}})
        request_id = fut.request_id
        forged = {"conflicts": {"forged": "yes"}}
        for _ in range(3):
            c.client.on_reply(3, request_id, forged)
        assert not fut.done()
        # and the honest quorum still wins
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}

    def test_view_change_claim_without_certificate_rejected(self):
        # A prepared claim must carry 2f+1 verifiable prepare signatures;
        # an uncertified (or self-signed-only) claim is ignored.
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.bft import _digest

        c = BFTCluster(4)
        evil_request = {
            "client_id": "client-0", "request_id": "client-0:999",
            "command": {"entries": {"stolen": "tx-evil"}},
        }
        d = _digest(evil_request)
        evil_sig = c.replicas[3]._sign_prepare(0, 0, d)
        msg = {
            "kind": "view_change", "new_view": 1,
            "prepared": [[0, d, evil_request, 0, [[3, evil_sig]]]],
        }
        c.replicas[1].on_message(3, serialize(msg))
        assert d not in c.replicas[1].requests
        assert c.replicas[1].pre_prepares.get(0) != d

    def test_view_change_certificate_carries_prepared_request(self):
        # A claim backed by a genuine 2f+1 certificate IS honored from a
        # single message (PBFT P-set semantics).
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.bft import _digest

        c = BFTCluster(4)
        fut = c.client.submit({"entries": {"s1": "tx1"}})
        c.pump()
        fut.result(timeout=0)
        # replica 0 prepared seq 0 in view 0: reuse its real certificate
        certs = c.replicas[0]._prepared_certificates()
        assert certs, "replica 0 should hold a prepared certificate"
        fresh = BFTCluster(4)  # a replica with no history
        msg = {"kind": "view_change", "new_view": 1, "prepared": certs}
        fresh.replicas[1].on_message(3, serialize(msg))
        seq, d = certs[0][0], certs[0][1]
        assert fresh.replicas[1].pre_prepares.get(seq) == d
        assert d in fresh.replicas[1].requests

    def test_primary_failure_view_change(self):
        c = BFTCluster(4)
        c.partitioned.add(0)  # primary of view 0 is dead
        fut = c.client.submit({"entries": {"k": "t"}})
        c.pump()
        assert not fut.done()
        # non-primaries time out waiting for the primary and change view
        t = 0.0
        for _ in range(12):
            t += 10.0
            c.tick_all(t)
            if fut.done():
                break
        assert fut.result(timeout=0) == {"conflicts": {}}
        live_views = {r.view for i, r in enumerate(c.replicas) if i != 0}
        assert live_views == {1}

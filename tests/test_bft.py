"""PBFT notary consensus tests (coverage parity with the reference's
BFTNotaryServiceTests): normal-case commit, replica-down progress,
uniqueness conflicts, duplicate-request dedup, primary-failure view change.
Deterministic pumping, no wall clock."""
from collections import deque

import pytest

from corda_tpu.core.serialization.codec import deserialize, serialize
from corda_tpu.node.bft import BFTClient, BFTReplica


class _DictMeta:
    """KVStore-shaped adapter over a plain dict (survives replica
    restarts the way the node's durable KVStore does)."""

    def __init__(self, d):
        self._d = d

    def get(self, k):
        return self._d.get(k)

    def put(self, k, v):
        self._d[k] = v


class BFTCluster:
    def __init__(self, n=4):
        self.queue = deque()
        self.partitioned = set()
        self.n = n
        self.applied = {i: [] for i in range(n)}
        self.uniqueness = {i: {} for i in range(n)}
        self.meta = {i: {} for i in range(n)}  # durable replica meta
        self.replicas = []
        self.client = BFTClient("client-0", n, self._client_send)
        for i in range(n):
            self.replicas.append(self._make_replica(i))

    def _make_replica(self, idx):
        def apply(command):
            self.applied[idx].append(command)
            conflicts = {}
            umap = self.uniqueness[idx]
            for key, txid in command["entries"].items():
                if key in umap and umap[key] != txid:
                    conflicts[key] = umap[key]
            if not conflicts:
                umap.update(command["entries"])
            return {"conflicts": conflicts}

        def transport(dst, payload):
            self.queue.append(("replica", idx, dst, payload))

        def reply(client_id, request_id, result):
            self.queue.append(("reply", idx, request_id, result))

        def snapshot():
            return serialize(dict(self.uniqueness[idx]))

        def restore(data):
            self.uniqueness[idx].clear()
            self.uniqueness[idx].update(deserialize(data))

        return BFTReplica(
            idx, self.n, transport, apply, reply,
            snapshot_fn=snapshot, restore_fn=restore,
            meta_store=_DictMeta(self.meta[idx]),
        )

    def restart(self, idx):
        """Simulate a process restart: a FRESH replica instance sharing
        only the durable stores (uniqueness map + meta)."""
        self.partitioned.discard(idx)
        self.replicas[idx] = self._make_replica(idx)

    def _client_send(self, replica_id, request):
        self.queue.append(("request", None, replica_id, request))

    def pump(self, max_rounds=5000):
        rounds = 0
        while self.queue and rounds < max_rounds:
            item = self.queue.popleft()
            rounds += 1
            kind = item[0]
            if kind == "replica":
                _, src, dst, payload = item
                if src in self.partitioned or dst in self.partitioned:
                    continue
                self.replicas[dst].on_message(src, payload)
            elif kind == "request":
                _, _, dst, request = item
                if dst in self.partitioned:
                    continue
                self.replicas[dst].on_request(request)
            elif kind == "reply":
                _, idx, request_id, result = item
                if idx in self.partitioned:
                    continue
                self.client.on_reply(idx, request_id, result)

    def tick_all(self, now):
        for i, r in enumerate(self.replicas):
            if i not in self.partitioned:
                r.tick(now)
        self.pump()


class TestBFT:
    def test_normal_commit(self):
        c = BFTCluster(4)
        fut = c.client.submit({"entries": {"s1": "tx1"}})
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}
        # every replica executed it exactly once
        assert all(len(c.applied[i]) == 1 for i in range(4))

    def test_conflict_detected_consistently(self):
        c = BFTCluster(4)
        f1 = c.client.submit({"entries": {"s1": "tx1"}})
        c.pump()
        f1.result(timeout=0)
        f2 = c.client.submit({"entries": {"s1": "tx2"}})
        c.pump()
        assert f2.result(timeout=0) == {"conflicts": {"s1": "tx1"}}
        # idempotent re-commit of the original is clean
        f3 = c.client.submit({"entries": {"s1": "tx1"}, "nonce": 1})
        c.pump()
        assert f3.result(timeout=0) == {"conflicts": {}}

    def test_progress_with_one_replica_down(self):
        c = BFTCluster(4)
        c.partitioned.add(3)  # f = 1 tolerated
        fut = c.client.submit({"entries": {"k": "t"}})
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}

    def test_no_progress_with_two_down_f1(self):
        c = BFTCluster(4)
        c.partitioned.update({2, 3})
        fut = c.client.submit({"entries": {"k": "t"}})
        c.pump()
        assert not fut.done()

    def test_repeated_reply_from_one_replica_cannot_forge_quorum(self):
        # A single Byzantine replica repeating a fabricated verdict f+1
        # times must not resolve the future (advisor finding, round 1).
        c = BFTCluster(4)
        fut = c.client.submit({"entries": {"s1": "tx1"}})
        request_id = fut.request_id
        forged = {"conflicts": {"forged": "yes"}}
        for _ in range(3):
            c.client.on_reply(3, request_id, forged)
        assert not fut.done()
        # and the honest quorum still wins
        c.pump()
        assert fut.result(timeout=0) == {"conflicts": {}}

    def test_view_change_claim_without_certificate_rejected(self):
        # A prepared claim must carry 2f+1 verifiable prepare signatures;
        # an uncertified (or self-signed-only) claim is ignored.
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.bft import _digest

        c = BFTCluster(4)
        evil_request = {
            "client_id": "client-0", "request_id": "client-0:999",
            "command": {"entries": {"stolen": "tx-evil"}},
        }
        d = _digest(evil_request)
        evil_sig = c.replicas[3]._sign_prepare(0, 0, d)
        msg = {
            "kind": "view_change", "new_view": 1,
            "prepared": [[0, d, evil_request, 0, [[3, evil_sig]]]],
        }
        c.replicas[1].on_message(3, serialize(msg))
        assert d not in c.replicas[1].requests
        assert c.replicas[1].pre_prepares.get(0) != d

    def test_view_change_certificate_carries_prepared_request(self):
        # A claim backed by a genuine 2f+1 certificate IS honored from a
        # single message (PBFT P-set semantics).
        from corda_tpu.core.serialization.codec import serialize
        from corda_tpu.node.bft import _digest

        c = BFTCluster(4)
        fut = c.client.submit({"entries": {"s1": "tx1"}})
        c.pump()
        fut.result(timeout=0)
        # replica 0 prepared seq 0 in view 0: reuse its real certificate
        certs = c.replicas[0]._prepared_certificates()
        assert certs, "replica 0 should hold a prepared certificate"
        fresh = BFTCluster(4)  # a replica with no history
        msg = {"kind": "view_change", "new_view": 1, "prepared": certs}
        fresh.replicas[1].on_message(3, serialize(msg))
        seq, d = certs[0][0], certs[0][1]
        assert fresh.replicas[1].pre_prepares.get(seq) == d
        assert d in fresh.replicas[1].requests

    def test_primary_failure_view_change(self):
        c = BFTCluster(4)
        c.partitioned.add(0)  # primary of view 0 is dead
        fut = c.client.submit({"entries": {"k": "t"}})
        c.pump()
        assert not fut.done()
        # non-primaries time out waiting for the primary and change view
        t = 0.0
        for _ in range(12):
            t += 10.0
            c.tick_all(t)
            if fut.done():
                break
        assert fut.result(timeout=0) == {"conflicts": {}}
        live_views = {r.view for i, r in enumerate(c.replicas) if i != 0}
        assert live_views == {1}


class TestStateTransfer:
    """Reference DefaultRecoverable snapshot get/install parity: a
    restarted replica resumes from its durable meta AND catches up on
    entries committed while it was down via f+1-verified state transfer
    — so one restart does not permanently degrade the cluster to f=0."""

    def test_restart_resumes_from_durable_meta(self):
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t1"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        c.restart(3)
        # the fresh instance resumed at its own executed prefix, not -1
        assert c.replicas[3].last_executed == 0
        # and participates in the next round without any catch-up
        f = c.client.submit({"entries": {"b": "t2"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        assert c.uniqueness[3] == c.uniqueness[0]

    def test_restarted_replica_catches_up_missed_entries(self):
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t1"}})
        c.pump()
        f.result(timeout=0)
        c.partitioned.add(3)
        for k in range(3):  # replica 3 misses seqs 1..3
            f = c.client.submit({"entries": {f"k{k}": f"t{k}"}})
            c.pump()
            assert f.result(timeout=0) == {"conflicts": {}}
        c.restart(3)
        assert c.replicas[3].last_executed == 0  # behind the cluster
        # a new round reaches it: it commits seq 4 but cannot execute
        # (seqs 1..3 missing) -> the gap timer fires a state_req
        f = c.client.submit({"entries": {"z": "tz"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        c.tick_all(100.0)   # arms the gap timer
        c.tick_all(103.0)   # past STATE_GAP_TIMEOUT: state_req + responses
        assert c.replicas[3].last_executed == 4
        assert c.uniqueness[3] == c.uniqueness[0]
        # fully recovered: it is a counted member again (f=1 restored) —
        # progress continues with a DIFFERENT member down
        c.partitioned.add(2)
        f = c.client.submit({"entries": {"w": "tw"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        assert c.uniqueness[3].get("w") == "tw"

    def test_restart_across_missed_view_change_recovers(self):
        """n=7 (f=2): a replica sleeps through BOTH a view change and
        several commits. After restart it is wedged by the view guards
        (every current-view message is dropped, so the seq-gap detector
        alone would never fire) — signature-verified prepare traffic from
        the HIGHER view is the evidence that triggers state transfer,
        whose f+1 agreement carries the view."""
        c = BFTCluster(7)
        f = c.client.submit({"entries": {"a": "t1"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        c.partitioned.add(6)   # replica 6 sleeps
        c.partitioned.add(0)   # the view-0 primary dies
        f2 = c.client.submit({"entries": {"b": "t2"}})
        c.pump()
        for t in (0.0, 31.0, 32.0, 33.0):
            c.tick_all(t)
        assert f2.result(timeout=1) == {"conflicts": {}}
        view_now = c.replicas[1].view
        assert view_now >= 1
        f3 = c.client.submit({"entries": {"c": "t3"}})
        c.pump()
        assert f3.result(timeout=0) == {"conflicts": {}}
        c.restart(6)
        assert c.replicas[6].view == 0  # behind the cluster's view
        f4 = c.client.submit({"entries": {"d": "t4"}})
        c.pump()
        assert f4.result(timeout=0) == {"conflicts": {}}
        c.tick_all(100.0)
        c.tick_all(103.0)
        c.tick_all(106.0)
        assert c.replicas[6].view == view_now
        assert c.uniqueness[6] == c.uniqueness[1]


class TestCheckpointGC:
    """PBFT §4.3 stable checkpoints + log garbage collection (r3 VERDICT
    #4; reference BFTSMaRt.kt:150-276 DefaultRecoverable snapshot install
    + log truncation)."""

    @staticmethod
    def _log_size(r):
        return (
            len(r.pre_prepares) + len(r.prepares) + len(r.commits)
            + len(r.prepare_sigs) + len(r.committed) + len(r.executed)
            + len(r.requests) + len(r.checkpoint_votes)
        )

    def test_log_truncates_at_stable_checkpoint(self, monkeypatch):
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 8)
        c = BFTCluster(4)
        for k in range(30):
            f = c.client.submit({"entries": {f"k{k}": f"t{k}"}})
            c.pump()
            assert f.result(timeout=0) == {"conflicts": {}}
        for r in c.replicas:
            # seqs 0..29 executed; checkpoints fired at 8, 16, 24
            assert r.last_executed == 29
            assert r.stable_seq == 24
            assert len(r.stable_cert) >= 3  # 2f+1 signatures retained
            # every log structure lives strictly above the checkpoint
            assert all(s > 24 for s in r.pre_prepares)
            assert all(k[1] > 24 for k in r.prepares)
            assert all(k[1] > 24 for k in r.commits)
            assert all(s > 24 for s in r.committed)
            assert all(s > 24 for s in r.executed)

    def test_memory_bounded_under_sustained_load(self, monkeypatch):
        """The r3 gap: the per-sequence message log grew without bound.
        Under 10x CHECKPOINT_INTERVAL commands the live log must stay
        O(interval), not O(history)."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 8)
        c = BFTCluster(4)
        sizes = []
        for k in range(80):
            f = c.client.submit({"entries": {f"m{k}": f"t{k}"}})
            c.pump()
            assert f.result(timeout=0) == {"conflicts": {}}
            sizes.append(max(self._log_size(r) for r in c.replicas))
        # the high-water mark over the last 40 commands must not exceed
        # the mark after the first 20 + slack: i.e. no monotonic growth
        assert max(sizes[40:]) <= max(sizes[:20]) + 10, sizes[::8]

    def test_truncated_cluster_heals_rejoiner_via_snapshot(self, monkeypatch):
        """A replica that slept past a GC cycle cannot replay discarded
        log entries — it must catch up via the f+1-agreed snapshot, which
        also becomes its own stable checkpoint."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 4)
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        c.partitioned.add(3)
        for k in range(12):  # replica 3 misses seqs 1..12, GC at 4, 8, 12
            f = c.client.submit({"entries": {f"g{k}": f"t{k}"}})
            c.pump()
            assert f.result(timeout=0) == {"conflicts": {}}
        assert c.replicas[0].stable_seq >= 8  # log below is GONE
        c.restart(3)
        f = c.client.submit({"entries": {"z": "tz"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        c.tick_all(100.0)
        c.tick_all(103.0)
        r3 = c.replicas[3]
        assert r3.last_executed == 13
        assert c.uniqueness[3] == c.uniqueness[0]
        assert r3.stable_seq >= 8  # snapshot install IS a stable checkpoint
        # and it is a full member again: progress with another member down
        c.partitioned.add(1)
        f = c.client.submit({"entries": {"w": "tw"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        assert c.uniqueness[3].get("w") == "tw"

    def test_forged_checkpoint_signature_cannot_truncate(self, monkeypatch):
        """A Byzantine replica spraying unsigned/forged checkpoint votes
        must not advance the stable checkpoint (log truncation without a
        real 2f+1 certificate could discard committable entries)."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 1000)
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        victim = c.replicas[0]
        before = victim.stable_seq
        for voter in (1, 2, 3):
            victim.on_message(voter, serialize({
                "kind": "checkpoint", "seq": 0,
                "digest": b"\x11" * 32, "csig": b"\x00" * 64,
            }))
        assert victim.stable_seq == before
        assert victim.checkpoint_votes == {}

    def test_restart_keeps_stable_seq_watermark(self, monkeypatch):
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 4)
        c = BFTCluster(4)
        for k in range(6):
            f = c.client.submit({"entries": {f"r{k}": f"t{k}"}})
            c.pump()
            f.result(timeout=0)
        assert c.replicas[2].stable_seq == 4
        c.restart(2)
        assert c.replicas[2].stable_seq == 4  # durable via meta

    def test_checkpoint_digest_spray_bounded_per_voter(self, monkeypatch):
        """One Byzantine replica validly signing many DISTINCT digests for
        one seq must hold at most ONE live vote there — not one table
        entry per message (review finding: unbounded growth)."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 1000)
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        victim, evil = c.replicas[0], c.replicas[3]
        from corda_tpu.node.bft import _checkpoint_statement
        from corda_tpu.core.crypto import ed25519_math

        for k in range(50):
            d = bytes([k]) * 32
            sig = ed25519_math.sign(
                evil._signing_seed, _checkpoint_statement(5, d)
            )
            victim.on_message(3, serialize({
                "kind": "checkpoint", "seq": 5, "digest": d, "csig": sig,
            }))
        entries = [k for k in victim.checkpoint_votes if k[0] == 5]
        assert len(entries) == 1  # only the newest vote survives

    def test_checkpoint_ahead_of_execution_triggers_state_fetch(self, monkeypatch):
        """A replica that adopts a 2f+1 checkpoint BEYOND its own
        execution must fetch state immediately — the GC just discarded
        the commit evidence the gap detector needed, and no further
        client traffic may ever arrive (review finding: idle wedge)."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 4)
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        # replica 3 stops seeing pre-prepare BODIES but still gets
        # checkpoint votes: emulate by partitioning it, running past a
        # checkpoint boundary, then delivering ONLY the checkpoint votes
        c.partitioned.add(3)
        for k in range(6):
            f = c.client.submit({"entries": {f"k{k}": f"t{k}"}})
            c.pump()
            f.result(timeout=0)
        assert c.replicas[0].stable_seq == 4
        c.partitioned.discard(3)
        r3 = c.replicas[3]
        assert r3.last_executed == 0
        # deliver the stable certificate votes straight to replica 3
        for voter, sig in c.replicas[0].stable_cert.items():
            if voter != 3:
                r3.on_message(voter, serialize({
                    "kind": "checkpoint", "seq": 4,
                    "digest": c.replicas[0].stable_digest, "csig": sig,
                }))
        assert r3.stable_seq == 4          # adopted, ahead of execution
        assert r3.last_executed < 4
        c.pump()  # the IMMEDIATE state_req round trips; no tick needed
        assert r3.last_executed >= 4
        assert c.uniqueness[3] == c.uniqueness[0]


class TestByzantineBehaviors:
    """Byzantine cases beyond signature withholding (r3 VERDICT weak #5):
    primary equivocation, corrupt digests, forged pre-prepares."""

    def test_equivocating_primary_cannot_split_commits(self):
        """A primary sending DIFFERENT digests for the same seq to
        different replicas must not get both committed: the 2f+1 prepare
        quorum can only form for (at most) one of them."""
        c = BFTCluster(4)
        from corda_tpu.node.bft import _digest

        req_a = {"client_id": "c", "request_id": "c:1",
                 "command": {"entries": {"k": "ta"}}}
        req_b = {"client_id": "c", "request_id": "c:2",
                 "command": {"entries": {"k": "tb"}}}
        da, db = _digest(req_a), _digest(req_b)
        evil = c.replicas[0]  # view-0 primary equivocates
        sig_a = evil._sign_prepare(0, 0, da)
        sig_b = evil._sign_prepare(0, 0, db)
        # replicas 1,2 see digest A; replica 3 sees digest B
        for dst, d, req, sig in ((1, da, req_a, sig_a), (2, da, req_a, sig_a),
                                 (3, db, req_b, sig_b)):
            c.replicas[dst].on_message(0, serialize({
                "kind": "pre_prepare", "view": 0, "seq": 0, "digest": d,
                "request": req, "psig": sig,
            }))
        c.pump()
        # digest A can reach quorum (1, 2 + primary's own record would be
        # needed; here at most replicas 1,2 prepared it) — digest B never
        # can. No replica may have EXECUTED b; and no two replicas may
        # have executed different commands for seq 0.
        executed = [
            (i, c.applied[i][0]["entries"]["k"])
            for i in range(4) if c.applied[i]
        ]
        assert len({v for _, v in executed}) <= 1, executed
        assert all(v != "tb" for _, v in executed) or all(
            v == "tb" for _, v in executed)

    def test_corrupt_digest_preprepare_rejected(self):
        """A pre-prepare whose digest does not hash its request body must
        be DROPPED at receipt: the digest is the commit key, so accepting
        a mismatched body would let a Byzantine primary drive one quorum
        to divergent executions (same digest, different bodies). This
        test found the missing check in round 4."""
        c = BFTCluster(4)
        from corda_tpu.node.bft import _digest

        req = {"client_id": "c", "request_id": "c:1",
               "command": {"entries": {"x": "t1"}}}
        bogus_digest = b"\x42" * 32
        assert bogus_digest != _digest(req)
        evil = c.replicas[0]
        sig = evil._sign_prepare(0, 0, bogus_digest)
        for dst in (1, 2, 3):
            c.replicas[dst].on_message(0, serialize({
                "kind": "pre_prepare", "view": 0, "seq": 0,
                "digest": bogus_digest, "request": req, "psig": sig,
            }))
        c.pump()
        for i in (1, 2, 3):
            assert c.replicas[i].pre_prepares.get(0) is None
            assert not c.applied[i]

    def test_same_digest_different_bodies_cannot_diverge(self):
        """The concrete attack the digest check closes: same digest d,
        body A to replicas 1-2, body B to replica 3. Without the check,
        commits keyed on d reach one quorum while replicas hold
        different commands for seq 0."""
        c = BFTCluster(4)
        from corda_tpu.node.bft import _digest

        req_a = {"client_id": "c", "request_id": "c:1",
                 "command": {"entries": {"k": "ta"}}}
        req_b = {"client_id": "c", "request_id": "c:2",
                 "command": {"entries": {"k": "tb"}}}
        d = _digest(req_a)
        sig = c.replicas[0]._sign_prepare(0, 0, d)
        for dst, req in ((1, req_a), (2, req_a), (3, req_b)):
            c.replicas[dst].on_message(0, serialize({
                "kind": "pre_prepare", "view": 0, "seq": 0, "digest": d,
                "request": req, "psig": sig,
            }))
        c.pump()
        # replica 3 must have dropped the mismatched body entirely
        assert c.replicas[3].pre_prepares.get(0) is None
        # and nobody executed "tb"
        for i in range(4):
            for cmd in c.applied[i]:
                assert cmd["entries"].get("k") != "tb"

    def test_forged_preprepare_from_non_primary_ignored(self):
        c = BFTCluster(4)
        req = {"client_id": "c", "request_id": "c:1",
               "command": {"entries": {"x": "t1"}}}
        from corda_tpu.node.bft import _digest

        d = _digest(req)
        evil = c.replicas[3]  # NOT the view-0 primary
        sig = evil._sign_prepare(0, 0, d)
        c.replicas[1].on_message(3, serialize({
            "kind": "pre_prepare", "view": 0, "seq": 0, "digest": d,
            "request": req, "psig": sig,
        }))
        c.pump()
        assert c.replicas[1].pre_prepares.get(0) is None
        assert not c.applied[1]

class TestCheckpointHardening:
    """Round-4 advisor findings: malformed checkpoint digests must not
    escape on_message; a silently corrupted replica must DETECT the
    divergence at the next stable checkpoint and re-sync instead of
    executing on wrong state; the stable checkpoint's digest+cert must
    survive a restart alongside its seq."""

    def test_nonbytes_checkpoint_digest_rejected(self, monkeypatch):
        """A Byzantine peer sending a non-bytes digest previously raised
        inside serialize() (before the sig check) or as an unhashable
        dict key, escaping on_message into the message pump."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 1000)
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        victim = c.replicas[0]
        for bad in ({"x": 1}, "not-bytes", 7, None, b"short", b"\x11" * 33):
            victim.on_message(1, serialize({
                "kind": "checkpoint", "seq": 3, "digest": bad,
                "csig": b"\x00" * 64,
            }))  # must not raise
        # a missing seq key must be dropped too, not raise KeyError
        victim.on_message(1, serialize({
            "kind": "checkpoint", "digest": b"\x11" * 32,
            "csig": b"\x00" * 64,
        }))
        assert victim.checkpoint_votes == {}

    def test_diverged_replica_detects_and_resyncs(self, monkeypatch):
        """Corrupt replica 3's uniqueness map mid-run. At the next stable
        checkpoint its own digest disagrees with the 2f+1-certified one:
        it must halt execution, fetch f+1-agreed state, and converge."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 4)
        c = BFTCluster(4)
        for k in range(3):
            f = c.client.submit({"entries": {f"k{k}": f"t{k}"}})
            c.pump()
            assert f.result(timeout=0) == {"conflicts": {}}
        # silent corruption (disk rot / bad restore) on replica 3
        c.uniqueness[3]["k0"] = "CORRUPT"
        for k in range(3, 8):
            f = c.client.submit({"entries": {f"k{k}": f"t{k}"}})
            c.pump()
            assert f.result(timeout=0) == {"conflicts": {}}
        # the seq-4 checkpoint certified the honest digest; replica 3's
        # own digest differed -> divergence detected -> state transfer
        assert c.uniqueness[3] == c.uniqueness[0]
        assert c.uniqueness[3].get("k0") == "t0"
        r3 = c.replicas[3]
        assert not r3._diverged
        # and it keeps executing new traffic on the healed state
        f = c.client.submit({"entries": {"post": "tp"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        assert c.uniqueness[3].get("post") == "tp"

    def test_diverged_replica_halts_execution_until_resync(self, monkeypatch):
        """Between detection and snapshot install the replica must not
        apply further commands on the corrupt state."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 2)
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        r3 = c.replicas[3]
        r3._diverged = True  # as _record_checkpoint sets on mismatch
        applied_before = len(c.applied[3])
        # traffic flows for the cluster but replica 3 must not execute
        f = c.client.submit({"entries": {"b": "t1"}})
        # drain only replica messages, skipping state transfer responses
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}  # quorum of 0,1,2
        assert len(c.applied[3]) == applied_before

    def test_restart_restores_stable_digest_and_cert(self, monkeypatch):
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 4)
        c = BFTCluster(4)
        for k in range(6):
            f = c.client.submit({"entries": {f"r{k}": f"t{k}"}})
            c.pump()
            f.result(timeout=0)
        r2 = c.replicas[2]
        assert r2.stable_seq == 4
        digest, cert = r2.stable_digest, dict(r2.stable_cert)
        assert len(digest) == 32 and len(cert) >= 3
        c.restart(2)
        assert c.replicas[2].stable_seq == 4
        assert c.replicas[2].stable_digest == digest
        assert c.replicas[2].stable_cert == cert

    def test_diverged_halt_survives_restart(self, monkeypatch):
        """Review finding (r5): the divergence halt must be durable — a
        crash+restart between detection and re-sync must come back
        halted, not executing on the corrupt state."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 1000)
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        r3 = c.replicas[3]
        r3._diverged = True
        r3._save_meta()
        c.restart(3)  # fresh instance over the same durable meta
        r3 = c.replicas[3]
        assert r3._diverged
        applied_before = len(c.applied[3])
        f = c.client.submit({"entries": {"b": "t1"}})
        c.pump()
        assert f.result(timeout=0) == {"conflicts": {}}
        assert len(c.applied[3]) == applied_before

    def test_malformed_state_messages_do_not_raise(self, monkeypatch):
        """Byzantine state_req/state_resp with wrong-typed fields must be
        dropped, not raise out of on_message (the diverged-recovery flow
        actively solicits state_resp from every peer)."""
        c = BFTCluster(4)
        f = c.client.submit({"entries": {"a": "t0"}})
        c.pump()
        f.result(timeout=0)
        victim = c.replicas[0]
        for bad in (
            {"kind": "state_resp", "last_executed": "five", "view": 0,
             "digest": b"\x00" * 32, "dump": b"x"},
            {"kind": "state_resp", "last_executed": 5, "view": 0,
             "digest": b"short", "dump": b"x"},
            {"kind": "state_resp", "last_executed": 5, "view": 0,
             "digest": b"\x00" * 32, "dump": "not-bytes"},
            {"kind": "state_resp", "last_executed": 5, "view": "zero",
             "digest": b"\x00" * 32, "dump": b"x"},
            {"kind": "state_resp"},
            {"kind": "state_req", "have": "nope"},
            {"kind": "state_req"},
        ):
            victim.on_message(3, serialize(bad))  # must not raise
        assert victim.last_executed == 0  # nothing was installed

    def test_snapshot_at_stable_seq_keeps_cert(self, monkeypatch):
        """A snapshot install that merely re-confirms the existing stable
        point must not wipe the genuine 2f+1 cert (review finding r5)."""
        monkeypatch.setattr(BFTReplica, "CHECKPOINT_INTERVAL", 4)
        c = BFTCluster(4)
        for k in range(6):
            f = c.client.submit({"entries": {f"c{k}": f"t{k}"}})
            c.pump()
            f.result(timeout=0)
        r0 = c.replicas[0]
        assert r0.stable_seq == 4 and len(r0.stable_cert) >= 3
        cert = dict(r0.stable_cert)
        digest_before = r0.stable_digest
        # fake a diverged recovery that lands exactly on the stable
        # point: a dump whose digest REPRODUCES the stable digest (the
        # state as of seq 4 — keys c0..c4, serialized canonically)
        dump = serialize({f"c{k}": f"t{k}" for k in range(5)})
        import hashlib as _h
        assert _h.sha256(dump).digest() == digest_before  # test premise
        r0._diverged = True
        r0.last_executed = 4
        for sender in (1, 2):
            r0.on_message(sender, serialize({
                "kind": "state_resp", "last_executed": 4, "view": 0,
                "digest": digest_before, "dump": dump,
            }))
        assert not r0._diverged  # recovery completed
        assert r0.stable_seq == 4
        assert r0.stable_digest == digest_before
        assert r0.stable_cert == cert  # the 2f+1 evidence survived

"""Replayed bench headlines must carry the ORIGINAL measurement's
semantics (r4 VERDICT weak #2): BENCH_r04 stamped `end_to_end: true`
onto r01's kernel-only figure. These tests pin the replay contract
without running the (slow) bench itself."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_best_tpu_capture_returns_source_record():
    cap = bench._best_tpu_capture()
    if cap is None:
        pytest.skip("no TPU datapoint anywhere in the repo")
    res, prov = cap
    assert res.get("backend") == "tpu"
    assert "value" in res
    assert "source" in prov


def test_replay_does_not_upgrade_semantics():
    """Drive bench.py's replay branch logic directly: a source record
    WITHOUT an explicit end_to_end must replay as end_to_end False, and
    the provenance block must reproduce the source record verbatim."""
    # the exact shape BENCH_r01.json's parsed record has (kernel-only run)
    res = {
        "metric": "ed25519-sig-verifies/sec/chip",
        "value": 26899.0,
        "unit": "sigs/s",
        "vs_baseline": 0.1076,
        "batch": 16384,
        "backend": "tpu",
    }
    # reproduce the replay-branch field derivation (bench.main else-arm)
    end_to_end = bool(res.get("end_to_end", False))
    provenance = {"live": False, "source": "BENCH_r01.json",
                  "source_record": res}
    assert end_to_end is False
    assert provenance["source_record"] == res


def test_bench_replay_branch_source_matches_headline():
    """The real invariant, checked against bench.py's source: the replay
    arm must not contain an optimistic end_to_end default and must embed
    source_record. A regression reintroducing `res.get("end_to_end",
    True)` fails here."""
    src = open(os.path.join(REPO, "bench.py")).read()
    assert 'res.get("end_to_end", True)' not in src
    assert '"source_record": res' in src


def test_bench_emits_stage_timings_fields():
    """The bench record must carry the per-stage seam timings so future
    rounds can attribute system-path regressions to a stage instead of
    guessing (VERDICT open item 2). Source-pinned like the replay
    contract: a regression dropping the fields fails here without
    running the slow bench."""
    src = open(os.path.join(REPO, "bench.py")).read()
    for field in (
        '"stage_timings"',
        '"codec_encode_us_per_tx"',
        '"uniq_commit_batch_mean"',
        '"batcher_flush_wall_s"',
    ):
        assert field in src, f"bench.py no longer records {field}"


def test_codec_encode_seam_measures():
    us = bench._codec_encode_us(n=50)
    assert 0 < us < 100_000  # sane microseconds per encode


def test_uniqueness_burst_reports_batch_telemetry():
    """The batched uniqueness path must report coalescing telemetry, and
    concurrent submitters must actually coalesce (mean batch > 1)."""
    from corda_tpu.loadtest.latency import measure_uniqueness_batch

    out = measure_uniqueness_batch(n_tx=200, threads=8)
    for key in (
        "raft_commits_s", "raft_commit_batches", "raft_commit_batch_mean",
        "raft_commit_batch_max", "single_commits_s", "commit_threads",
    ):
        assert key in out
    assert out["raft_commit_batch_mean"] > 1.0
    assert out["raft_commit_batches"] < 200


@pytest.mark.heavy
def test_bench_cpu_replay_end_to_end_matches_source():
    """Full-process check (heavy tier): run bench.py forced to the CPU
    arm with secondaries skipped; if it replays a capture, the top-level
    semantics must match the embedded source record."""
    env = dict(os.environ)
    env["CORDA_TPU_BENCH_FORCE_CPU"] = "1"
    env["CORDA_TPU_BENCH_HEADLINE_ONLY"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=900, env=env,
    )
    line = next(
        (ln for ln in out.stdout.splitlines() if ln.startswith("{")), None)
    assert line, out.stdout[-500:] + out.stderr[-500:]
    rec = json.loads(line)
    prov = rec.get("provenance", {})
    if prov.get("live", True):
        pytest.skip("live run, not a replay")
    src = prov["source_record"]
    assert rec["value"] == src["value"]
    assert rec["end_to_end"] == bool(src.get("end_to_end", False))

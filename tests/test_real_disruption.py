"""Fault injection against REAL node processes (reference
`tools/loadtest/.../Disruption.kt:17-90` + `StabilityTest.kt`: hang via
SIGSTOP, kill, restart, deleteDb fired at an SSH-managed cluster while
load runs; here the cluster is a cordform-deployed local network of OS
processes and the disruptions are signals on those PIDs).

Invariants checked after every heal:
  * no loss — every payment the client saw complete is on the
    counterparty's ledger;
  * no duplication — the counterparty holds exactly one state per
    payment transaction (and the notary never double-commits a spend);
  * liveness — fresh pairs complete end-to-end after the heal.
"""
import tempfile
import time

import pytest


def _boot(base):
    from corda_tpu.testing.smoketesting import Factory
    from corda_tpu.tools.cordform import deploy_nodes

    spec = {
        "nodes": [
            {"name": "O=DisNotary,L=Zurich,C=CH", "notary": "validating",
             "network_map_service": True},
            {"name": "O=DisBankA,L=London,C=GB"},
            {"name": "O=DisBankB,L=Paris,C=FR"},
        ]
    }
    resolved = deploy_nodes(spec, base)
    factory = Factory(base)
    nodes = [factory.launch(conf["dir"]) for conf in resolved]
    return factory, resolved, nodes


from corda_tpu.loadtest.procdriver import (  # noqa: E402
    PairDriver as _Driver,
    assert_no_loss_no_dup as _assert_no_loss_no_dup,
    payment_txids as _b_payment_txids,
    resolve_identities,
)


def _setup_identities(nodes):
    return resolve_identities(nodes[1], nodes[2])


@pytest.mark.slow
class TestRealProcessDisruptions:
    def _run_scenario(self, disrupt, min_before=4, settle=0.5):
        """Boot the network, drive pairs, call disrupt(nodes, factory) mid
        flight (it returns the possibly-relaunched node list), heal, stop
        driving, assert the invariants."""
        base = tempfile.mkdtemp(prefix="disrupt-real-")
        factory, resolved, nodes = _boot(base)
        try:
            me, notary, peer = _setup_identities(nodes)
            driver = _Driver(nodes[1], notary, me, peer).start()
            deadline = time.monotonic() + 60
            while len(driver.completed) < min_before:
                assert time.monotonic() < deadline, (
                    f"warm-up stalled: {driver.errors[-3:]}"
                )
                time.sleep(0.2)
            nodes = disrupt(nodes, factory, resolved)
            time.sleep(settle)  # keep driving across the healed topology
            driver.stop()
            _assert_no_loss_no_dup(driver, nodes[2])
            return driver, nodes
        finally:
            for n in nodes:
                n.close()

    def test_counterparty_hang_sigstop(self):
        """Bank B hangs (SIGSTOP) mid-run and resumes: the bridge's
        store-and-forward queue absorbs the outage (Disruption.kt 'hang')."""

        def disrupt(nodes, factory, resolved):
            nodes[2].suspend()
            time.sleep(1.5)
            nodes[2].resume()
            return nodes

        driver, _ = self._run_scenario(disrupt)
        assert not driver.errors, driver.errors[:3]

    def test_counterparty_kill_and_restart(self):
        """Bank B is SIGKILLed mid-run and relaunched from its directory:
        durable journals + checkpoint restore mean nothing completed is
        lost (Disruption.kt 'kill' + 'restart')."""

        def disrupt(nodes, factory, resolved):
            nodes[2].kill()
            time.sleep(0.5)
            nodes[2] = factory.launch(resolved[2]["dir"])
            return nodes

        self._run_scenario(disrupt, settle=1.5)

    def test_notary_kill_and_restart(self):
        """The VALIDATING NOTARY is SIGKILLed mid-run and relaunched: its
        sqlite uniqueness log survives, in-flight notarisations fail or
        stall and retry, and no spend is ever committed twice."""

        def disrupt(nodes, factory, resolved):
            nodes[0].kill()
            time.sleep(0.5)
            nodes[0] = factory.launch(resolved[0]["dir"])
            return nodes

        driver, nodes = self._run_scenario(disrupt, settle=2.0)
        # liveness after heal: fresh pairs completed post-restart
        # (settle window drove more pairs through the restarted notary)
        assert len(driver.completed) >= 4

    def test_delete_message_store_then_restart(self):
        """Bank B is killed, its broker journal wiped (the 'deleteDb'
        disruption), and relaunched: in-flight broadcasts queued in B's
        journal may be gone, but the network stays LIVE — fresh pairs
        complete end-to-end through the rebuilt store."""
        base = tempfile.mkdtemp(prefix="disrupt-deldb-")
        factory, resolved, nodes = _boot(base)
        try:
            me, notary, peer = _setup_identities(nodes)
            driver = _Driver(nodes[1], notary, me, peer).start()
            deadline = time.monotonic() + 60
            while len(driver.completed) < 4:
                assert time.monotonic() < deadline, driver.errors[-3:]
                time.sleep(0.2)
            driver.stop()

            nodes[2].kill()
            nodes[2].delete_message_store()
            nodes[2] = factory.launch(resolved[2]["dir"])

            driver2 = _Driver(nodes[1], notary, me, peer).start()
            deadline = time.monotonic() + 60
            while len(driver2.completed) < 3:
                assert time.monotonic() < deadline, driver2.errors[-3:]
                time.sleep(0.2)
            driver2.stop()
            _assert_no_loss_no_dup(driver2, nodes[2])
        finally:
            for n in nodes:
                n.close()


@pytest.mark.slow
class TestBFTNotaryClusterProcesses:
    """A 4-member PBFT notary cluster as real OS processes (reference
    BFTNotaryServiceTests: BFT-SMaRt replicas as real nodes). PBFT
    traffic rides the nodes' P2P bridges; commits return f+1 replica
    signatures fulfilling the f+1-threshold composite identity; killing
    one non-primary member (f=1) mid-run must not stop notarisation."""

    @staticmethod
    def _boot_cluster(prefix, cluster_name, extra=None, warm_to=3):
        """Deploy 4 BFT members + 2 banks, resolve identities, start a
        driver and let it complete `warm_to` pairs. Returns
        (factory, resolved, nodes, cluster, me, peer, driver)."""
        from corda_tpu.testing.smoketesting import Factory
        from corda_tpu.tools.cordform import deploy_nodes

        base = tempfile.mkdtemp(prefix=prefix)
        notary_entry = {
            "name": cluster_name, "notary": "bft", "cluster_size": 4,
            "network_map_service": True,
        }
        notary_entry.update(extra or {})
        spec = {"nodes": [
            notary_entry,
            {"name": "O=%sBankA,L=London,C=GB" % prefix.rstrip("-")},
            {"name": "O=%sBankB,L=Paris,C=FR" % prefix.rstrip("-")},
        ]}
        resolved = deploy_nodes(spec, base)
        assert len(resolved) == 6  # 4 members + 2 banks
        factory = Factory(base)
        nodes = []
        driver = None
        try:
            for conf in resolved:  # explicit loop: partial boots must be
                nodes.append(factory.launch(conf["dir"]))  # closable below
            conn = nodes[4].connect()
            try:
                me = conn.proxy.node_info()
                notaries = conn.proxy.notary_identities()
                # exactly ONE notary: the cluster identity, not 4 members
                assert len(notaries) == 1, [n.name for n in notaries]
                cluster = notaries[0]
                assert cluster.name == cluster_name
            finally:
                conn.close()
            conn_b = nodes[5].connect()
            try:
                peer = conn_b.proxy.node_info()
            finally:
                conn_b.close()
            driver = _Driver(nodes[4], cluster, me, peer).start()
            deadline = time.monotonic() + 300
            while len(driver.completed) < warm_to:
                assert time.monotonic() < deadline, (
                    f"cluster never notarised: {driver.errors[-3:]}"
                )
                time.sleep(0.3)
        except BaseException:
            # a failed boot/warm-up must not orphan up to 6 OS processes
            # or leave the driver thread spinning against dead nodes
            if driver is not None:
                try:
                    driver.stop(timeout=5)
                except BaseException:
                    pass
            for n in nodes:
                n.close()
            raise
        return factory, resolved, nodes, cluster, me, peer, driver

    def test_cluster_notarises_and_survives_member_kill(self):
        (factory, resolved, nodes, _cluster, _me, _peer,
         driver) = self._boot_cluster("bft-real-", "O=BFTNotary,L=Zurich,C=CH")
        try:

            # kill member 1: not the view-0 primary (member 0) and not
            # the member holding the cluster route (last registered), so
            # the remaining 3 >= 2f+1 keep committing without view change
            nodes[1].kill()
            before = len(driver.completed)
            deadline = time.monotonic() + 300
            while len(driver.completed) < before + 3:
                assert time.monotonic() < deadline, (
                    f"no progress after member kill: {driver.errors[-3:]}"
                )
                time.sleep(0.3)

            # HEAL: relaunch member 1 — it resumes from its durable meta
            # and catches up on the entries committed while it was down
            # via f+1-verified state transfer. Then kill a DIFFERENT
            # member: the 2f+1 quorum now REQUIRES the restored member,
            # so continued progress proves f=1 tolerance was restored
            # (reference DefaultRecoverable state-transfer semantics).
            nodes[1] = factory.launch(resolved[1]["dir"])
            time.sleep(4)  # gap timer + state transfer
            nodes[2].kill()
            before = len(driver.completed)
            deadline = time.monotonic() + 300
            while len(driver.completed) < before + 2:
                assert time.monotonic() < deadline, (
                    f"no progress with the restored member required: "
                    f"{driver.errors[-3:]}"
                )
                time.sleep(0.3)
            driver.stop()
            _assert_no_loss_no_dup(driver, nodes[5])
        finally:
            for n in nodes:
                n.close()

    def test_primary_kill_triggers_view_change(self):
        """Killing the view-0 PRIMARY (member 0) forces a PBFT view
        change: the remaining 3 >= 2f+1 replicas time out on the pending
        request, elect view 1 (member 1 primary, carrying prepared
        certificates), and notarisation resumes — the reference's
        BFT-SMaRt leader-failure semantics as real OS processes."""
        (_factory, _resolved, nodes, _cluster, _me, _peer,
         driver) = self._boot_cluster(
            "bft-vc-", "O=BFTVC,L=Zurich,C=CH",
            # short view-change timer: fail over inside the client wait
            extra={"view_timeout": 6.0}, warm_to=2,
        )
        try:
            nodes[0].kill()  # the view-0 primary orders all commits
            before = len(driver.completed)
            deadline = time.monotonic() + 300
            while len(driver.completed) < before + 2:
                assert time.monotonic() < deadline, (
                    f"no progress after PRIMARY kill (view change "
                    f"failed): {driver.errors[-3:]}"
                )
                time.sleep(0.3)
            driver.stop()
            _assert_no_loss_no_dup(driver, nodes[5])
        finally:
            for n in nodes:
                n.close()


@pytest.mark.slow
class TestRaftNotaryClusterProcesses:
    """A 3-member Raft VALIDATING notary cluster as real OS processes
    (reference: the raft notary-demo cluster; Disruption.kt fired at a
    distributed notary). Raft traffic rides the nodes' P2P bridges; the
    cluster presents one composite identity; killing a minority member
    mid-run must not stop notarisation or lose anything."""

    def test_route_holder_kill_fails_over(self):
        """Killing the member whose address currently serves the CLUSTER
        route (the last registrant) must not strand notarisation until
        the 12h TTL refresh: every member re-registers the shared
        identity on the fast cadence (cluster_route_refresh), so the
        route flips to a live member within one interval and the banks'
        bridges reconnect there with their queued requests."""
        from corda_tpu.testing.smoketesting import Factory
        from corda_tpu.tools.cordform import deploy_nodes

        base = tempfile.mkdtemp(prefix="raft-route-")
        spec = {
            "nodes": [
                {"name": "O=RouteNotary,L=Zurich,C=CH",
                 "notary": "raft-validating", "cluster_size": 3,
                 "cluster_route_refresh": 3.0,
                 "network_map_service": True},
                {"name": "O=RouteBankA,L=London,C=GB"},
                {"name": "O=RouteBankB,L=Paris,C=FR"},
            ]
        }
        resolved = deploy_nodes(spec, base)
        factory = Factory(base)
        nodes = []
        driver = None
        try:
            for conf in resolved:  # explicit loop: partial boots close below
                nodes.append(factory.launch(conf["dir"]))
            conn = nodes[3].connect()
            try:
                me = conn.proxy.node_info()
                cluster = conn.proxy.notary_identities()[0]
            finally:
                conn.close()
            conn_b = nodes[4].connect()
            try:
                peer = conn_b.proxy.node_info()
            finally:
                conn_b.close()

            driver = _Driver(nodes[3], cluster, me, peer).start()
            deadline = time.monotonic() + 240
            while len(driver.completed) < 2:
                assert time.monotonic() < deadline, driver.errors[-3:]
                time.sleep(0.3)

            # member 2 registered LAST at boot, so it holds the initial
            # cluster route (subsequent fast refreshes may move it — any
            # single member kill must heal within ~one interval either way)
            nodes[2].kill()
            before = len(driver.completed)
            deadline = time.monotonic() + 300
            while len(driver.completed) < before + 2:
                assert time.monotonic() < deadline, (
                    f"route never failed over: {driver.errors[-3:]}"
                )
                time.sleep(0.3)
            driver.stop()
            _assert_no_loss_no_dup(driver, nodes[4])
        finally:
            if driver is not None and not driver._stop.is_set():
                try:
                    driver.stop(timeout=5)
                except BaseException:
                    pass
            for n in nodes:
                n.close()

    def test_cluster_notarises_and_survives_member_kill(self):
        from corda_tpu.testing.smoketesting import Factory
        from corda_tpu.tools.cordform import deploy_nodes

        base = tempfile.mkdtemp(prefix="raft-real-")
        spec = {
            "nodes": [
                {"name": "O=RaftNotary,L=Zurich,C=CH",
                 "notary": "raft-validating", "cluster_size": 3,
                 "network_map_service": True},
                {"name": "O=RaftBankA,L=London,C=GB"},
                {"name": "O=RaftBankB,L=Paris,C=FR"},
            ]
        }
        resolved = deploy_nodes(spec, base)
        assert len(resolved) == 5  # 3 members + 2 banks
        factory = Factory(base)
        nodes = [factory.launch(conf["dir"]) for conf in resolved]
        try:
            conn = nodes[3].connect()
            try:
                me = conn.proxy.node_info()
                notaries = conn.proxy.notary_identities()
                # exactly ONE notary: the cluster identity, not 3 members
                assert len(notaries) == 1, [n.name for n in notaries]
                cluster = notaries[0]
                assert cluster.name == "O=RaftNotary,L=Zurich,C=CH"
            finally:
                conn.close()
            conn_b = nodes[4].connect()
            try:
                peer = conn_b.proxy.node_info()
            finally:
                conn_b.close()

            driver = _Driver(nodes[3], cluster, me, peer).start()
            deadline = time.monotonic() + 240
            while len(driver.completed) < 3:
                assert time.monotonic() < deadline, (
                    f"cluster never notarised: {driver.errors[-3:]}"
                )
                time.sleep(0.3)

            # kill a MINORITY member (not the last-registered one that
            # holds the cluster route): quorum 2/3 survives, the serving
            # member forwards commits to the re-elected leader
            nodes[0].kill()
            before = len(driver.completed)
            deadline = time.monotonic() + 240
            while len(driver.completed) < before + 3:
                assert time.monotonic() < deadline, (
                    f"no progress after member kill: {driver.errors[-3:]}"
                )
                time.sleep(0.3)
            driver.stop()
            _assert_no_loss_no_dup(driver, nodes[4])

            # heal: the killed member restores its replicated uniqueness
            # log (snapshot/backfill) and rejoins
            nodes[0] = factory.launch(resolved[0]["dir"])
            driver2 = _Driver(nodes[3], cluster, me, peer).start()
            deadline = time.monotonic() + 240
            while len(driver2.completed) < 2:
                assert time.monotonic() < deadline, driver2.errors[-3:]
                time.sleep(0.3)
            driver2.stop()
            _assert_no_loss_no_dup(driver2, nodes[4])
        finally:
            for n in nodes:
                n.close()


@pytest.mark.slow
def test_chaos_harness_with_proxy_partition():
    """The chaos rotation's wire-partition kind: bank B deployed behind
    the controllable TCP proxy (advertised_address wiring), the stall
    fired mid-soak, the catalog heal asserting pairs RESUME, and the
    end-of-soak no-loss/no-dup contract holding through it."""
    from corda_tpu.loadtest.chaos import run

    out = run(duration=35.0, seed=13, proxy_partition=True)
    assert out["consistent"] and out["pairs"] > 0
    assert out["disruptions"] >= 1


@pytest.mark.slow
def test_remote_soak_localhost_rig():
    """The full `python -m corda_tpu.loadtest.remote` soak on the
    committed localhost rig: 3 composed disruption kinds (restart,
    SIGSTOP hang, proxy partition) each RECOVERED, the typed-shed
    overload burst, the explorer action mix, end-of-soak
    no-loss/no-dup + cross-host reconciliation, slo_violations == []."""
    from corda_tpu.loadtest.remote import parse_hosts, run

    out = run(
        parse_hosts("local"), duration=15.0, seed=7,
        overload_burst=240,
    )
    assert out["consistent"] is True
    assert out["disruptions_fired"] >= 3
    assert out["disruptions_recovered"] == out["disruptions_fired"]
    kinds = {k for _, k, state in out["events"] if "recovered" in state}
    assert {"restart", "hang", "partition"} <= kinds
    assert out["overload"]["shed"] >= 1
    assert out["overload"]["recovered"] == 1.0
    assert out["slo_violations"] == [], out["slo_violations"]


@pytest.mark.slow
def test_chaos_harness_short_soak():
    """The packaged chaos harness (loadtest.chaos) runs end-to-end at a
    short duration: pairs complete, at least one disruption fires, and
    the no-loss/no-dup invariant holds. (The reference run — 21k pairs /
    600 s / 25 disruptions / 0 errors — is documented in its docstring;
    CI keeps this at ~40 s.)"""
    from corda_tpu.loadtest.chaos import run

    out = run(duration=30.0, seed=11)
    assert out["consistent"] and out["pairs"] > 0
    assert out["disruptions"] >= 1
